"""MultiLayerNetwork — the linear-stack network with fit/output/score/evaluate.

Reference parity: org/deeplearning4j/nn/multilayer/MultiLayerNetwork.java
(~4k LoC: fitHelper → Solver → StochasticGradientDescent →
computeGradientAndScore → per-layer activate/backpropGradient → updater →
step; SURVEY.md §3.1) — path-cite, mount empty this round.

TPU-native collapse: the entire minibatch iteration — forward, loss, reverse
AD, updater, parameter step — is ONE jitted function, compiled once per input
shape and executed as a single XLA program on device. The reference crosses
JNI per op and keeps params/gradients as flattened off-heap views; here
params/optimizer state live on device as pytrees and are donated
(buffer-aliased) across steps, the PJRT-era equivalent of workspaces.

Listeners fire on the host with the scalar loss (fetching only the scalar —
one small transfer per iteration, matching the reference's
TrainingListener.iterationDone cadence).
"""

from __future__ import annotations

import functools
import inspect
import time
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.util import cost_model as cmod
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import note_trace


def _struct_of(tree):
    """Pytree → matching ShapeDtypeStruct tree (AOT warmup operands)."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _dispatch_sig(*args):
    """Shape/dtype signature of the data operands of one step/forward call —
    the key for the AOT-compiled executable table (warmup). Handles arrays,
    ShapeDtypeStructs, None, and (for ComputationGraph) dicts/lists of them."""
    from deeplearning4j_tpu.util.compile_watcher import _shape_of

    return tuple(_shape_of(a) for a in args)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.params: List[dict] = []
        self.states: List[dict] = []
        self.opt_states: List[Any] = []
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self.score_value: float = float("nan")
        self.last_iteration_wall_ns = None  # set during coalesced dispatch
        self._train_step = None
        self._it_dev = None   # device-resident iteration counter
        self._it_sync = -1    # host iteration the device counter mirrors
        from deeplearning4j_tpu.nn.listeners import CoalescingListenerDispatcher

        self._dispatcher = CoalescingListenerDispatcher(
            self, getattr(conf, "sync_every", 1))
        self._updaters = [
            (lyr.updater or conf.updater or upd.Sgd(0.1)) for lyr in conf.layers
        ]
        # fused donated optimizer apply (docs/KERNELS.md#fused-optimizer-
        # apply): built in init() once params exist; None = per-leaf walk
        self._fused = None
        if (getattr(conf, "loss_scale", "none") != "none"
                and not getattr(conf, "fused_update", False)):
            raise ValueError(
                "loss_scale requires fused_update=True — the scale "
                "automaton lives in the fused optimizer state")
        self._rng_key = jax.random.PRNGKey(conf.seed)
        # Mask plumbing (setLayerMaskArrays/feedForwardMaskArray parity):
        # which layers' apply()/compute_loss() accept a mask kwarg.
        self._mask_aware = [
            "mask" in inspect.signature(lyr.apply).parameters for lyr in self.layers
        ]
        self._loss_mask_aware = hasattr(self.layers[-1], "compute_loss") and (
            "mask" in inspect.signature(self.layers[-1].compute_loss).parameters
        )
        self._segments = self._build_segments()
        # Shape bucketing (data/bucketing.py): ragged batches pad to a fixed
        # bucket set with 0-weighted rows; None when both knobs are off.
        self._bucketing = BucketingPolicy.from_conf(conf)
        # AOT-warmed executables (warmup()): dispatch signature → compiled.
        self._aot_steps: dict = {}
        self._aot_forward: dict = {}
        # Cost attribution (util/cost_model.py): one stable scope tag per
        # layer, threaded through every trace as named_scope("layer:<tag>")
        # so the compiled HLO (and the profiler's device events) attribute
        # per layer. Index prefix keeps tags unique under repeated names.
        self._layer_tags = [
            cmod.sanitize_tag(f"{i}_{lyr.name or type(lyr).__name__}")
            for i, lyr in enumerate(self.layers)
        ]
        self._cost_flops_per_example = None  # set by cost_report()
        self._peak_flops = None
        # Device-resident 0/1 weight vectors keyed by (size, real-count):
        # fit ALWAYS threads per-example weights (ones when unbucketed), so
        # bucketed and unbucketed batches execute the SAME weighted-loss
        # program — the bit-identity invariant (data/bucketing.py
        # dev_weights).
        self._w_cache: dict = {}
        self._last_fit_ns = None  # step-cadence stamp (telemetry histogram)

    def _dev_weights(self, size: int, real: int):
        from deeplearning4j_tpu.data.bucketing import dev_weights

        return dev_weights(self._w_cache, size, real)

    # ------------------------------------------- fusion-boundary segmentation
    def _build_segments(self):
        """Partition the layer stack into remat/fusion stages
        (util/xla_tuning.py). Returns (list of (start, end) index pairs,
        tail_start) or None when no policy/barrier is configured. The loss
        head (and anything after the last boundary) always runs unwrapped."""
        conf = self.conf
        active = (getattr(conf, "remat_policy", None) not in (None, "none")
                  or getattr(conf, "stage_barriers", False))
        if not active:
            return None
        n = len(self.layers)
        bounds = sorted(set(conf.remat_stages or ()))
        for b in bounds:
            if not 0 < b < n:
                raise ValueError(
                    f"remat stage boundary {b} out of range (1..{n - 1}); "
                    "the loss head always runs in the unwrapped tail")
        if not bounds:
            bounds = [n - 1]  # whole body before the loss head = one stage
        spans, start = [], 0
        for b in bounds:
            spans.append((start, b))
            start = b
        return spans, start

    # ------------------------------------------------------------------ init
    def init(self, input_shape=None) -> "MultiLayerNetwork":
        """Initialize params/state (MultiLayerNetwork.init parity)."""
        shape = tuple(input_shape or self.conf.input_shape or ())
        if not shape:
            raise ValueError("input_shape required (set_input_type on the builder)")
        key = jax.random.PRNGKey(self.conf.seed)
        self.params, self.states = [], []
        cur = shape
        for lyr in self.layers:
            key, sub = jax.random.split(key)
            p, s = lyr.initialize(sub, cur)
            self.params.append(p)
            self.states.append(s)
            cur = lyr.output_shape(cur)
        if getattr(self.conf, "fused_update", False):
            self._fused = upd.FusedUpdateEngine(
                self._updaters, self.params,
                loss_scale=getattr(self.conf, "loss_scale", "none"),
                loss_scale_value=getattr(self.conf, "loss_scale_value",
                                         2.0 ** 15),
                growth_interval=getattr(self.conf, "loss_scale_growth", 2000))
            self.opt_states = self._fused.init_state(self.params)
        else:
            self.opt_states = [
                u.init_state(p) for u, p in zip(self._updaters, self.params)
            ]
        self._output_shape = cur
        self._train_step = self._build_train_step()
        self._forward_jit = jax.jit(functools.partial(self._forward, training=False))
        self._forward_train_jit = jax.jit(functools.partial(self._forward, training=True))
        return self

    def num_params(self) -> int:
        return sum(int(np.prod(x.shape)) for p in self.params for x in jax.tree_util.tree_leaves(p))

    # --------------------------------------------------------------- forward
    def _kscope(self):
        """Kernel-dispatch scope for every trace of this net's layers
        (ops/kernels — docs/KERNELS.md). conf.kernel_impl None leaves the
        ambient DL4J_TPU_KERNEL_IMPL / auto resolution in place."""
        from deeplearning4j_tpu.ops import kernels as _kern

        return _kern.impl_scope(getattr(self.conf, "kernel_impl", None))

    def _cast(self, x):
        if self.conf.compute_dtype == "bfloat16" and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(jnp.bfloat16)
        return x

    def _cast_params(self, params):
        if self.conf.compute_dtype != "bfloat16":
            return params
        return jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16) if jnp.issubdtype(p.dtype, jnp.floating) else p,
            params,
        )

    def _forward(self, params, states, x, *, training, keys=None, mask=None):
        note_trace("MultiLayerNetwork.forward", x, mask)  # trace-time only
        with self._kscope():
            return self._forward_body(params, states, x, training=training,
                                      keys=keys, mask=mask)

    def _forward_body(self, params, states, x, *, training, keys=None,
                      mask=None):
        h = self._cast(x)
        cparams = self._cast_params(params)
        new_states = []
        for i, lyr in enumerate(self.layers):
            k = keys[i] if keys is not None else None
            kw = {}
            if (
                mask is not None
                and self._mask_aware[i]
                and h.ndim == 3
                and mask.shape[:2] == h.shape[:2]
            ):
                kw["mask"] = mask
            with cmod.layer_scope(self._layer_tags[i]):
                h, ns = lyr.apply(cparams[i], states[i], h,
                                  training=training, key=k, **kw)
            new_states.append(ns)
            if h.ndim < 3:
                mask = None  # time axis consumed (LastTimeStep/GlobalPooling)
        return h, new_states

    def _loss_body(self, params, states, carries, x, y, keys, weights, mask,
                   label_mask, training=True):
        """The ONE forward+loss body shared by training (_loss), evaluation
        (_loss_eval), and truncated BPTT (_tbptt_step). ``carries`` is None
        for whole-sequence paths; a per-layer carry list routes recurrent
        layers through ``apply_seq`` (TBPTT segments). ``weights``: optional
        per-example loss weights (ParallelWrapper uses zeros to mask padded
        examples exactly). ``mask``/``label_mask``: (B,T) masks."""
        with self._kscope():
            return self._loss_body_impl(params, states, carries, x, y, keys,
                                        weights, mask, label_mask, training)

    def _loss_body_impl(self, params, states, carries, x, y, keys, weights,
                        mask, label_mask, training=True):
        h = self._cast(x)
        cparams = self._cast_params(params)
        new_states, new_carries = [], []
        fmask = mask
        for i, lyr in enumerate(self.layers[:-1]):
            seg_mask = (
                fmask
                if (fmask is not None and h.ndim == 3
                    and fmask.shape[:2] == h.shape[:2])
                else None
            )
            if carries is not None and self._is_recurrent(lyr):
                with cmod.layer_scope(self._layer_tags[i]):
                    h = lyr._maybe_dropout(h, training, keys[i])
                    h, c = lyr.apply_seq(cparams[i], h, carries[i],
                                         mask=seg_mask, training=training,
                                         key=keys[i])
                new_carries.append(c)
                new_states.append(states[i])
            else:
                kw = {}
                if seg_mask is not None and self._mask_aware[i]:
                    kw["mask"] = seg_mask
                with cmod.layer_scope(self._layer_tags[i]):
                    h, ns = lyr.apply(cparams[i], states[i], h,
                                      training=training, key=keys[i], **kw)
                new_states.append(ns)
                new_carries.append(None if carries is None else carries[i])
            if h.ndim < 3:
                fmask = None
        out = self.layers[-1]
        if not hasattr(out, "compute_loss"):
            raise ValueError("last layer must be an OutputLayer/LossLayer")
        loss_kw = {}
        lm = label_mask if label_mask is not None else fmask
        if lm is not None and self._loss_mask_aware:
            loss_kw["mask"] = lm
        if weights is not None:
            loss_kw["weights"] = weights
        with cmod.layer_scope(self._layer_tags[-1]):
            loss = out.compute_loss(
                cparams[-1], states[-1], h, y, training=training,
                key=keys[-1], **loss_kw,
            )
        new_states.append(states[-1])
        new_carries.append(None if carries is None else carries[-1])
        reg = sum(
            (lyr.regularization(params[i]) for i, lyr in enumerate(self.layers)),
            start=jnp.asarray(0.0),
        )
        return loss.astype(jnp.float32) + reg, (new_states, new_carries)

    def _loss(self, params, states, x, y, keys, weights=None, mask=None,
              label_mask=None):
        if self._segments is not None and mask is None and label_mask is None:
            # fusion-boundary path (util/xla_tuning.py): masked sequence
            # nets keep the plain path — remat targets the conv stacks
            return self._loss_remat(params, states, x, y, keys, weights)
        loss, (new_states, _) = self._loss_body(
            params, states, None, x, y, keys, weights, mask, label_mask)
        return loss, new_states

    def _loss_remat(self, params, states, x, y, keys, weights=None):
        """_loss with the layer stack split into remat/fusion stages: each
        stage runs inside ``jax.checkpoint`` under the configured policy,
        ``stage_barriers`` fences fusion at the boundaries. Exact same values
        and gradients as the plain path (remat only changes what XLA keeps
        live across fwd/bwd)."""
        with self._kscope():
            return self._loss_remat_impl(params, states, x, y, keys, weights)

    def _loss_remat_impl(self, params, states, x, y, keys, weights=None):
        from deeplearning4j_tpu.util import xla_tuning

        spans, tail_start = self._segments
        wrap, policy = xla_tuning.resolve_policy(self.conf.remat_policy)
        h = self._cast(x)
        cparams = self._cast_params(params)
        new_states = [None] * len(self.layers)

        def stage_runner(a, b):
            def run(seg_params, seg_states, seg_keys, h):
                st = []
                for j, i in enumerate(range(a, b)):
                    with cmod.layer_scope(self._layer_tags[i]):
                        h, ns = self.layers[i].apply(
                            seg_params[j], seg_states[j], h, training=True,
                            key=seg_keys[j])
                    st.append(ns)
                return h, st
            return run

        for a, b in spans:
            run = stage_runner(a, b)
            if wrap:
                run = jax.checkpoint(run, policy=policy)
            h, st = run([cparams[i] for i in range(a, b)],
                        [states[i] for i in range(a, b)],
                        [keys[i] for i in range(a, b)], h)
            new_states[a:b] = st
            if self.conf.stage_barriers:
                h = xla_tuning.barrier(h)
        for i in range(tail_start, len(self.layers) - 1):
            with cmod.layer_scope(self._layer_tags[i]):
                h, ns = self.layers[i].apply(cparams[i], states[i], h,
                                             training=True, key=keys[i])
            new_states[i] = ns
        out = self.layers[-1]
        if not hasattr(out, "compute_loss"):
            raise ValueError("last layer must be an OutputLayer/LossLayer")
        loss_kw = {} if weights is None else {"weights": weights}
        with cmod.layer_scope(self._layer_tags[-1]):
            loss = out.compute_loss(
                cparams[-1], states[-1], h, y, training=True, key=keys[-1],
                **loss_kw,
            )
        new_states[-1] = states[-1]
        reg = sum(
            (lyr.regularization(params[i]) for i, lyr in enumerate(self.layers)),
            start=jnp.asarray(0.0),
        )
        return loss.astype(jnp.float32) + reg, new_states

    # ------------------------------------------------------------ train step
    def make_step_fn(self, weighted: bool = False):
        """The un-jitted train step (forward+AD+updaters). ParallelWrapper
        reuses this under mesh shardings; ``weighted`` adds a per-example
        loss-weight argument."""
        updaters = self._updaters
        n_layers = len(self.layers)
        engine = self._fused

        def step(params, states, opt_states, iteration, x, y, key, weights=None,
                 mask=None, label_mask=None):
            keys = list(jax.random.split(key, n_layers))
            scale = engine.current_scale(opt_states) if engine is not None \
                else None
            # loss scaling (arXiv:1710.03740): gradients come out scale x
            # true (the fused apply unscales them); the aux threads the
            # UNSCALED loss for reporting. One trace shape with/without.
            (_, (new_states, loss)), grads = jax.value_and_grad(
                upd.FusedUpdateEngine.wrap_scaled(self._loss, scale),
                has_aux=True
            )(params, states, x, y, keys, weights, mask, label_mask)
            with cmod.optimizer_scope():  # cost attribution: (optimizer) row
                if engine is not None:
                    new_params, new_opts = engine.apply(
                        params, grads, opt_states, iteration)
                else:
                    new_params, new_opts = [], []
                    for i in range(n_layers):
                        if not grads[i]:
                            new_params.append(params[i])
                            new_opts.append(opt_states[i])
                            continue
                        p, s = upd.apply_updater(
                            updaters[i], params[i], grads[i], opt_states[i],
                            iteration
                        )
                        new_params.append(p)
                        new_opts.append(s)
            return new_params, new_states, new_opts, loss

        if weighted:
            return step
        return lambda params, states, opt_states, iteration, x, y, key, \
            mask=None, label_mask=None: step(
            params, states, opt_states, iteration, x, y, key,
            mask=mask, label_mask=label_mask,
        )

    def _build_train_step(self):
        """Jit the step with iteration and RNG-key evolution INSIDE the
        program: per-step host work is then a single enqueue (no scalar
        host->device transfer for the iteration counter, no tiny device
        program for jax.random.split — both cost whole round-trips through
        the remote-chip tunnel)."""
        base = self.make_step_fn(weighted=True)

        def step(params, states, opt_states, iteration, key, x, y,
                 weights=None, mask=None, label_mask=None):
            # trace-time only: one retrace == one line in the CompileWatcher
            note_trace("MultiLayerNetwork.train_step", x, y, weights, mask,
                       label_mask)
            new_key, sub = jax.random.split(key)
            p, s, o, loss = base(params, states, opt_states, iteration, x, y,
                                 sub, weights=weights, mask=mask,
                                 label_mask=label_mask)
            return p, s, o, loss, iteration + 1, new_key

        return jax.jit(step, donate_argnums=(0, 1, 2, 3, 4))

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1):
        """fit(x, y) | fit(DataSet) | fit(iterator) | fit(iterator, epochs=N)."""
        if labels is not None:
            for _ in range(epochs):
                self._fit_batch(jnp.asarray(data), jnp.asarray(labels))
                self._end_epoch()
            return self
        from deeplearning4j_tpu.data.dataset import DataSet

        if isinstance(data, DataSet):  # fit(DataSet) parity: one-batch iterator
            data = [data]
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                # arrays pass through untouched: _fit_batch pads (bucketing)
                # on the HOST before the one host->device transfer
                self._fit_batch(
                    ds.features, ds.labels,
                    mask=getattr(ds, "features_mask", None),
                    label_mask=getattr(ds, "labels_mask", None),
                )
            self._end_epoch()
        return self

    def _end_epoch(self):
        self._dispatcher.flush()  # epoch-end callbacks see a complete epoch
        self.epoch += 1
        for lst in self.listeners:
            if hasattr(lst, "on_epoch_end"):
                lst.on_epoch_end(self)

    # -------------------------------------------------------- truncated BPTT
    def _is_recurrent(self, lyr) -> bool:
        return hasattr(lyr, "apply_seq") and hasattr(lyr, "init_carry")

    @functools.cached_property
    def _tbptt_step(self):
        """One jitted train step over a TBPTT segment: recurrent layers take
        carries in and hand carries out; gradients stop at segment boundaries
        because the incoming carry is a plain (non-differentiated) argument.
        (MultiLayerNetwork.doTruncatedBPTT parity — SURVEY.md §5.7.)"""
        updaters = self._updaters
        n_layers = len(self.layers)
        engine = self._fused

        def step(params, states, opt_states, carries, iteration, x, y, key,
                 mask, label_mask, weights=None):
            note_trace("MultiLayerNetwork.tbptt_step", x, y, weights, mask,
                       label_mask)
            keys = list(jax.random.split(key, n_layers))
            scale = engine.current_scale(opt_states) if engine is not None \
                else None
            (_, ((new_states, new_carries), loss)), grads = \
                jax.value_and_grad(
                    upd.FusedUpdateEngine.wrap_scaled(self._loss_body, scale),
                    has_aux=True)(
                    params, states, carries, x, y, keys, weights, mask,
                    label_mask)
            with cmod.optimizer_scope():  # cost attribution: (optimizer) row
                if engine is not None:
                    new_params, new_opts = engine.apply(
                        params, grads, opt_states, iteration)
                else:
                    new_params, new_opts = [], []
                    for i in range(n_layers):
                        if not grads[i]:
                            new_params.append(params[i])
                            new_opts.append(opt_states[i])
                            continue
                        p, s = upd.apply_updater(
                            updaters[i], params[i], grads[i], opt_states[i],
                            iteration)
                        new_params.append(p)
                        new_opts.append(s)
            return new_params, new_states, new_opts, new_carries, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _init_carries(self, batch_size, dtype):
        return [
            lyr.init_carry(batch_size, dtype) if self._is_recurrent(lyr) else None
            for lyr in self.layers
        ]

    def _fit_batch_tbptt(self, x, y, mask=None, label_mask=None):
        """Segment loop: carries flow forward, gradients are truncated at
        segment boundaries; each segment applies the updater and counts as an
        iteration (update-per-segment semantics — Adam bias correction and
        LR schedules advance per update, as in the reference)."""
        k = self.conf.tbptt_length
        real_n = np.shape(x)[0]
        if self._bucketing is not None:
            # batch axis: pad rows + 0/1 weights (bit-identical, like the
            # non-TBPTT path). Time axis is NOT whole-sequence padded here —
            # each segment pads individually below, so every tail remainder
            # lands on the same (B, k) signature. The whole segment loop
            # stays in HOST numpy (slice/pad on host, ONE upload per step) —
            # slicing a device array per segment would sync device->host
            # for every pad_segment call.
            x = np.asarray(x)
            y = np.asarray(y)
            npad = self._bucketing.bucket_batch(real_n)
            if npad != real_n:
                pad = lambda a: (None if a is None else  # noqa: E731
                                 np.pad(np.asarray(a),
                                        [(0, npad - real_n)] +
                                        [(0, 0)] * (np.ndim(a) - 1)))
                x, y, mask, label_mask = pad(x), pad(y), pad(mask), pad(label_mask)
        else:
            # unbucketed: device-resident slicing (no host round trips)
            x = jnp.asarray(x)
            y = jnp.asarray(y)
        weights = self._dev_weights(np.shape(x)[0], real_n)
        T = x.shape[1]
        # carries live in the compute dtype: an fp32 carry would promote the
        # recurrent matmuls and silently drop the bf16/MXU policy
        carries = self._init_carries(x.shape[0], self._cast(x).dtype)
        losses = []
        for s in range(0, T, k):
            xs = x[:, s:s + k]
            ys = y[:, s:s + k] if y.ndim == 3 else y
            ms = None if mask is None else mask[:, s:s + k]
            lms = None if label_mask is None else label_mask[:, s:s + k]
            if self._bucketing is not None:
                # pad the tail remainder up to k (masks zero over the pad)
                # AND attach all-ones masks to full segments, so every
                # segment — tail or not — shares ONE jit signature
                (xs, ys), ms, lms = self._bucketing.pad_segment(
                    (xs, ys), ms, lms, k)
            self._rng_key, sub = jax.random.split(self._rng_key)
            with tm.step_span("mln.tbptt_step", iteration=self.iteration,
                              segment_start=s):
                (self.params, self.states, self.opt_states, carries, loss) = (
                    self._tbptt_step(self.params, self.states,
                                     self.opt_states, carries,
                                     jnp.asarray(self.iteration), xs, ys,
                                     sub, ms, lms, weights))
            self.iteration += 1
            losses.append(loss)
        self._dispatcher.flush()  # keep cross-path dispatch ordering intact
        self.score_value = float(jnp.mean(jnp.stack(losses)))
        self.last_features = x  # full sequence, not the last TBPTT segment
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)

    # ------------------------------------------------- stateful rnn inference
    def rnn_time_step(self, x):
        """Stateful step-by-step inference (rnnTimeStep parity): carries
        persist across calls. ``x``: (B, T, F) or (B, F) for one step."""
        from deeplearning4j_tpu.nn.recurrent import Bidirectional

        for lyr in self.layers:
            if isinstance(lyr, Bidirectional):
                # the backward direction needs the FUTURE sequence — stepping
                # is ill-defined (the reference's rnnTimeStep throws too)
                raise ValueError("rnn_time_step does not support Bidirectional layers")
        x = self._cast(jnp.asarray(x))
        cparams = self._cast_params(self.params)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None]
        carries = getattr(self, "_rnn_carries", None)
        if carries is not None:
            for c in carries:
                for leaf in jax.tree_util.tree_leaves(c):
                    if leaf.shape[0] != x.shape[0]:
                        raise ValueError(
                            f"rnn_time_step batch size changed ({leaf.shape[0]}"
                            f" -> {x.shape[0]}); call rnn_clear_previous_state()")
        else:
            carries = self._init_carries(x.shape[0], x.dtype)
        h = x
        new_carries = []
        for i, lyr in enumerate(self.layers):
            if self._is_recurrent(lyr):
                h, c = lyr.apply_seq(cparams[i], h, carries[i], training=False)
                new_carries.append(c)
            else:
                h, _ = lyr.apply(cparams[i], self.states[i], h, training=False)
                new_carries.append(None)
        self._rnn_carries = new_carries
        return h[:, -1] if (squeeze and h.ndim == 3) else h

    def rnn_clear_previous_state(self):
        """rnnClearPreviousState parity."""
        self._rnn_carries = None

    def _fit_batch(self, x, y, mask=None, label_mask=None):
        # fit() passes DataSet arrays through raw (bucketing pads on the
        # host); coerce list-typed inputs here without touching arrays that
        # are already on device (np.asarray on a jnp array would sync)
        if not hasattr(x, "ndim"):
            x = np.asarray(x)
        if not hasattr(y, "ndim"):
            y = np.asarray(y)
        if mask is not None and not hasattr(mask, "ndim"):
            mask = np.asarray(mask)
        if label_mask is not None and not hasattr(label_mask, "ndim"):
            label_mask = np.asarray(label_mask)
        if (self.conf.tbptt_length and x.ndim == 3 and y.ndim == 3
                and x.shape[1] > self.conf.tbptt_length):
            # per-sequence (2-D) labels cannot be segmented: fall back to
            # whole-sequence BPTT, as the reference's doTruncatedBPTT does
            return self._fit_batch_tbptt(x, y, mask=mask, label_mask=label_mask)
        real_n = np.shape(x)[0]
        if self._bucketing is not None:
            # host-side padding (numpy): no pad-program compiles, and the
            # weights vector is attached to EVERY batch so the epoch keeps
            # one jit signature per bucket (ragged tail => 0 extra traces)
            x, y, mask, label_mask, _ = self._bucketing.pad_batch(
                x, y, mask, label_mask)
        if self._train_step is None:  # cleared by external training masters
            self._train_step = self._build_train_step()
        if self._it_dev is None or self._it_sync != self.iteration:
            self._it_dev = jax.device_put(jnp.asarray(self.iteration, jnp.int32))
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        # always-weighted: ones over the real rows, zeros over padding
        weights = self._dev_weights(x.shape[0], real_n)
        mask = None if mask is None else jnp.asarray(mask)
        label_mask = None if label_mask is None else jnp.asarray(label_mask)
        # AOT-warmed executable for this signature if warmup() built one
        # (zero retrace/compile risk on the serving path), else the jit path
        step = self._aot_steps.get(
            _dispatch_sig(x, y, weights, mask, label_mask), self._train_step)
        if tm.enabled():
            now = time.time_ns()
            if self._last_fit_ns is not None:
                dt = (now - self._last_fit_ns) / 1e9
                tm.observe("train.step_seconds", dt, model="mln")
                if dt > 0:
                    # cost attribution gauges (docs/OBSERVABILITY.md): real
                    # throughput each step; MFU once cost_report() measured
                    # the program's FLOPs and a peak is configured
                    tm.gauge("train.examples_per_sec", real_n / dt,
                             model="mln")
                    if self._cost_flops_per_example and self._peak_flops:
                        tm.gauge(
                            "train.model_flops_utilization",
                            self._cost_flops_per_example * x.shape[0]
                            / dt / self._peak_flops, model="mln")
            self._last_fit_ns = now
            tm.counter("train.steps_total", model="mln")
        # dispatch span with XLA trace/compile sub-spans when this shape
        # retraced (CompileWatcher markers — docs/OBSERVABILITY.md)
        with tm.step_span("mln.train_step", iteration=self.iteration):
            (self.params, self.states, self.opt_states, loss,
             self._it_dev, self._rng_key) = step(
                self.params, self.states, self.opt_states, self._it_dev,
                self._rng_key, x, y, weights, mask, label_mask,
            )
        self.score_value = loss  # fetched lazily; float() forces transfer
        # activation-stats listeners must never see fabricated padding rows
        self.last_features = x if real_n == x.shape[0] else x[:real_n]
        self.iteration += 1
        self._it_sync = self.iteration
        # sync_every=1: immediate dispatch (legacy cadence); >1: the device
        # loss is queued and listeners fire in coalesced windows — one host
        # round-trip per window instead of a sync point every iteration
        self._dispatcher.iteration_done(loss, self.iteration, self.epoch)

    # -------------------------------------------------------------- pretrain
    def pretrain(self, data, epochs: int = 1):
        """MultiLayerNetwork.pretrain(DataSetIterator) parity: layerwise
        unsupervised training of every pretrain-capable layer (AutoEncoder,
        VariationalAutoencoder), in order. Labels are ignored."""
        for i, lyr in enumerate(self.layers):
            if getattr(lyr, "is_pretrain_layer", lambda: False)():
                self.pretrain_layer(i, data, epochs=epochs)
        return self

    def pretrain_layer(self, i: int, data, epochs: int = 1):
        """pretrainLayer(int, DataSetIterator) parity: train ONE layer on its
        unsupervised objective, inputs fed forward (inference mode) through
        the layers below. One jitted loss+grad+update program per layer."""
        from deeplearning4j_tpu.data.dataset import DataSet

        lyr = self.layers[i]
        if not getattr(lyr, "is_pretrain_layer", lambda: False)():
            raise ValueError(
                f"layer {i} ({type(lyr).__name__}) is not a pretrain layer")
        updater = self._updaters[i]
        opt = updater.init_state(self.params[i])
        layers = self.layers
        below_p = [self.params[j] for j in range(i)]
        below_s = [self.states[j] for j in range(i)]

        @jax.jit
        def step(p, opt_state, iteration, x, key):
            for j in range(i):
                x, _ = layers[j].apply(below_p[j], below_s[j], x,
                                       training=False)
            loss, g = jax.value_and_grad(lyr.pretrain_loss)(p, x, key)
            new_p, new_opt = upd.apply_updater(updater, p, g, opt_state,
                                               iteration)
            return new_p, new_opt, loss

        if isinstance(data, (np.ndarray, jnp.ndarray)):
            data = [DataSet(np.asarray(data), None)]
        elif isinstance(data, DataSet):
            data = [data]
        loss = None
        it_count = 0
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                x = jnp.asarray(ds.features if hasattr(ds, "features") else ds)
                self._rng_key, sub = jax.random.split(self._rng_key)
                self.params[i], opt, loss = step(
                    self.params[i], opt, jnp.asarray(it_count), x, sub)
                it_count += 1
        if loss is not None:
            self.score_value = loss
        return self

    # ------------------------------------------------------------ AOT warmup
    def warmup(self, shapes=None, *, train=True, inference=True,
               dtype=jnp.float32, export_dir=None):
        """Ahead-of-time compile the train step and/or inference forward for
        every bucket BEFORE traffic arrives (``jit(...).lower().compile()``),
        so the first real batch executes a pre-built binary instead of
        paying trace+compile in the serving path (docs/COMPILE_CACHE.md).

        ``shapes``: iterable of full input shapes INCLUDING the batch dim
        (e.g. ``[(8, 28, 28, 1), (16, 28, 28, 1)]``). Defaults to the
        explicit ``batch_buckets`` list x ``conf.input_shape``. The compiled
        executables are kept per signature and dispatched directly by
        fit()/output(); with a persistent compilation cache enabled the
        lowering also lands on disk for the NEXT process.

        ``export_dir``: directory for the on-disk AOT LOWERING store
        (util/aot_store.py): the first process serializes the lowered
        module, a later process deserializes it and skips the Python
        trace + MLIR build entirely — combined with the persistent
        compilation cache, a restarted server's warmup is deserialize-only.
        Trade-off: the loaded path does not donate buffers (an extra
        params/opt-state copy per step) — right for serving and short
        fine-tunes. Returns the number of executables built/loaded."""
        if not self.params:
            raise ValueError("init() the network before warmup()")
        if shapes is None:
            if self.conf.input_shape is None:
                raise ValueError("warmup() needs shapes= or conf.input_shape")
            if (self._bucketing is None
                    or not isinstance(self._bucketing.batch_buckets, tuple)):
                raise ValueError(
                    "warmup() without shapes= needs explicit batch_buckets "
                    "on the conf (pow2 has no finite bucket list)")
            shapes = [(b,) + tuple(self.conf.input_shape)
                      for b in self._bucketing.batch_buckets]
        store = None
        if export_dir is not None:
            from deeplearning4j_tpu.util.aot_store import AotStore

            store = AotStore(export_dir)
        built = 0
        p_s, s_s, o_s = (_struct_of(self.params), _struct_of(self.states),
                         _struct_of(self.opt_states))
        it_s = jax.ShapeDtypeStruct((), jnp.int32)
        key_s = _struct_of(self._rng_key)
        for shape in shapes:
            shape = tuple(int(d) for d in shape)
            b = shape[0]
            x_s = jax.ShapeDtypeStruct(shape, dtype)
            y_s = jax.ShapeDtypeStruct((b,) + tuple(self._output_shape),
                                       jnp.float32)
            # fit always threads a weights vector (ones when unbucketed)
            w_s = jax.ShapeDtypeStruct((b,), jnp.float32)
            if train:
                if self._train_step is None:
                    self._train_step = self._build_train_step()
                sig = _dispatch_sig(x_s, y_s, w_s, None, None)
                if sig not in self._aot_steps:
                    self._aot_steps[sig] = self._aot_build(
                        store, "mln_train_step", sig, self._train_step,
                        (p_s, s_s, o_s, it_s, key_s, x_s, y_s, w_s, None,
                         None), {})
                    built += 1
            if inference:
                # inference path pads rows but carries no weights; both
                # train=False and train=True forwards share one lowering rule
                fsig = (False, _dispatch_sig(x_s, None))
                if fsig not in self._aot_forward:
                    self._aot_forward[fsig] = self._aot_build(
                        store, "mln_forward", fsig, self._forward_jit,
                        (p_s, s_s, x_s), {"mask": None})
                    built += 1
        return built

    def _aot_build(self, store, tag, sig, jit_fn, args, kwargs):
        from deeplearning4j_tpu.util.aot_store import aot_build

        return aot_build(store, tag, self.conf.to_json(), sig, jit_fn,
                         args, kwargs)

    # -------------------------------------------------------- cost reporting
    def cost_report(self, batch_size=None, *, shape=None, dtype=jnp.float32,
                    profile: bool = False, steps: int = 3, peak_flops=None,
                    name: str = "mln", publish: bool = True):
        """Per-layer FLOPs / bytes / device-time cost table for ONE train
        step (docs/OBSERVABILITY.md#cost-attribution--mfu). Static costs
        come from the compiled executable itself — ``lower().compile()``
        then ``cost_analysis()`` totals + HLO op-metadata attribution over
        the ``layer:`` named scopes (util/cost_model.py); backends without
        XLA cost analysis fall back to analytic conf-keyed formulas, tagged
        ``source: analytic``.

        ``profile=True`` additionally executes the compiled step on COPIES
        of the live state (donation-safe — the model does not advance),
        measuring wall step time and a per-layer fwd/bwd device-time table
        from the JAX profiler's XPlane events. MFU is reported against
        ``peak_flops`` (default: the ``DL4J_TPU_PEAK_FLOPS`` env knob).
        The report publishes to the UI server's ``/costs`` route and primes
        the ``train.model_flops_utilization`` gauge for subsequent fits."""
        from deeplearning4j_tpu.util import cost_model as _cm

        if not self.params:
            raise ValueError("init() the network before cost_report()")
        if shape is None:
            if self.conf.input_shape is None:
                raise ValueError(
                    "cost_report() needs shape= or conf.input_shape")
            shape = (int(batch_size or 8),) + tuple(self.conf.input_shape)
        shape = tuple(int(d) for d in shape)
        b = shape[0]
        params_by_tag = {
            t: int(sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(p)))
            for t, p in zip(self._layer_tags, self.params)}
        if self._train_step is None:
            self._train_step = self._build_train_step()
        p_s, s_s, o_s = (_struct_of(self.params), _struct_of(self.states),
                         _struct_of(self.opt_states))
        it_s = jax.ShapeDtypeStruct((), jnp.int32)
        key_s = _struct_of(self._rng_key)
        x_s = jax.ShapeDtypeStruct(shape, dtype)
        y_s = jax.ShapeDtypeStruct((b,) + tuple(self._output_shape),
                                   jnp.float32)
        w_s = jax.ShapeDtypeStruct((b,), jnp.float32)
        compiled = self._train_step.lower(
            p_s, s_s, o_s, it_s, key_s, x_s, y_s, w_s, None, None).compile()
        totals: dict = {}
        attrib = None
        source = "analytic"
        try:
            totals = _cm.compiled_totals(compiled)
            attrib = _cm.attribute_hlo(_cm.compiled_text(compiled))
            source = "xla"
        except _cm.CostAnalysisUnavailable:
            pass
        step_time = layer_times = device_time = None
        if profile:
            rng = np.random.default_rng(0)
            if jnp.issubdtype(dtype, jnp.floating):
                x = jnp.asarray(rng.normal(size=shape), dtype=dtype)
            else:
                x = jnp.zeros(shape, dtype)
            y = jnp.zeros((b,) + tuple(self._output_shape), jnp.float32)
            w = jnp.ones((b,), jnp.float32)
            step_time, layer_times, device_time = _cm.profile_compiled_step(
                compiled,
                (self.params, self.states, self.opt_states,
                 jnp.asarray(0, jnp.int32), self._rng_key),
                (x, y, w, None, None), steps=steps,
                inst_map=attrib.inst_map if attrib else None)
        if attrib is not None:
            rows = _cm.rows_from_attribution(attrib, params_by_tag,
                                             layer_times)
        else:
            entries, cur = [], tuple(self.conf.input_shape or shape[1:])
            for tag, lyr in zip(self._layer_tags, self.layers):
                entries.append((tag, lyr, cur, params_by_tag.get(tag, 0)))
                cur = tuple(lyr.output_shape(cur))
            rows = _cm.analytic_rows(entries, b)
            totals = {"flops": sum(r.flops for r in rows)}
        report = _cm.CostReport(
            rows=rows, totals=totals, batch=b,
            params_total=self.num_params(), source=source, model=str(name),
            step_time_s=step_time, device_time_s=device_time,
            peak_flops=(peak_flops if peak_flops is not None
                        else _cm.peak_flops_from_env(
                            self.conf.compute_dtype)))
        self._cost_flops_per_example = report.flops_per_step / b
        self._peak_flops = report.peak_flops
        if publish:
            _cm.publish_report(str(name), report)
        return report

    # ---------------------------------------------------------------- output
    def make_forward_fn(self):
        """fn(params, states, x) -> output activations (serving wrappers)."""

        def fwd(params, states, x):
            out, _ = self._forward(params, states, x, training=False)
            return out

        return fwd

    def output(self, x, train: bool = False, mask=None):
        """Forward pass (MultiLayerNetwork.output parity). The OutputLayer's
        apply() gives dense+activation, i.e. probabilities. ``train=True``
        uses training-mode statistics (e.g. batchnorm batch stats) but no
        dropout (no RNG is threaded, matching the reference's output(train)).
        ``mask``: (B,T) feature mask (output(x, fMask) parity).

        Under shape bucketing, a ragged batch pads up to its bucket and the
        padded rows are sliced off the result — row-independent layers leave
        the real rows bit-identical while eval keeps one compile per bucket."""
        real_n = None
        if self._bucketing is not None and mask is None:
            x, real_n = self._bucketing.pad_inference_batch(x)
            if real_n == x.shape[0]:
                real_n = None
        mk = None if mask is None else jnp.asarray(mask)
        x = jnp.asarray(x)
        fn = self._forward_train_jit if train else self._forward_jit
        aot = self._aot_forward.get((bool(train), _dispatch_sig(x, mk)))
        out, _ = (aot or fn)(self.params, self.states, x, mask=mk)
        return out if real_n is None else out[:real_n]

    def feed_forward(self, x):
        """Per-layer activations (MultiLayerNetwork.feedForward parity)."""
        h = self._cast(jnp.asarray(x))
        acts = [h]
        for i, lyr in enumerate(self.layers):
            h, _ = lyr.apply(self._cast_params(self.params)[i], self.states[i], h, training=False)
            acts.append(h)
        return acts

    def score(self, dataset=None, x=None, y=None, mask=None, label_mask=None) -> float:
        """Loss on a dataset (MultiLayerNetwork.score parity). Honors the
        DataSet's feature/label masks, like training does."""
        if dataset is not None:
            x, y = dataset.features, dataset.labels
            mask = getattr(dataset, "features_mask", None)
            label_mask = getattr(dataset, "labels_mask", None)
        real_n = np.shape(x)[0]
        if self._bucketing is not None:
            x, y, mask, label_mask, _ = self._bucketing.pad_batch(
                x, y, mask, label_mask)
        mk = None if mask is None else jnp.asarray(mask)
        lmk = None if label_mask is None else jnp.asarray(label_mask)
        x = jnp.asarray(x)
        loss, _ = self._loss_eval(
            self.params, self.states, x, jnp.asarray(y), mk, lmk,
            self._dev_weights(x.shape[0], real_n))
        return float(loss)

    @functools.cached_property
    def _loss_eval(self):
        def eval_loss(params, states, x, y, mask, label_mask, weights=None):
            note_trace("MultiLayerNetwork.loss_eval", x, y, mask, label_mask,
                       weights)
            keys = [None] * len(self.layers)
            loss, _ = self._loss_body(params, states, None, x, y, keys,
                                      weights, mask, label_mask,
                                      training=False)
            return loss, None

        return jax.jit(eval_loss)

    # -------------------------------------------------------------- evaluate
    def evaluate(self, iterator):
        """Classification evaluation over an iterator → Evaluation."""
        from deeplearning4j_tpu.eval import Evaluation

        ev = Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            preds = self.output(ds.features,
                                mask=getattr(ds, "features_mask", None))
            ev.eval(np.asarray(ds.labels), np.asarray(preds))
        return ev

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.eval import RegressionEvaluation

        ev = RegressionEvaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            preds = self.output(ds.features,
                                mask=getattr(ds, "features_mask", None))
            ev.eval(np.asarray(ds.labels), np.asarray(preds))
        return ev

    # -------------------------------------------------------------- plumbing
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    @property
    def score_(self):
        return float(self.score_value)

    def get_score(self) -> float:
        return float(self.score_value)
