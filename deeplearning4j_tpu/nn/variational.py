"""Unsupervised pretraining layers: AutoEncoder + VariationalAutoencoder.

Reference parity: org/deeplearning4j/nn/conf/layers/AutoEncoder.java (denoising
AE with corruption level, tied decoder) and nn/conf/layers/variational/
VariationalAutoencoder.java + nn/layers/variational/VariationalAutoencoder.java
(encoder/decoder stacks, p(z|x) gaussian head, reconstruction distributions,
ELBO pretraining) — path-cite, mount empty this round.

TPU-native collapse: the reference hand-writes the pretrain param gradients
(computeGradientAndScore in the variational layer impl, ~1k LoC); here each
layer exposes ``pretrain_loss`` — a pure function — and the layerwise
``MultiLayerNetwork.pretrain()`` loop jits loss+grad+update into one XLA
program per layer. In the supervised path (fit/output) both layers activate
exactly like the reference: AE = encoder half, VAE = mean of q(z|x).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as act
from deeplearning4j_tpu.nn import weights as winit
from deeplearning4j_tpu.nn.layers import Layer, register_layer
from deeplearning4j_tpu.ops import random as randops


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(Layer):
    """Denoising autoencoder with tied decoder weights (AutoEncoder.java:
    corruptionLevel, sparsity; decode = act(h @ W^T + vb))."""

    n_in: int = 0
    n_out: int = 0
    activation: str = "sigmoid"
    weight_init: str = "xavier"
    corruption_level: float = 0.3
    sparsity: float = 0.0          # L1 penalty on hidden activations
    loss: str = "mse"              # reconstruction loss: "mse" | "xent"

    def initialize(self, key, input_shape):
        n_in = self.n_in or int(input_shape[-1])
        params = {
            "W": winit.init(key, self.weight_init, (n_in, self.n_out)),
            "b": jnp.zeros((self.n_out,)),
            "vb": jnp.zeros((n_in,)),   # visible bias (decoder)
        }
        return params, {}

    def is_pretrain_layer(self) -> bool:
        return True

    def encode(self, params, x):
        return act.resolve(self.activation)(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return act.resolve(self.activation)(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, training=False, key=None):
        x = self._maybe_dropout(x, training, key)
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        return self.encode(params, x), state

    def output_shape(self, input_shape):
        return (self.n_out,)

    def pretrain_loss(self, params, x, key):
        """Denoising reconstruction objective (one minibatch)."""
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        corrupted = x
        if self.corruption_level > 0.0 and key is not None:
            keep = jax.random.bernoulli(
                key, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        h = self.encode(params, corrupted)
        recon = self.decode(params, h)
        if self.loss == "xent":
            eps = 1e-7
            r = jnp.clip(recon, eps, 1.0 - eps)
            loss = -jnp.mean(jnp.sum(
                x * jnp.log(r) + (1.0 - x) * jnp.log(1.0 - r), axis=-1))
        else:
            loss = jnp.mean(jnp.sum(jnp.square(recon - x), axis=-1))
        if self.sparsity:
            loss = loss + self.sparsity * jnp.mean(jnp.sum(jnp.abs(h), axis=-1))
        return loss


def _mlp_init(key, sizes, weight_init):
    params = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params.append({"W": winit.init(sub, weight_init, (a, b)),
                       "b": jnp.zeros((b,))})
    return params


def _mlp_apply(stack, x, fn):
    for lyr in stack:
        x = fn(x @ lyr["W"] + lyr["b"])
    return x


@register_layer
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(Layer):
    """VAE pretrain layer (VariationalAutoencoder.java parity).

    ``n_out`` is the latent size; supervised ``apply`` outputs the latent mean
    (pzxActivationFn applied), matching the reference's activate()."""

    n_in: int = 0
    n_out: int = 0                      # latent dimensionality
    encoder_layer_sizes: tuple = (64,)
    decoder_layer_sizes: tuple = (64,)
    activation: str = "relu"            # encoder/decoder hidden activation
    pzx_activation: str = "identity"    # applied to the latent mean output
    reconstruction_distribution: str = "gaussian"  # | "bernoulli"
    num_samples: int = 1                # MC samples of z per example
    weight_init: str = "xavier"

    def initialize(self, key, input_shape):
        n_in = self.n_in or int(input_shape[-1])
        k_enc, k_mu, k_lv, k_dec, k_out = jax.random.split(key, 5)
        enc_sizes = (n_in,) + tuple(self.encoder_layer_sizes)
        dec_sizes = (self.n_out,) + tuple(self.decoder_layer_sizes)
        h_enc = enc_sizes[-1]
        h_dec = dec_sizes[-1]
        # gaussian reconstruction head outputs mean+logvar per input dim
        out_mult = 2 if self.reconstruction_distribution == "gaussian" else 1
        params = {
            "encoder": _mlp_init(k_enc, enc_sizes, self.weight_init),
            "mu": {"W": winit.init(k_mu, self.weight_init, (h_enc, self.n_out)),
                   "b": jnp.zeros((self.n_out,))},
            "logvar": {"W": winit.init(k_lv, self.weight_init,
                                       (h_enc, self.n_out)),
                       "b": jnp.zeros((self.n_out,))},
            "decoder": _mlp_init(k_dec, dec_sizes, self.weight_init),
            "out": {"W": winit.init(k_out, self.weight_init,
                                    (h_dec, n_in * out_mult)),
                    "b": jnp.zeros((n_in * out_mult,))},
        }
        return params, {}

    def is_pretrain_layer(self) -> bool:
        return True

    def _latent(self, params, x):
        fn = act.resolve(self.activation)
        h = _mlp_apply(params["encoder"], x, fn)
        mu = h @ params["mu"]["W"] + params["mu"]["b"]
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mu, logvar

    def _decode(self, params, z):
        fn = act.resolve(self.activation)
        h = _mlp_apply(params["decoder"], z, fn)
        return h @ params["out"]["W"] + params["out"]["b"]

    def apply(self, params, state, x, *, training=False, key=None):
        x = self._maybe_dropout(x, training, key)
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        mu, _ = self._latent(params, x)
        return act.resolve(self.pzx_activation)(mu), state

    def output_shape(self, input_shape):
        return (self.n_out,)

    def reconstruct(self, params, x):
        """Deterministic reconstruction through the latent mean (the
        reference's reconstructionProbability companion utility)."""
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        mu, _ = self._latent(params, x)
        out = self._decode(params, mu)
        if self.reconstruction_distribution == "gaussian":
            out = out[..., : out.shape[-1] // 2]
        else:
            out = jax.nn.sigmoid(out)
        return out

    def pretrain_loss(self, params, x, key):
        """Negative ELBO (reparameterized), averaged over the batch."""
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        mu, logvar = self._latent(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + jnp.square(mu) - 1.0 - logvar,
                           axis=-1)
        rec = jnp.zeros(x.shape[0])
        for s in range(self.num_samples):
            sub = jax.random.fold_in(key, s) if key is not None else None
            eps = (jax.random.normal(sub, mu.shape, mu.dtype)
                   if sub is not None else jnp.zeros_like(mu))
            z = mu + jnp.exp(0.5 * logvar) * eps
            out = self._decode(params, z)
            if self.reconstruction_distribution == "gaussian":
                m, lv = jnp.split(out, 2, axis=-1)
                rec = rec + 0.5 * jnp.sum(
                    lv + jnp.square(x - m) / jnp.exp(lv)
                    + jnp.log(2.0 * jnp.pi), axis=-1)
            else:  # bernoulli
                rec = rec + jnp.sum(
                    jax.nn.softplus(out) - x * out, axis=-1)
        return jnp.mean(rec / self.num_samples + kl)
