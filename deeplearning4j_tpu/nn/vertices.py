"""Graph vertices — the DAG combinators of ComputationGraph.

Reference parity: org/deeplearning4j/nn/conf/graph/{MergeVertex,
ElementWiseVertex, SubsetVertex, ScaleVertex, ShiftVertex, StackVertex,
UnstackVertex, ReshapeVertex, L2NormalizeVertex, PreprocessorVertex}.java and
their runtime twins under org/deeplearning4j/nn/graph/vertex/impl/** (each
with hand-written doForward/doBackward) — path-cite, mount empty this round.

TPU-native collapse: a vertex is a pure function over its input activations;
there is no doBackward anywhere — JAX reverse-mode differentiates through the
whole graph, and XLA fuses vertex arithmetic into adjacent ops (a residual add
is literally one fused HLO with the conv it follows).

Conventions match nn/layers.py: shapes exclude the batch dim; CNN format NHWC.
``axis`` fields index the BATCHED array (axis 0 = batch); ``output_shape``
converts internally since its shapes exclude the batch dim.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

_VERTEX_TYPES: Dict[str, type] = {}


def register_vertex(cls):
    _VERTEX_TYPES[cls.__name__] = cls
    return cls


def vertex_from_dict(d: dict) -> "GraphVertex":
    d = dict(d)
    cls = _VERTEX_TYPES[d.pop("@vertex")]
    for k, v in list(d.items()):
        if isinstance(v, dict) and "@vertex" in v:  # nested (FrozenVertex)
            d[k] = vertex_from_dict(v)
        elif isinstance(v, list):
            d[k] = tuple(tuple(x) if isinstance(x, list) else x for x in v)
    return cls(**d)


@dataclasses.dataclass(frozen=True)
class GraphVertex:
    """Parameter-free DAG node taking >=1 input activations."""

    def apply(self, *inputs):
        raise NotImplementedError

    def output_shape(self, *input_shapes) -> Tuple[int, ...]:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}
        d["@vertex"] = type(self).__name__
        return d


def _shape_axis(axis: int) -> int:
    """Batched-array axis → batch-excluded shape-tuple axis."""
    if axis == 0:
        raise ValueError("vertex axis 0 is the batch axis")
    return axis - 1 if axis > 0 else axis


@register_vertex
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis (conf/graph/MergeVertex.java).
    axis=-1 is the channel axis in NHWC (the reference merges on dim 1 —
    its NCHW channel axis; same semantics)."""

    axis: int = -1

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=self.axis)

    def output_shape(self, *input_shapes):
        base = list(input_shapes[0])
        ax = _shape_axis(self.axis)
        base[ax] = sum(s[ax] for s in input_shapes)
        return tuple(base)


@register_vertex
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """Pointwise combine (conf/graph/ElementWiseVertex.java).
    op: add | subtract | product | average | max."""

    op: str = "add"

    def apply(self, *inputs):
        o = self.op.lower()
        if o == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if o == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract requires exactly 2 inputs")
            return inputs[0] - inputs[1]
        if o == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if o in ("average", "avg"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out / len(inputs)
        if o == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        if o == "min":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.minimum(out, x)
            return out
        raise ValueError(f"unknown ElementWiseVertex op {self.op}")

    def output_shape(self, *input_shapes):
        return tuple(input_shapes[0])


@register_vertex
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """Slice [from, to] inclusive on the feature axis
    (conf/graph/SubsetVertex.java)."""

    from_idx: int = 0
    to_idx: int = 0
    axis: int = -1

    def apply(self, *inputs):
        (x,) = inputs
        idx = [slice(None)] * x.ndim
        idx[self.axis] = slice(self.from_idx, self.to_idx + 1)
        return x[tuple(idx)]

    def output_shape(self, *input_shapes):
        base = list(input_shapes[0])
        base[_shape_axis(self.axis)] = self.to_idx - self.from_idx + 1
        return tuple(base)


@register_vertex
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    """x * scale (conf/graph/ScaleVertex.java)."""

    scale: float = 1.0

    def apply(self, *inputs):
        return inputs[0] * self.scale

    def output_shape(self, *input_shapes):
        return tuple(input_shapes[0])


@register_vertex
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    """x + shift (conf/graph/ShiftVertex.java)."""

    shift: float = 0.0

    def apply(self, *inputs):
        return inputs[0] + self.shift

    def output_shape(self, *input_shapes):
        return tuple(input_shapes[0])


@register_vertex
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over all non-batch dims (conf/graph/L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def apply(self, *inputs):
        (x,) = inputs
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))
        return x / (norm + self.eps)

    def output_shape(self, *input_shapes):
        return tuple(input_shapes[0])


@register_vertex
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """Concatenate along the BATCH axis (conf/graph/StackVertex.java) —
    used for weight sharing: one subnet applied to several inputs."""

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=0)

    def output_shape(self, *input_shapes):
        return tuple(input_shapes[0])


@register_vertex
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    """Take slice ``index`` of ``num_stacked`` equal batch chunks
    (conf/graph/UnstackVertex.java) — inverse of StackVertex."""

    index: int = 0
    num_stacked: int = 1

    def apply(self, *inputs):
        (x,) = inputs
        step = x.shape[0] // self.num_stacked
        return x[self.index * step : (self.index + 1) * step]

    def output_shape(self, *input_shapes):
        return tuple(input_shapes[0])


@register_vertex
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertex):
    """Reshape non-batch dims (conf/graph/ReshapeVertex.java)."""

    new_shape: tuple = ()  # excl. batch

    def apply(self, *inputs):
        (x,) = inputs
        return x.reshape((x.shape[0],) + tuple(self.new_shape))

    def output_shape(self, *input_shapes):
        return tuple(self.new_shape)


@register_vertex
@dataclasses.dataclass(frozen=True)
class PoolHelperVertex(GraphVertex):
    """Strip first row+col (conf/graph/PoolHelperVertex.java — GoogLeNet
    import compat)."""

    def apply(self, *inputs):
        (x,) = inputs
        return x[:, 1:, 1:, :]

    def output_shape(self, *input_shapes):
        h, w, c = input_shapes[0]
        return (h - 1, w - 1, c)


@register_vertex
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two activations → (B, 1)
    (conf/graph/L2Vertex.java)."""

    eps: float = 1e-8

    def apply(self, *inputs):
        a, b = inputs
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps)

    def output_shape(self, *input_shapes):
        return (1,)


@register_vertex
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """(B, T, C) → (B, C), last step (conf/graph/rnn/LastTimeStepVertex.java;
    the masked variant lives in the LastTimeStep layer wrapper, which sees
    the mask through the layer path)."""

    def apply(self, *inputs):
        (x,) = inputs
        return x[:, -1]

    def output_shape(self, *input_shapes):
        t, c = input_shapes[0]
        return (c,)


@register_vertex
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """(B, C) broadcast along a reference sequence's time axis → (B, T, C)
    (conf/graph/rnn/DuplicateToTimeSeriesVertex.java). Inputs: (static,
    sequence) — T is read from the second input."""

    def apply(self, *inputs):
        x, seq = inputs
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], seq.shape[1], x.shape[1]))

    def output_shape(self, *input_shapes):
        (c,), (t, _) = input_shapes[0], input_shapes[1]
        return (t, c)


@register_vertex
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    """InputPreProcessor-in-a-vertex (conf/graph/PreprocessorVertex.java).
    mode: "rnn_to_ff" (merge time into batch), "ff_to_rnn" (split it back,
    needs t), "cnn_to_ff" (flatten), "ff_to_cnn" (reshape to (h, w, c))."""

    mode: str = "cnn_to_ff"
    shape: tuple = ()  # t for ff_to_rnn; (h, w, c) for ff_to_cnn

    def apply(self, *inputs):
        (x,) = inputs
        if self.mode == "cnn_to_ff":
            return x.reshape(x.shape[0], -1)
        if self.mode == "ff_to_cnn":
            return x.reshape((x.shape[0],) + tuple(self.shape))
        if self.mode == "rnn_to_ff":
            return x.reshape(-1, x.shape[-1])
        if self.mode == "ff_to_rnn":
            (t,) = self.shape
            return x.reshape(-1, t, x.shape[-1])
        raise ValueError(f"unknown preprocessor mode {self.mode!r}")

    def output_shape(self, *input_shapes):
        s = input_shapes[0]
        if self.mode == "cnn_to_ff":
            n = 1
            for d in s:
                n *= d
            return (n,)
        if self.mode == "ff_to_cnn":
            return tuple(self.shape)
        if self.mode == "rnn_to_ff":
            return (s[-1],)
        if self.mode == "ff_to_rnn":
            return (self.shape[0], s[-1])
        raise ValueError(f"unknown preprocessor mode {self.mode!r}")


@register_vertex
@dataclasses.dataclass(frozen=True)
class FrozenVertex(GraphVertex):
    """stop_gradient wrapper (conf/graph/FrozenVertex.java): blocks gradient
    flow through the wrapped vertex's output."""

    inner: Optional[GraphVertex] = None

    def apply(self, *inputs):
        import jax

        return jax.lax.stop_gradient(self.inner.apply(*inputs))

    def output_shape(self, *input_shapes):
        return self.inner.output_shape(*input_shapes)

    def to_dict(self):
        d = super().to_dict()
        d["inner"] = self.inner.to_dict()
        return d
