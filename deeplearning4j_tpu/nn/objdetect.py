"""Object detection: YOLOv2 output layer + detection decoding.

Reference parity: org/deeplearning4j/nn/conf/layers/objdetect/
Yolo2OutputLayer.java (+ impl org/deeplearning4j/nn/layers/objdetect/
Yolo2OutputLayer.java, YoloUtils.java, DetectedObject.java) — path-cite,
mount empty this round.

Label format matches the reference: labels (B, 4+C, Sy, Sx)... transposed to
our NHWC world as (B, Sy, Sx, 4+C): channels [x1, y1, x2, y2] in GRID units
plus one-hot class, zero rows where no object. Network output is
(B, Sy, Sx, A*(5+C)) from a 1x1 conv head.

The loss is YOLOv2's: sigmoid(tx,ty) center offsets + exp(tw,th)*anchor
sizes, squared-error on position/size for the responsible anchor (best IOU),
confidence targets = IOU for responsible anchors and 0 (weighted by
lambda_noobj) elsewhere, softmax cross-entropy on classes. The whole loss is
one jittable function — the reference computes per-cell on the JVM.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.layers import Layer, register_layer


def _iou_wh(wh1, wh2):
    """IOU of boxes sharing a center: intersection of widths/heights."""
    inter = jnp.minimum(wh1[..., 0], wh2[..., 0]) * jnp.minimum(wh1[..., 1], wh2[..., 1])
    union = wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter
    return inter / jnp.maximum(union, 1e-9)


@register_layer
@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(Layer):
    """conf/layers/objdetect/Yolo2OutputLayer.java parity (loss-only layer)."""

    anchors: Tuple[Tuple[float, float], ...] = ()  # (A, 2) in grid units
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    def has_params(self):
        return False

    @property
    def n_anchors(self):
        return len(self.anchors)

    def apply(self, params, state, x, *, training=False, key=None):
        return x, state  # predictions pass through; loss via compute_loss

    def _split(self, x, n_classes):
        b, sy, sx, _ = x.shape
        a = self.n_anchors
        x = x.reshape(b, sy, sx, a, 5 + n_classes)
        txy = x[..., 0:2]
        twh = x[..., 2:4]
        tc = x[..., 4]
        tcls = x[..., 5:]
        return txy, twh, tc, tcls

    def compute_loss(self, params, state, x, labels, *, training=True,
                     key=None, weights=None):
        # no mask parameter on purpose: declaring one makes the network route
        # (B,T) label masks here, which have no YOLO meaning — per-example
        # exclusion goes through ``weights``
        """labels (B, Sy, Sx, 4+C): [x1,y1,x2,y2] grid units + one-hot class;
        all-zero class vector = no object in cell."""
        labels = jnp.asarray(labels, jnp.float32)
        b, sy, sx, _ = x.shape
        n_classes = labels.shape[-1] - 4
        anchors = jnp.asarray(self.anchors, jnp.float32)  # (A,2)
        txy, twh, tc, tcls = self._split(x.astype(jnp.float32), n_classes)

        # predicted boxes in grid units
        pred_xy = jax.nn.sigmoid(txy)                       # offset in cell
        pred_wh = jnp.exp(twh) * anchors[None, None, None]  # (B,Sy,Sx,A,2)
        pred_conf = jax.nn.sigmoid(tc)

        # ground truth per cell
        gt_x1, gt_y1 = labels[..., 0], labels[..., 1]
        gt_x2, gt_y2 = labels[..., 2], labels[..., 3]
        gt_wh = jnp.stack([gt_x2 - gt_x1, gt_y2 - gt_y1], -1)   # (B,Sy,Sx,2)
        gt_cxy = jnp.stack([(gt_x1 + gt_x2) / 2, (gt_y1 + gt_y2) / 2], -1)
        cell_xy = gt_cxy - jnp.floor(gt_cxy)                    # offset in cell
        obj = (jnp.sum(labels[..., 4:], -1) > 0).astype(jnp.float32)  # (B,Sy,Sx)

        # responsible anchor: best IOU with gt by shape
        ious_a = _iou_wh(gt_wh[..., None, :], anchors[None, None, None])  # (B,Sy,Sx,A)
        resp = jax.nn.one_hot(jnp.argmax(ious_a, -1), self.n_anchors)     # (B,Sy,Sx,A)
        resp = resp * obj[..., None]

        # position/size loss (sqrt on wh as in the paper/reference)
        pos = jnp.sum(resp[..., None] * (pred_xy - cell_xy[..., None, :]) ** 2,
                      axis=(-2, -1))
        siz = jnp.sum(resp[..., None] * (jnp.sqrt(jnp.maximum(pred_wh, 1e-9))
                                         - jnp.sqrt(jnp.maximum(gt_wh[..., None, :], 1e-9))) ** 2,
                      axis=(-2, -1))

        # confidence: target IOU(pred, gt) for responsible anchors, 0 others
        # (IOU is a LABEL — stop_gradient, else box sizes inflate to chase it)
        iou_pg = jax.lax.stop_gradient(_iou_wh(pred_wh, gt_wh[..., None, :]))
        conf_obj = jnp.sum(resp * (pred_conf - iou_pg) ** 2, -1)
        conf_noobj = jnp.sum((1.0 - resp) * pred_conf ** 2, -1)

        # class loss: softmax xent on responsible anchors
        logp = jax.nn.log_softmax(tcls, axis=-1)
        cls = -jnp.sum(resp[..., None] * labels[..., None, 4:] * logp,
                       axis=(-2, -1))

        per_cell = (self.lambda_coord * (pos + siz)
                    + conf_obj + self.lambda_noobj * conf_noobj + cls * obj)
        per_ex = jnp.sum(per_cell, axis=(1, 2))
        if weights is not None:
            return jnp.sum(per_ex * weights) / jnp.maximum(jnp.sum(weights), 1e-9)
        return jnp.mean(per_ex)

    def output_shape(self, input_shape):
        return tuple(input_shape)


@dataclasses.dataclass
class DetectedObject:
    """org/deeplearning4j/nn/layers/objdetect/DetectedObject.java parity."""

    center_x: float
    center_y: float
    width: float
    height: float
    predicted_class: int
    confidence: float

    def top_left(self):
        return (self.center_x - self.width / 2, self.center_y - self.height / 2)

    def bottom_right(self):
        return (self.center_x + self.width / 2, self.center_y + self.height / 2)


def get_predicted_objects(layer: Yolo2OutputLayer, network_output,
                          threshold: float = 0.5,
                          nms_threshold: float = 0.4) -> List[List[DetectedObject]]:
    """YoloUtils.getPredictedObjects + NMS parity (host-side decode)."""
    out = np.asarray(network_output, np.float32)
    b, sy, sx, _ = out.shape
    a = layer.n_anchors
    n_classes = out.shape[-1] // a - 5
    out = out.reshape(b, sy, sx, a, 5 + n_classes)
    anchors = np.asarray(layer.anchors, np.float32)
    results: List[List[DetectedObject]] = []
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for bi in range(b):
        objs: List[DetectedObject] = []
        conf = sig(out[bi, ..., 4])
        for yi, xi, ai in zip(*np.nonzero(conf > threshold)):
            o = out[bi, yi, xi, ai]
            cx = xi + sig(o[0])
            cy = yi + sig(o[1])
            w = float(np.exp(o[2]) * anchors[ai, 0])
            h = float(np.exp(o[3]) * anchors[ai, 1])
            cls = int(np.argmax(o[5:]))
            objs.append(DetectedObject(float(cx), float(cy), w, h, cls,
                                       float(conf[yi, xi, ai])))
        results.append(_nms(objs, nms_threshold))
    return results


def _nms(objs: List[DetectedObject], thr: float) -> List[DetectedObject]:
    objs = sorted(objs, key=lambda o: -o.confidence)
    kept: List[DetectedObject] = []
    for o in objs:
        if all(_iou_xy(o, k) < thr for k in kept):
            kept.append(o)
    return kept


def _iou_xy(a: DetectedObject, b: DetectedObject) -> float:
    ax1, ay1 = a.top_left()
    ax2, ay2 = a.bottom_right()
    bx1, by1 = b.top_left()
    bx2, by2 = b.bottom_right()
    ix = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    iy = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = ix * iy
    union = a.width * a.height + b.width * b.height - inter
    return inter / max(union, 1e-9)
