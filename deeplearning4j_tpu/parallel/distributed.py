"""Multi-host bootstrap: the control plane for DCN-spanning meshes.

Reference parity: nd4j-parameter-server (ModelParameterServer,
AeronUdpTransport, MeshOrganizer — SURVEY.md §2.2 J17, §2.4) and the Spark
driver's role as coordinator in §3.4.

TPU-native collapse: there is no parameter-server process and no UDP mesh to
organize — the data plane is XLA collectives over ICI within a slice and DCN
across slices, emitted by the compiler from the SAME single-program step the
tests run on one host. What remains of J17 is only bootstrap: every process
must find the coordinator, learn its process id, and see the global device
set. That is ``jax.distributed.initialize`` (PJRT distributed runtime — a
tiny gRPC control plane), wrapped here with the reference's vocabulary.

Usage on each host of a pod/multi-slice job:

    from deeplearning4j_tpu.parallel import distributed
    distributed.initialize(coordinator="10.0.0.1:8476",
                           num_processes=4, process_id=host_idx)
    mesh = distributed.global_mesh(data=-1)     # all chips across all hosts
    ParallelWrapper(net, mesh=mesh).fit(iterator)

The test story mirrors the reference's (§4 "distributed without a cluster"):
multi-chip behavior is validated on the 8-virtual-device CPU mesh in-process;
``initialize`` itself is exercised single-process (num_processes=1), which
runs the full coordinator service on localhost.
"""

from __future__ import annotations

from typing import Optional

import jax

from deeplearning4j_tpu.parallel.mesh import TrainingMesh

_initialized = False


def initialize(coordinator: Optional[str] = None, num_processes: Optional[int] = None,
               process_id: Optional[int] = None, local_device_ids=None) -> None:
    """ModelParameterServer-bootstrap parity over jax.distributed.

    ``coordinator``: "host:port" of process 0 (the reference's master/driver
    address). No-op when already initialized or when running single-process
    with no coordinator given."""
    global _initialized
    if _initialized:
        return
    if coordinator is None and (num_processes is None or num_processes <= 1):
        return  # single-process: nothing to bootstrap
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(data: int = -1, model: int = 1, seq: int = 1) -> TrainingMesh:
    """Mesh over ALL devices visible across processes. ``data=-1`` fills the
    data axis with whatever model*seq leaves."""
    devices = jax.devices()  # global list under jax.distributed
    if data <= 0:
        data = len(devices) // (model * seq)
    return TrainingMesh(data=data, model=model, seq=seq, devices=devices)


def is_coordinator() -> bool:
    """True on the process that should write checkpoints/logs (driver
    parity: the Spark master's save/report role in §3.4)."""
    return jax.process_index() == 0
