"""Multi-host bootstrap: the control plane for DCN-spanning meshes.

Reference parity: nd4j-parameter-server (ModelParameterServer,
AeronUdpTransport, MeshOrganizer — SURVEY.md §2.2 J17, §2.4) and the Spark
driver's role as coordinator in §3.4.

TPU-native collapse: there is no parameter-server process and no UDP mesh to
organize — the data plane is XLA collectives over ICI within a slice and DCN
across slices, emitted by the compiler from the SAME single-program step the
tests run on one host. What remains of J17 is only bootstrap: every process
must find the coordinator, learn its process id, and see the global device
set. That is ``jax.distributed.initialize`` (PJRT distributed runtime — a
tiny gRPC control plane), wrapped here with the reference's vocabulary.

Usage on each host of a pod/multi-slice job:

    from deeplearning4j_tpu.parallel import distributed
    distributed.initialize(coordinator="10.0.0.1:8476",
                           num_processes=4, process_id=host_idx)
    mesh = distributed.global_mesh(data=-1)     # all chips across all hosts
    ParallelWrapper(net, mesh=mesh).fit(iterator)

The test story mirrors the reference's (§4 "distributed without a cluster"):
multi-chip behavior is validated on the 8-virtual-device CPU mesh in-process;
``initialize`` itself is exercised single-process (num_processes=1), which
runs the full coordinator service on localhost.
"""

from __future__ import annotations

from typing import Optional

import jax

from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.util.faults import RetryPolicy

_initialized = False

#: default handshake policy: workers racing the coordinator's gRPC service
#: coming up (the normal elastic-restart case) back off and retry instead of
#: dying on the first connection refusal; jittered so N restarted workers
#: don't re-dial in lockstep (docs/FAULT_TOLERANCE.md)
BOOTSTRAP_RETRY = RetryPolicy(max_attempts=5, base_delay=1.0, max_delay=10.0,
                              deadline=120.0)


def initialize(coordinator: Optional[str] = None, num_processes: Optional[int] = None,
               process_id: Optional[int] = None, local_device_ids=None,
               retry: Optional[RetryPolicy] = BOOTSTRAP_RETRY) -> None:
    """ModelParameterServer-bootstrap parity over jax.distributed.

    ``coordinator``: "host:port" of process 0 (the reference's master/driver
    address). No-op when already initialized or when running single-process
    with no coordinator given. The handshake runs under ``retry``
    (util/faults.py): a worker restarted by the elastic supervisor while
    the coordinator is still coming up backs off instead of crash-looping;
    ``retry=None`` restores the old one-shot behavior."""
    global _initialized
    if _initialized:
        return
    if coordinator is None and (num_processes is None or num_processes <= 1):
        return  # single-process: nothing to bootstrap

    def handshake():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
            )
        except RuntimeError as e:
            # a retried attempt after a partially-successful first one:
            # the runtime IS up — that's success, not a handshake failure
            if "already initialized" in str(e).lower():
                return
            raise

    if retry is not None:
        retry.run(handshake, name="dcn_bootstrap",
                  retry_on=(RuntimeError, ConnectionError, OSError))
    else:
        handshake()
    _initialized = True


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(data: int = -1, model: int = 1, seq: int = 1) -> TrainingMesh:
    """Mesh over ALL devices visible across processes. ``data=-1`` fills the
    data axis with whatever model*seq leaves."""
    devices = jax.devices()  # global list under jax.distributed
    if data <= 0:
        data = len(devices) // (model * seq)
    return TrainingMesh(data=data, model=model, seq=seq, devices=devices)


def host_count() -> int:
    """Process/host count with a single-process fallback — the default
    ``hosts`` factor for the hierarchical compressed all-reduce
    (parallel/compression.py): intra-host combines stay full-precision
    over ICI, only the cross-host exchange is encoded (the DCN seam this
    module bootstraps)."""
    try:
        return int(jax.process_count())
    except RuntimeError:
        return 1


def is_coordinator() -> bool:
    """True on the process that should write checkpoints/logs (driver
    parity: the Spark master's save/report role in §3.4)."""
    return jax.process_index() == 0
