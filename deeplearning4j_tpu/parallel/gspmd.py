"""GSPMD building blocks: lane decomposition, deterministic combines, ZeRO.

This module is the shared machinery of the mesh + ``NamedSharding`` + ``jit``
rewrite (ROADMAP item 1): every distributed-training entry point —
``ParallelWrapper``, both Spark-style training masters, MoE expert
parallelism, ring attention, the GPipe pipeline — is ONE ``jit``-compiled
SPMD program whose parallelism is expressed as sharding annotations, with
XLA's partitioner inserting the collectives (SNIPPETS.md [2]/[3];
whole-program compilation per arXiv:1810.09868). No per-device mapped
functions, no pmap,
no per-device Python.

Three ideas live here:

**Lanes.** Data parallelism is expressed as a leading ``replicas`` axis
("lanes"): the global batch reshapes to ``(R, b, ...)`` and the per-lane
step runs under ``vmap`` with the lane axis sharded over the mesh ``data``
axis. With one lane per device the per-device tensor shapes equal the lane
shapes, which is what makes determinism provable (below).

**Deterministic combines.** XLA rewrites a reduce over a sharded dimension
into partial-reduce + AllReduce, whose accumulation order depends on the
topology — the reason naive DP training is not reproducible across device
counts. ``pairwise_sum`` instead writes the cross-lane combine as an
explicit balanced binary tree of adds over lane slices: GSPMD only moves
data, never re-associates explicit adds, so the combined value is
bit-identical on 8 devices and on 1 — PROVIDED no multiply shares a fused
kernel with the tree adds (LLVM FMA contraction is fusion-context
dependent; the wrapper therefore stages lane-compute / combine / update as
three jit programs — see the determinism note in parallel/wrapper.py).
The single-device reference is the SAME vmapped jit executed
unpartitioned, giving the proven invariant: an 8-virtual-device sharded
fit equals the single-device fit BIT-FOR-BIT (params, Adam moments, RNG
key) for gemm/recurrent topologies. (Known backend limits, pinned by
tests: XLA:CPU lowers the vmapped conv *filter gradient* to a
batch-grouped convolution whose accumulation grouping depends on the lane
fold, and gemm k-blocking becomes shape-dependent for contraction dims
>= ~1024 — such topologies reproduce to ~1e-6 instead of exactly.)

**ZeRO optimizer-state sharding** (arXiv:2004.13336, "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"):
optimizer moments (Adam m/v, momentum buffers) are placed with each leaf
sharded over the ``data`` axis and the layout is re-asserted inside the
step with ``with_sharding_constraint``; the partitioner then emits
reduce-scatter(grads) -> sharded elementwise update -> all-gather(params),
so per-chip optimizer memory and update compute both drop ~Nx. Elementwise
updates are association-free, so ZeRO composes with the deterministic mode
without losing bit-identity.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.util import cost_model as cmod

# ---------------------------------------------------------------------------
# deterministic cross-lane combines
# ---------------------------------------------------------------------------


def pairwise_sum(t):
    """Sum over axis 0 as an explicit balanced tree of adds.

    The association is fixed by the op graph — ((x0+x1)+(x2+x3))... — so the
    result is bit-identical whether the lane axis lives on one device or is
    sharded across the mesh (GSPMD moves slices, it cannot re-associate
    explicit adds the way it re-associates a ``reduce``). Odd remainders
    fold in at the end of each level, so any R works.
    """
    while t.shape[0] > 1:
        half = t.shape[0] // 2
        even = t[0 : 2 * half : 2] + t[1 : 2 * half : 2]
        t = even if t.shape[0] % 2 == 0 else jnp.concatenate(
            [even, t[-1:]], axis=0)
    return t[0]


def pairwise_mean(t):
    return pairwise_sum(t) * (1.0 / t.shape[0])


def tree_pairwise_sum(tree):
    return jax.tree_util.tree_map(pairwise_sum, tree)


def tree_pairwise_mean(tree):
    return jax.tree_util.tree_map(pairwise_mean, tree)


def combine_states(stacked_states):
    """Cross-lane combine for non-trainable state (batchnorm statistics):
    floating leaves average (the pmean the legacy per-device path applied),
    everything else takes lane 0's copy."""
    return jax.tree_util.tree_map(
        lambda v: pairwise_mean(v)
        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact) else v[0],
        stacked_states)


# ---------------------------------------------------------------------------
# per-lane loss/grad for MLN and ComputationGraph
# ---------------------------------------------------------------------------


def _lane_scaled(model) -> bool:
    """Whether this model's lane bodies must run under loss scaling: the
    fused engine owns the policy (nn/updaters.py) — lane gradients then
    come out ``scale`` x true and the fused apply unscales them
    (the satellite closing parallel/gspmd.py's old NotImplementedError)."""
    engine = getattr(model, "_fused", None)
    return engine is not None and engine.loss_scale != "none"


def _lane_value_and_grad(loss_fn, scaled, args, scale):
    """Shared AD tail of every lane body: plain value_and_grad, or the
    ``wrap_scaled`` variant whose gradients are ``scale`` x true while the
    reported loss stays unscaled (ONE trace shape either way — the same
    contract as the single-host step in nn/multilayer.py)."""
    if not scaled:
        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(*args)
        return loss, new_states, grads
    (_, (new_states, loss)), grads = jax.value_and_grad(
        upd.FusedUpdateEngine.wrap_scaled(loss_fn, scale), has_aux=True
    )(*args)
    return loss, new_states, grads


def make_lane_value_and_grad(model) -> Callable:
    """fn(params, states, x, y, key, weights, fm, lm, scale) ->
    ((loss, weight_sum), (new_states, grads)) for ONE lane.

    Works for MultiLayerNetwork (list-keyed params, single input) and
    ComputationGraph (dict-keyed params, multi input/output — raw arrays or
    lists zip with the graph's declared input/output order, exactly like
    ``make_step_fn``). ``weight_sum`` is the lane's loss-weight mass — the
    wrapper's combine stage recombines lane means into the global weighted
    mean with it. ``scale``: the loss-scale multiplier when the model's
    fused engine has a scaling policy (the wrapper threads
    ``engine.current_scale(opt_states)``; pass None otherwise) — gradients
    then come out scaled and the fused apply unscales at update time."""
    is_graph = isinstance(model._updaters, dict)
    scaled = _lane_scaled(model)
    if is_graph:
        layer_names = [n.name for n in model.topo if n.is_layer]
        in_names = list(model.conf.inputs)
        out_names = list(model.conf.outputs)

        def lane(params, states, x, y, key, weights, fm, lm, scale=None):
            subkeys = jax.random.split(key, len(layer_names))
            keys = dict(zip(layer_names, subkeys))
            feed = (dict(zip(in_names, x)) if isinstance(x, (list, tuple))
                    else {in_names[0]: x})
            labs = (dict(zip(out_names, y)) if isinstance(y, (list, tuple))
                    else {out_names[0]: y})
            loss, new_states, grads = _lane_value_and_grad(
                model._loss, scaled,
                (params, states, feed, labs, keys, weights, fm, lm), scale)
            wsum = jnp.sum(weights) if weights is not None \
                else jnp.asarray(1.0, jnp.float32)
            return (loss, wsum), (new_states, grads)

        return lane

    n_layers = len(model.layers)

    def lane(params, states, x, y, key, weights, fm, lm, scale=None):
        keys = list(jax.random.split(key, n_layers))
        loss, new_states, grads = _lane_value_and_grad(
            model._loss, scaled,
            (params, states, x, y, keys, weights, fm, lm), scale)
        wsum = jnp.sum(weights) if weights is not None \
            else jnp.asarray(1.0, jnp.float32)
        return (loss, wsum), (new_states, grads)

    return lane


def make_lane_tbptt_value_and_grad(model) -> Callable:
    """TBPTT-segment variant (MultiLayerNetwork only): carries in/out, one
    update per segment — the lane body of the wrapper's sharded
    ``doTruncatedBPTT``. Loss scaling threads through exactly like
    :func:`make_lane_value_and_grad`."""
    if isinstance(model._updaters, dict):
        raise NotImplementedError(
            "sharded TBPTT is implemented for MultiLayerNetwork; fit the "
            "ComputationGraph through its own fit() or without tbptt_length")
    n_layers = len(model.layers)
    scaled = _lane_scaled(model)

    def seg_loss(params, states, carries, x, y, keys, weights, fm, lm):
        loss, (new_states, new_carries) = model._loss_body(
            params, states, carries, x, y, keys, weights, fm, lm)
        return loss, (new_states, new_carries)

    def lane(params, states, carries, x, y, key, weights, fm, lm,
             scale=None):
        keys = list(jax.random.split(key, n_layers))
        args = (params, states, carries, x, y, keys, weights, fm, lm)
        if scaled:
            (_, ((new_states, new_carries), loss)), grads = \
                jax.value_and_grad(
                    upd.FusedUpdateEngine.wrap_scaled(seg_loss, scale),
                    has_aux=True)(*args)
        else:
            (loss, (new_states, new_carries)), grads = jax.value_and_grad(
                seg_loss, has_aux=True)(*args)
        wsum = jnp.sum(weights) if weights is not None \
            else jnp.asarray(1.0, jnp.float32)
        return (loss, wsum), (new_states, new_carries, grads)

    return lane


def apply_updaters(model, params, grads, opt_states, iteration,
                   scaled_grads: bool = False):
    """One updater application over the model's per-layer updaters — the
    shared tail of every sharded step (MLN list / CG dict keyed). A model
    built with ``fused_update`` routes through its FusedUpdateEngine: the
    flat per-(rule, dtype) buffers are exactly what ZeRO shards
    (zero_shardings on the 1-D padded dimension), so the partitioner emits
    reduce-scatter(grad buffer) -> sharded fused update ->
    all-gather(params) with no extra plumbing.

    ``scaled_grads``: the caller scaled its lane losses (the wrapper's
    lane builders under a loss_scale policy), so the engine's unscale at
    apply time is CORRECT; callers that compute unscaled gradients (the
    Spark-facade masters) leave it False and a scaling policy fails loudly
    instead of silently double-unscaling."""
    engine = getattr(model, "_fused", None)
    if engine is not None:
        if engine.loss_scale != "none" and not scaled_grads:
            raise NotImplementedError(
                "loss_scale under this master is not wired: its lane "
                "value-and-grad computes unscaled gradients, so the fused "
                "unscale would corrupt them — use ParallelWrapper (which "
                "scales the lane loss), or keep loss_scale='none' here")
        with cmod.optimizer_scope():
            return engine.apply(params, grads, opt_states, iteration)
    is_graph = isinstance(model._updaters, dict)
    updaters = model._updaters
    if is_graph:
        new_params, new_opts = dict(params), dict(opt_states)
        keys = [n.name for n in model.topo if n.is_layer]
    else:
        new_params, new_opts = list(params), list(opt_states)
        keys = range(len(model.layers))
    with cmod.optimizer_scope():
        for k in keys:
            if not grads[k]:
                continue
            p, s = upd.apply_updater(
                updaters[k], params[k], grads[k], opt_states[k], iteration)
            new_params[k] = p
            new_opts[k] = s
    return new_params, new_opts


def apply_updaters_flat(model, params, grad_bufs, opt_states, iteration):
    """:func:`apply_updaters` over PRE-FLATTENED fused group buffers — the
    compressed all-reduce path (parallel/compression.py): the per-lane
    gradients flatten once per step, the encode/all-reduce/decode chain runs
    on the flat buffers (what ZeRO reduce-scatters), and the decode output
    feeds the fused update directly — no per-leaf round trip."""
    engine = getattr(model, "_fused", None)
    if engine is None:
        raise ValueError(
            "apply_updaters_flat needs a fused_update model — only the "
            "FusedUpdateEngine defines the flat buffer layout")
    with cmod.optimizer_scope():
        return engine.apply_flat(params, grad_bufs, opt_states, iteration)


# ---------------------------------------------------------------------------
# ZeRO optimizer-state sharding (arXiv:2004.13336)
# ---------------------------------------------------------------------------


def zero_shardings(mesh: Mesh, tree, axis: str = "data",
                   min_elements: int = 1024):
    """Per-leaf ``NamedSharding`` tree for ZeRO-style optimizer-state
    sharding: each array leaf shards its first dimension divisible by the
    ``axis`` size; leaves too small (< ``min_elements``) or with no
    divisible dimension stay replicated. Sharding choice never changes
    values — optimizer updates are elementwise — only which device holds
    (and updates) which slice."""
    n = int(mesh.shape[axis]) if axis in mesh.shape else 1

    def spec_of(leaf):
        shape = np.shape(leaf)
        if n <= 1 or int(np.prod(shape or (0,))) < min_elements:
            return NamedSharding(mesh, P())
        for d, size in enumerate(shape):
            if size and size % n == 0:
                return NamedSharding(
                    mesh, P(*([None] * d + [axis])))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(spec_of, tree)


def constrain_tree(tree, shardings):
    """with_sharding_constraint leaf-wise (inside jit)."""
    return jax.tree_util.tree_map(
        lambda t, s: lax.with_sharding_constraint(t, s), tree, shardings)


def place_tree(tree, shardings):
    """device_put leaf-wise (outside jit)."""
    return jax.tree_util.tree_map(
        lambda t, s: jax.device_put(t, s), tree, shardings)


def sharded_fraction(shardings) -> float:
    """Fraction of leaves whose spec actually partitions (telemetry)."""
    leaves = [s for s in jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))]
    if not leaves:
        return 0.0
    n = sum(1 for s in leaves if any(s.spec))
    return n / len(leaves)


def tree_bytes_per_device(tree) -> int:
    """Bytes one device holds for a placed pytree — the ZeRO memory
    number. Computed from each leaf's sharding (``shard_shape``), not by
    fetching data."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = np.shape(leaf)
        itemsize = np.dtype(leaf.dtype).itemsize if hasattr(leaf, "dtype") \
            else 4
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(tuple(shape))
        total += int(np.prod(shape or (1,))) * itemsize
    return total


def tree_bytes(tree) -> int:
    return sum(
        int(np.prod(np.shape(l) or (1,)))
        * (np.dtype(l.dtype).itemsize if hasattr(l, "dtype") else 4)
        for l in jax.tree_util.tree_leaves(tree))


def describe_shardings(tree) -> Dict[str, str]:
    """{key-path: PartitionSpec} for a placed pytree — the per-device
    layout table kept on ``ParallelWrapper.layout`` and summarized by the
    ``parallel.*`` telemetry gauges."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        s = getattr(leaf, "sharding", None)
        out[key] = str(getattr(s, "spec", s))
    return out


def layout_signature(mesh, extra: Any = None) -> str:
    """Stable string describing the mesh layout (+ optional extras like the
    ZeRO flag / replica count): folded into AOT/compile-cache keys so an
    executable compiled for one sharding layout is never served for
    another. (jit's in-memory dispatch cache and the persistent XLA
    compilation cache both already key on input shardings/partitioned HLO;
    this signature makes the layout explicit for on-disk export keys and
    for tests.)"""
    shape = dict(getattr(mesh, "shape", {})) or {}
    sig = ",".join(f"{k}={v}" for k, v in sorted(shape.items()))
    return f"mesh({sig})|extra({extra})"
