"""Pipeline parallelism: GPipe-style microbatched stage execution, as GSPMD.

The reference has no pipeline parallelism (SURVEY.md §2.3: PP — absent);
this is the TPU-native extension: S identical-signature stages live on S
devices along a mesh axis and microbatches stream through the stage ring.
The schedule is the classic GPipe fill-drain: n_micro + S - 1 ticks, bubble
fraction (S-1)/(n_micro+S-1).

GSPMD formulation (no per-device mapped functions — ROADMAP item 1): stage params and the
inter-stage activation buffer carry an explicit leading stage axis annotated
``PartitionSpec(axis_name)``; each tick applies the stage function across
the stage axis with ``vmap`` (per device: its own stage's params on the
activation that just arrived) and rotates the buffer one stage with
``jnp.roll`` on the sharded axis — the partitioner lowers the roll to the
ring's collective-permute. The tick loop is a ``lax.scan``, so the whole
pipeline is ONE whole-program-compiled XLA computation (arXiv:1810.09868)
and reverse AD through the scan gives the backward pipeline for free: the
scan's transpose threads cotangents backwards through the SAME rolled stage
buffer, accumulating each stage's parameter gradient across its microbatches
(microbatch gradient accumulation, without a hand-written backward).

API:

    stacked = stack_stage_params([p0, p1, ...])       # leading stage axis
    y = pipeline_forward(stage_fn, stacked, x, n_micro=4,
                         mesh=m.mesh, axis_name="model")

``stage_fn(params_i, x) -> y`` must map activations of a fixed shape to the
same shape (equal-width stages — the standard PP regime; embed/head layers
live outside the pipeline).

Batch sizes not divisible by ``n_micro`` are padded by repeating the last
row up to divisibility and slicing the padded rows off the result — the r8
ragged-batch stance (pad, never raise; under a training loss the padded
rows carry 0/1 loss weights so gradients stay exact —
parallel/pipelined.py threads them).

:func:`gpipe_scan` is the raw differentiable building block (no jit, no
mesh): the :class:`~deeplearning4j_tpu.parallel.pipelined.PipelinedTrainer`
embeds it inside its lane-decomposed train step, where the lane axis rides
'data', tensor-parallel annotations ride 'model', and the stacked stage
axis rides 'pipe' — the full 3D (data x tensor x pipe) composition in one
jit program.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(params_list: Sequence):
    """[per-stage pytree] → one pytree with a leading stage axis (shard it
    over the pipeline mesh axis)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def bubble_fraction(stages: int, n_micro: int) -> float:
    """The GPipe fill-drain schedule's idle fraction: of the
    ``n_micro + S - 1`` ticks each stage is live for, ``S - 1`` are
    fill/drain bubble — identically for the forward scan and its AD
    transpose, so the whole-step bubble fraction is the same expression.
    Deterministic in (S, n_micro); computed from the schedule, not timed
    (the honest CPU stance — wall-clock ranking belongs to real chips)."""
    s, m = int(stages), int(n_micro)
    if s < 1 or m < 1:
        raise ValueError(f"stages ({s}) and n_micro ({m}) must be >= 1")
    return (s - 1) / (m + s - 1)


def gpipe_scan(stage_fn: Callable, stacked_params, micro,
               constrain: Optional[Callable] = None):
    """The raw GPipe tick loop, differentiable and transform-friendly.

    ``stage_fn(stage_params, x) -> y`` is vmapped over the leading stage
    axis of ``stacked_params`` (S stages); ``micro`` is ``(n_micro, mb,
    ...)``. Each tick feeds microbatch t to stage 0, applies every stage to
    the activation that just arrived, banks the last stage's output, and
    rotates the buffer one hop (``jnp.roll`` on the stage axis — the
    collective-permute once the axis is sharded). Returns ``(n_micro, mb,
    ...)`` outputs matching sequential stage application (tested).

    ``constrain``: optional ``tree -> tree`` hook asserting the stage-axis
    sharding on the rolled buffer (``pipeline_forward`` passes one; the
    pipelined trainer runs inside ``vmap`` where the annotation on the
    stacked params already pins the layout by propagation).

    No jit here: callers embed it inside their own compiled step — reverse
    AD through the scan yields the backward pipeline through the same
    rolled buffer, with per-stage gradients accumulated over microbatches
    by the scan transpose.
    """
    leading = {l.shape[0] for l in jax.tree_util.tree_leaves(stacked_params)}
    if len(leading) != 1:
        raise ValueError(
            f"stacked_params leading dims differ: {sorted(leading)} — every "
            "leaf needs the same leading stage axis (stack_stage_params)")
    (s,) = leading
    n_micro = micro.shape[0]
    mb_shape = micro.shape[1:]
    ident = lambda t: t  # noqa: E731
    pin = constrain or ident
    vstage = jax.vmap(stage_fn, in_axes=(0, 0))
    buffer = jnp.zeros((s,) + mb_shape, micro.dtype)
    outs = jnp.zeros((n_micro,) + mb_shape, micro.dtype)

    def tick(carry, t):
        buffer, outs = carry
        feed = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, n_micro - 1), keepdims=False)
        # stage 0 ingests microbatch t; stages 1..s-1 use what arrived
        inp = pin(buffer.at[0].set(feed))
        out = pin(vstage(stacked_params, inp))
        # last stage banks its result at slot t-(s-1) once the fill
        # phase is over
        slot = jnp.clip(t - (s - 1), 0, n_micro - 1)
        prev = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(t >= s - 1, out[s - 1], prev), slot, axis=0)
        # rotate activations one hop around the stage ring
        buffer = pin(jnp.roll(out, 1, axis=0))
        return (buffer, outs), None

    (_, outs), _ = lax.scan(tick, (buffer, outs),
                            jnp.arange(n_micro + s - 1))
    return outs


@functools.lru_cache(maxsize=64)
def _pipeline_program(stage_fn: Callable, mesh: Mesh, axis_name: str,
                      s: int, n_micro: int):
    stage_spec = NamedSharding(mesh, P(axis_name))

    def constrain_tree(t):
        return jax.tree_util.tree_map(
            lambda v: lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(axis_name))), t)

    def pin(v):
        return lax.with_sharding_constraint(v, stage_spec)

    def run(stacked_params, micro):
        # micro: (n_micro, mb, ...); buffer: (s, mb, ...) — the activation
        # each stage processes this tick, stage axis sharded over the ring
        stacked_params = constrain_tree(stacked_params)
        return gpipe_scan(stage_fn, stacked_params, micro, constrain=pin)

    return jax.jit(run)


def pipeline_forward(stage_fn: Callable, stacked_params, x, n_micro: int,
                     mesh: Mesh, axis_name: str = "model"):
    """Run x (batch, ...) through S pipelined stages, microbatched.

    ``stacked_params`` leaves have leading dim S == mesh.shape[axis_name];
    a batch not divisible by ``n_micro`` pads the last microbatch by
    repeating the final row (the padded rows are sliced off the result —
    the r8 pad-don't-raise stance; training losses weight them 0 via the
    pipelined trainer). Output matches running the stages sequentially
    (tested), with stage weights resident on separate devices.
    """
    s = int(mesh.shape[axis_name])
    b = x.shape[0]
    leading = {l.shape[0] for l in jax.tree_util.tree_leaves(stacked_params)}
    if leading != {s}:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(leading)} must equal the "
            f"{axis_name!r} mesh axis size {s} (one stage per device)")
    pad = (n_micro - b % n_micro) % n_micro
    if pad:
        x = jnp.concatenate([x, jnp.repeat(x[-1:], pad, axis=0)], axis=0)
    mb = (b + pad) // n_micro
    micro = x.reshape(n_micro, mb, *x.shape[1:])
    outs = _pipeline_program(stage_fn, mesh, axis_name, s,
                             int(n_micro))(stacked_params, micro)
    return outs.reshape(b + pad, *x.shape[1:])[:b]


def sequential_reference(stage_fn: Callable, params_list: Sequence, x):
    """The semantics pipeline_forward must match (for tests/docs)."""
    h = x
    for p in params_list:
        h = stage_fn(p, h)
    return h
