"""Pipeline parallelism: GPipe-style microbatched stage execution, as GSPMD.

The reference has no pipeline parallelism (SURVEY.md §2.3: PP — absent);
this is the TPU-native extension: S identical-signature stages live on S
devices along a mesh axis and microbatches stream through the stage ring.
The schedule is the classic GPipe fill-drain: n_micro + S - 1 ticks, bubble
fraction (S-1)/(n_micro+S-1).

GSPMD formulation (no per-device mapped functions — ROADMAP item 1): stage params and the
inter-stage activation buffer carry an explicit leading stage axis annotated
``PartitionSpec(axis_name)``; each tick applies the stage function across
the stage axis with ``vmap`` (per device: its own stage's params on the
activation that just arrived) and rotates the buffer one stage with
``jnp.roll`` on the sharded axis — the partitioner lowers the roll to the
ring's collective-permute. The tick loop is a ``lax.scan``, so the whole
pipeline is ONE whole-program-compiled XLA computation (arXiv:1810.09868)
and reverse AD through the scan gives the backward pipeline for free.

API:

    stacked = stack_stage_params([p0, p1, ...])       # leading stage axis
    y = pipeline_forward(stage_fn, stacked, x, n_micro=4,
                         mesh=m.mesh, axis_name="model")

``stage_fn(params_i, x) -> y`` must map activations of a fixed shape to the
same shape (equal-width stages — the standard PP regime; embed/head layers
live outside the pipeline).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(params_list: Sequence):
    """[per-stage pytree] → one pytree with a leading stage axis (shard it
    over the pipeline mesh axis)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


@functools.lru_cache(maxsize=64)
def _pipeline_program(stage_fn: Callable, mesh: Mesh, axis_name: str,
                      s: int, n_micro: int):
    stage_spec = NamedSharding(mesh, P(axis_name))

    def constrain(t):
        return jax.tree_util.tree_map(
            lambda v: lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(axis_name))), t)

    vstage = jax.vmap(stage_fn, in_axes=(0, 0))

    def run(stacked_params, micro):
        # micro: (n_micro, mb, ...); buffer: (s, mb, ...) — the activation
        # each stage processes this tick, stage axis sharded over the ring
        stacked_params = constrain(stacked_params)
        mb_shape = micro.shape[1:]
        buffer = jnp.zeros((s,) + mb_shape, micro.dtype)
        outs = jnp.zeros((n_micro,) + mb_shape, micro.dtype)

        def tick(carry, t):
            buffer, outs = carry
            feed = lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_micro - 1), keepdims=False)
            # stage 0 ingests microbatch t; stages 1..s-1 use what arrived
            inp = lax.with_sharding_constraint(
                buffer.at[0].set(feed), stage_spec)
            out = lax.with_sharding_constraint(
                vstage(stacked_params, inp), stage_spec)
            # last stage banks its result at slot t-(s-1) once the fill
            # phase is over
            slot = jnp.clip(t - (s - 1), 0, n_micro - 1)
            prev = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(t >= s - 1, out[s - 1], prev), slot, axis=0)
            # rotate activations one hop around the stage ring
            buffer = lax.with_sharding_constraint(
                jnp.roll(out, 1, axis=0), stage_spec)
            return (buffer, outs), None

        (_, outs), _ = lax.scan(tick, (buffer, outs),
                                jnp.arange(n_micro + s - 1))
        return outs

    return jax.jit(run)


def pipeline_forward(stage_fn: Callable, stacked_params, x, n_micro: int,
                     mesh: Mesh, axis_name: str = "model"):
    """Run x (batch, ...) through S pipelined stages, microbatched.

    ``stacked_params`` leaves have leading dim S == mesh.shape[axis_name];
    batch must divide n_micro. Output matches running the stages
    sequentially (tested), with stage weights resident on separate devices.
    """
    s = int(mesh.shape[axis_name])
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    leading = {l.shape[0] for l in jax.tree_util.tree_leaves(stacked_params)}
    if leading != {s}:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(leading)} must equal the "
            f"{axis_name!r} mesh axis size {s} (one stage per device)")
    mb = b // n_micro
    micro = x.reshape(n_micro, mb, *x.shape[1:])
    outs = _pipeline_program(stage_fn, mesh, axis_name, s,
                             int(n_micro))(stacked_params, micro)
    return outs.reshape(b, *x.shape[1:])


def sequential_reference(stage_fn: Callable, params_list: Sequence, x):
    """The semantics pipeline_forward must match (for tests/docs)."""
    h = x
    for p in params_list:
        h = stage_fn(p, h)
    return h
