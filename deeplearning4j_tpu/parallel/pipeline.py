"""Pipeline parallelism: GPipe-style microbatched stage execution.

The reference has no pipeline parallelism (SURVEY.md §2.3: PP — absent);
this is the TPU-native extension: S identical-signature stages live on S
devices along a mesh axis, microbatches stream through the ring with
``ppermute`` hops, and every device runs the SAME program (SPMD) — its own
stage's params applied to whatever activation just arrived. The schedule is
the classic GPipe fill-drain: n_micro + S - 1 ticks, bubble fraction
(S-1)/(n_micro+S-1).

API:

    stacked = stack_stage_params([p0, p1, ...])       # leading stage axis
    y = pipeline_forward(stage_fn, stacked, x, n_micro=4,
                         mesh=m.mesh, axis_name="model")

``stage_fn(params_i, x) -> y`` must map activations of a fixed shape to the
same shape (equal-width stages — the standard PP regime; embed/head layers
live outside the pipeline). Differentiable: JAX AD reverses the ppermute
ring, giving the backward pipeline for free.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(params_list: Sequence):
    """[per-stage pytree] → one pytree with a leading stage axis (shard it
    over the pipeline mesh axis)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_forward(stage_fn: Callable, stacked_params, x, n_micro: int,
                     mesh: Mesh, axis_name: str = "model"):
    """Run x (batch, ...) through S pipelined stages, microbatched.

    ``stacked_params`` leaves have leading dim S == mesh.shape[axis_name];
    batch must divide n_micro. Output matches running the stages
    sequentially (tested), with stage weights resident on separate devices.
    """
    s = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    leading = {l.shape[0] for l in jax.tree_util.tree_leaves(stacked_params)}
    if leading != {s}:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(leading)} must equal the "
            f"{axis_name!r} mesh axis size {s} (one stage per device)")
    mb = b // n_micro
    micro = x.reshape(n_micro, mb, *x.shape[1:])

    def local(params, micro):
        # this device's stage params: shard_map leaves the (length-1) sharded
        # leading axis in place — strip it
        params = jax.tree_util.tree_map(lambda v: v[0], params)
        stage = lax.axis_index(axis_name)
        n_ticks = n_micro + s - 1
        # state held between ticks: the activation each device will process
        carry = jnp.zeros((mb,) + micro.shape[2:], micro.dtype)
        outs = jnp.zeros((n_micro, mb) + micro.shape[2:], micro.dtype)
        perm = [(j, (j + 1) % s) for j in range(s)]

        def tick(t, state):
            carry, outs = state
            # stage 0 ingests microbatch t (when in range); others use the
            # activation that arrived from the previous stage
            feed = lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_micro - 1), keepdims=False)
            inp = jnp.where(stage == 0, feed, carry)
            out = stage_fn(params, inp)
            # last stage banks its result at slot t-(s-1)
            slot = jnp.clip(t - (s - 1), 0, n_micro - 1)
            bank = (stage == s - 1) & (t >= s - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(bank, out,
                          lax.dynamic_index_in_dim(outs, slot, keepdims=False)),
                slot, axis=0)
            # rotate activations one hop around the ring
            carry = lax.ppermute(out, axis_name, perm)
            return carry, outs

        _, outs = lax.fori_loop(0, n_ticks, tick, (carry, outs))
        # results live on the last stage; share them (replicated output)
        outs = lax.psum(jnp.where(stage == s - 1, outs, jnp.zeros_like(outs)),
                        axis_name)
        return outs

    out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, micro)
    return out.reshape(b, *x.shape[1:])


def sequential_reference(stage_fn: Callable, params_list: Sequence, x):
    """The semantics pipeline_forward must match (for tests/docs)."""
    h = x
    for p in params_list:
        h = stage_fn(p, h)
    return h
