"""Training masters: parameter averaging + shared (compressed) gradient
training over the mesh, with the Spark-facade entry points.

Reference parity (SURVEY.md §2.2 J18, §3.4):
- ParameterAveragingTrainingMaster.java (dl4j-spark impl/paramavg): each
  worker fits locally for ``averaging_frequency`` minibatches, then params +
  updater state are averaged cluster-wide (Spark aggregate).
- SharedTrainingMaster.java (dl4j-spark-parameterserver): decentralized
  gradient sharing — every step each worker threshold-encodes (grad +
  residual) and exchanges the sparse update over Aeron, applying the sum of
  everyone's quantized updates; residual stays local (call stack §3.4).
- SparkDl4jMultiLayer.java — the user facade.

TPU-native collapse: "workers" are lanes of ONE ``jit``-compiled GSPMD
program — a leading worker axis on the stacked state, sharded
``PartitionSpec("data")`` over the mesh, with the per-worker step vmapped
across it (parallel/gspmd.py; no per-device mapped functions — ROADMAP item 1). Parameter
averaging keeps genuinely divergent per-worker params (the stacked axis)
and averages every N steps with a deterministic pairwise-tree combine —
semantically identical to the Spark master with zero serialization. Shared
training runs the encode → cross-worker mean(quantized) → decode → update
chain inside the step: the partitioner-inserted all-reduce over ICI/DCN
replaces the Aeron mesh, the residual is worker-local state (stacked,
sharded), and the threshold adapts exactly like AdaptiveThresholdAlgorithm.
No Spark, no parameter server process, no message queues — the collective
IS the parameter server.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import gspmd
from deeplearning4j_tpu.parallel.accumulator import EncodedGradientsAccumulator
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.util import telemetry as tm


def _stack_tree(tree, n):
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), tree)


def _batch_masks(ds, model):
    """Sequence masks for one batch, in the shape the model's _loss expects:
    dict name->mask for ComputationGraphs (per-input/per-output), a single
    array for MultiLayerNetworks. The masters shard these alongside the
    batch so masked training matches local fit exactly."""
    from deeplearning4j_tpu.nn.computation_graph import _first_mask, _mask_dict

    if isinstance(model._updaters, dict):  # ComputationGraph
        return (_mask_dict(ds, model.conf.inputs,
                           "features_mask", "features_masks"),
                _mask_dict(ds, model.conf.outputs,
                           "labels_mask", "labels_masks"))
    return (_first_mask(ds, "features_mask", "features_masks"),
            _first_mask(ds, "labels_mask", "labels_masks"))


def _unstack_first(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


class ParameterAveragingTrainingMaster:
    """Sync parameter averaging every ``averaging_frequency`` minibatches."""

    def __init__(self, averaging_frequency: int = 5, mesh: Optional[TrainingMesh] = None):
        self.averaging_frequency = averaging_frequency
        self.mesh = mesh or TrainingMesh(data=len(jax.devices()))
        self._step = None
        self._avg = None

    # -- compiled programs --------------------------------------------------
    def _build(self, model):
        mesh = self.mesh.mesh
        step_fn = model.make_step_fn(weighted=True)
        stacked = NamedSharding(mesh, P("data"))

        def lanes_step(params, states, opts, iteration, x, y, keys, w, fm, lm):
            # every worker fits locally: the per-worker step vmapped over
            # the stacked axis, which the partitioner splits over 'data'
            return jax.vmap(
                step_fn, in_axes=(0, 0, 0, None, 0, 0, 0, 0, 0, 0)
            )(params, states, opts, iteration, x, y, keys, w, fm, lm)

        def average(params, opts, states):
            # deterministic pairwise-tree average, re-stacked so the state
            # keeps its worker-sharded layout for the next local steps
            def avg(t):
                return jax.tree_util.tree_map(
                    lambda v: jax.lax.with_sharding_constraint(
                        jnp.broadcast_to(
                            gspmd.pairwise_mean(v)[None], v.shape),
                        stacked),
                    t)
            return avg(params), avg(opts), avg(states)

        self._step = jax.jit(lanes_step, donate_argnums=(0, 1, 2))
        self._avg = jax.jit(average, donate_argnums=(0, 1, 2))

    # -- orchestration ------------------------------------------------------
    def fit(self, model, iterator, epochs: int = 1):
        if self._step is None:
            self._build(model)
        n = self.mesh.data
        shard = NamedSharding(self.mesh.mesh, P("data"))
        params = jax.tree_util.tree_map(np.asarray, model.params)
        params = jax.device_put(_stack_tree(params, n), shard)
        states = jax.device_put(_stack_tree(
            jax.tree_util.tree_map(np.asarray, model.states), n), shard)
        opts = jax.device_put(_stack_tree(
            jax.tree_util.tree_map(np.asarray, model.opt_states), n), shard)
        since_avg = 0
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                x, y, w, (fm, lm) = self.mesh.pad_lane_batch(
                    ds.features, ds.labels, n,
                    extras=_batch_masks(ds, model))
                model._rng_key, sub = jax.random.split(model._rng_key)
                keys = jax.device_put(
                    jax.random.split(sub, n), shard)
                params, states, opts, loss = self._step(
                    params, states, opts, jnp.asarray(model.iteration),
                    x, y, keys, w, fm, lm)
                model.iteration += 1
                model.score_value = float(jnp.mean(loss))
                since_avg += 1
                if since_avg >= self.averaging_frequency:
                    params, opts, states = self._avg(params, opts, states)
                    since_avg = 0
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration, model.epoch)
            model.epoch += 1
            # NO on_epoch_end dispatch here (unlike SharedTrainingMaster
            # below): the per-replica param stacks live in this loop's
            # locals until fit() returns, so an epoch-end checkpoint
            # listener would silently save pre-fit state. Supervise
            # SharedTrainingMaster (which syncs back per epoch) or wrap
            # ElasticTrainer around ParallelWrapper instead.
        if since_avg:
            params, opts, states = self._avg(params, opts, states)
        model.params = jax.tree_util.tree_map(np.asarray, _unstack_first(params))
        model.states = jax.tree_util.tree_map(np.asarray, _unstack_first(states))
        model.opt_states = jax.tree_util.tree_map(np.asarray, _unstack_first(opts))
        model._train_step = None  # params left host-side; rejit on next fit
        return model


class SharedTrainingMaster:
    """Every-step compressed gradient sharing with error feedback."""

    def __init__(self, threshold: float = 1e-3, mesh: Optional[TrainingMesh] = None,
                 accumulator: Optional[EncodedGradientsAccumulator] = None):
        self.mesh = mesh or TrainingMesh(data=len(jax.devices()))
        self.accumulator = accumulator or EncodedGradientsAccumulator()
        self.initial_threshold = threshold
        self._step = None
        #: last step's wire accounting (device scalars; same convention as
        #: ParallelWrapper.compression_stats)
        self.last_stats = None

    def _build(self, model):
        acc = self.accumulator
        lane_vg = gspmd.make_lane_value_and_grad(model)

        def lane(params, states, residual, threshold, iteration,
                 x, y, key, w, fm, lm):
            (loss, _), (new_states, grads) = lane_vg(
                params, states, x, y, key, w, fm, lm)
            quant, new_res, new_thr, _ratio = acc.encode(
                grads, residual, threshold, iteration)
            return loss, new_states, quant, new_res, new_thr

        def step(params, states, opts, residual, threshold, iteration,
                 x, y, keys, w, fm, lm):
            # per-worker lanes: params/states broadcast, residual/threshold
            # and the batch ride the stacked worker axis (sharded 'data')
            loss_l, states_l, quant_l, new_res, new_thr = jax.vmap(
                lane, in_axes=(None, None, 0, 0, None, 0, 0, 0, 0, 0, 0)
            )(params, states, residual, threshold, iteration,
              x, y, keys, w, fm, lm)
            # the all-reduce of quantized updates IS the parameter server;
            # pairwise-tree mean keeps the combine deterministic
            shared = gspmd.tree_pairwise_mean(quant_l)
            new_params, new_opts = gspmd.apply_updaters(
                model, params, shared, opts, iteration)
            # non-trainable state (batchnorm stats) kept consistent by mean
            new_states = gspmd.combine_states(states_l)
            # deterministic wire accounting (ONE byte-math definition,
            # shared with the wrapper's compressed path): one worker's
            # sparse threshold payload vs its dense fp32 payload
            from deeplearning4j_tpu.parallel.compression import (
                sparse_wire_bytes)

            q_leaves = jax.tree_util.tree_leaves(quant_l)
            workers = float(q_leaves[0].shape[0]) if q_leaves else 1.0
            nnz = sum(jnp.sum(q != 0).astype(jnp.float32)
                      for q in q_leaves)
            dense = float(sum(
                int(np.prod(q.shape[1:] or (1,)))
                * jnp.dtype(q.dtype).itemsize for q in q_leaves))
            wire = sparse_wire_bytes(len(q_leaves), nnz, workers)
            stats = {"wire_bytes": wire,
                     "dense_bytes": jnp.asarray(dense, jnp.float32),
                     "ratio": wire / jnp.asarray(dense, jnp.float32)}
            return (new_params, new_states, new_opts, new_res, new_thr,
                    gspmd.pairwise_mean(loss_l), stats)

        self._step = jax.jit(step, donate_argnums=(0, 1, 2, 3))

    def fit(self, model, iterator, epochs: int = 1):
        if self._step is None:
            self._build(model)
        n = self.mesh.data
        mesh = self.mesh.mesh
        shard = NamedSharding(mesh, P("data"))
        rep = NamedSharding(mesh, P())
        params = jax.device_put(model.params, rep)
        states = jax.device_put(model.states, rep)
        opts = jax.device_put(model.opt_states, rep)
        residual = jax.device_put(
            _stack_tree(self.accumulator.init_residual(model.params), n), shard)
        threshold = jax.device_put(
            jnp.full((n,), self.initial_threshold, jnp.float32), shard)
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                x, y, w, (fm, lm) = self.mesh.pad_lane_batch(
                    ds.features, ds.labels, n,
                    extras=_batch_masks(ds, model))
                model._rng_key, sub = jax.random.split(model._rng_key)
                keys = jax.device_put(jax.random.split(sub, n), shard)
                (params, states, opts, residual, threshold, loss,
                 self.last_stats) = self._step(
                    params, states, opts, residual, threshold,
                    jnp.asarray(model.iteration), x, y, keys, w, fm, lm)
                model.iteration += 1
                model.score_value = float(loss)
                tm.counter("train.steps_total", model="shared_master")
                if tm.enabled():
                    tm.gauge("parallel.allreduce_wire_bytes",
                             float(self.last_stats["wire_bytes"]),
                             source="shared_master")
                    tm.gauge("parallel.allreduce_compression_ratio",
                             float(self.last_stats["ratio"]),
                             source="shared_master")
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration, model.epoch)
            # epoch-boundary state sync-back: params here are complete
            # replicated arrays, so handing the references to the model
            # costs nothing and makes a mid-run checkpoint (ElasticTrainer /
            # ShardedCheckpointListener riding on_epoch_end) save REAL
            # state — before this, a SIGKILL mid-fit lost every epoch.
            # NOTE: the next epoch's first step DONATES these buffers, so
            # the window to read model.params is the epoch boundary itself
            # (exactly where on_epoch_end fires); mid-epoch readers like
            # the health monitor's probe already tolerate deleted buffers
            model.params, model.states, model.opt_states = params, states, opts
            model.epoch += 1
            for lst in model.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(model)
        # after >=1 epoch this re-binds the refs the loop's sync-back just
        # set (intentional no-op); it exists for epochs=0, where the loop —
        # and its sync-back — never runs
        model.params, model.states, model.opt_states = params, states, opts
        return model


class SparkDl4jMultiLayer:
    """User facade (SparkDl4jMultiLayer.java parity): wraps a network and a
    TrainingMaster. The SparkContext argument is accepted and ignored —
    there is no Spark; the mesh is the cluster."""

    def __init__(self, sc, network, training_master):
        self.network = network
        self.training_master = training_master

    def fit(self, iterator, epochs: int = 1):
        return self.training_master.fit(self.network, iterator, epochs=epochs)


SparkComputationGraph = SparkDl4jMultiLayer  # same facade over ComputationGraph
