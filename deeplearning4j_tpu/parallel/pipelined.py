"""PipelinedTrainer — full 3D (data x tensor x pipe) parallel ``fit()``.

ROADMAP item 1's composition: the pieces all existed — the GPipe scan
(parallel/pipeline.py, open since r12), Megatron-style TP annotation
(``mesh.tensor_shard_params``), ZeRO sharded weight updates
(arXiv:2004.13336), the encoded gradient collectives and the fused donated
optimizer — but no ``fit()`` path ever placed one model across a
(data, model, pipe) mesh. This module is that trainer:

- **Stage partition.** The net's layers split into ``pipe_stages``
  structurally-identical stages at the r6 ``stage_boundary()`` markers
  (``conf.remat_stages``), with an optional preamble (embed) chunk before
  and postamble chunk after — the loss head always runs outside the
  pipeline, on the whole lane batch, exactly like the unpipelined loss.
- **Stacked stage state.** Per-stage params/optimizer moments stack into
  one pytree with a leading S axis placed ``P('pipe', ...)`` — each pipe
  group holds ONLY its own stages' weights, which is what makes
  param+optimizer bytes/device ≈ 1/pipe_stages (the CI-gated
  ``pipeline_param_bytes_per_device`` contract) and "model too big for
  one chip" a config knob. Tensor-parallel rules compose by appending the
  'model' axis after 'pipe' on matching stage leaves.
- **The schedule.** Each data lane's batch splits into ``n_micro``
  microbatches streamed through the GPipe fill-drain scan
  (:func:`~deeplearning4j_tpu.parallel.pipeline.gpipe_scan`); reverse AD
  through the scan threads the backward pass through the SAME rolled
  stage buffer and accumulates per-stage gradients across microbatches —
  microbatch gradient accumulation without a hand-written backward. The
  whole step stays the r12 three-jit lane staging (lanes / combine /
  update), so the deterministic-lane contract carries over
  (docs/DISTRIBUTED.md#pipeline-parallelism for the exact boundary: a
  data-axis fold change is bit-identical for a FIXED pipe placement;
  changing the pipe placement itself re-fuses kernels and wobbles tails
  ~1 ulp — the r12/r15 FMA-contraction class). ``pipeline_bubble_fraction``
  is computed from the schedule — (S-1)/(n_micro+S-1) — not timed (the r6
  honest-CPU stance).
- **DP-axis composition.** The combine stage is the wrapper's: pairwise
  deterministic lane combine, optional ``grad_compression``
  encode→all-reduce(quantized)→decode with the error-feedback residual as
  worker-sharded resident state, and ZeRO layout constraints on the
  optimizer state. A ``fused_update`` model gets a PIPELINE-LAYOUT
  :class:`~deeplearning4j_tpu.nn.updaters.FusedUpdateEngine` whose flat
  buffers treat each STACKED stage tree as single leaves — flatten and
  unflatten are reshape-only, never a slice of the pipe-sharded stage
  axis (this jaxlib's SPMD partitioner mis-lowers such slices on
  multi-axis meshes — pinned by
  tests/test_pipeline_fit.py::test_partitioner_slice_hazard_documented);
  the engine's resident masters convert bit-exactly to/from the net's
  model-layout engine state at checkpoint boundaries (element
  permutation, elementwise rules are position-independent — the r14
  argument), so the resync invariant and checkpoint compatibility hold.
- **Elastic / checkpoint.** The trainer keeps the canonical model-layout
  state on the wrapped net in sync at checkpoint boundaries
  (:meth:`sync_model` — stack/unstack is bit-exact), so
  ``ShardedCheckpointer``/``ElasticTrainer``/``ModelSerializer`` carry the
  stacked stage state through SIGKILL + regroup unchanged;
  :meth:`reshard` re-places onto the survivors' mesh.

Activation checkpointing: the configured r6 ``remat_policy`` wraps each
stage's body in ``jax.checkpoint`` — per-microbatch recompute instead of
storing every tick's activations.

Limits (loud, not silent): masked/TBPTT batches, and stages holding
floating-point layer STATE (batchnorm running stats — the pipeline would
update them per-microbatch in schedule order) are rejected at
construction. ComputationGraphs are supported when the graph is a linear
single-input chain of layer nodes; general DAGs raise.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.parallel import gspmd
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.parallel.pipeline import bubble_fraction, gpipe_scan
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.util import cost_model as cmod
from deeplearning4j_tpu.util import telemetry as tm


# ---------------------------------------------------------------------------
# stage partition
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StagePartition:
    """The net's layers split for pipelining. ``pre``/``post`` are
    [(key, layer)] run outside the pipeline on the whole lane batch;
    ``stages`` is S lists of per-stage (key, layer) pairs, structurally
    identical; ``head`` is the loss layer. Keys are layer indices (MLN) or
    node names (linear-chain CG)."""

    pre: List[Tuple[Any, Any]]
    stages: List[List[Tuple[Any, Any]]]
    post: List[Tuple[Any, Any]]
    head: Tuple[Any, Any]
    order: List[Any]          # every key in original layer order (incl head)

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    @property
    def per_stage(self) -> int:
        return len(self.stages[0])

    def pp_keys(self) -> List[str]:
        """The pipeline-layout dict keys, in a stable order."""
        return ([f"pre:{i}" for i in range(len(self.pre))]
                + [f"stage:{j}" for j in range(self.per_stage)]
                + [f"post:{i}" for i in range(len(self.post))]
                + ["head"])


def _linear_chain_items(model) -> List[Tuple[str, Any]]:
    """A ComputationGraph as an ordered (name, layer) chain, or a loud
    explanation of why it cannot pipeline."""
    conf = model.conf
    if len(conf.inputs) != 1 or len(conf.outputs) != 1:
        raise ValueError(
            "pipelined fit() supports single-input single-output "
            f"ComputationGraphs (got {len(conf.inputs)} inputs / "
            f"{len(conf.outputs)} outputs)")
    prev = conf.inputs[0]
    items = []
    for n in model.topo:
        if not n.is_layer:
            raise ValueError(
                f"pipelined fit() needs a linear chain of LAYER nodes; "
                f"{n.name!r} is a {type(n.node).__name__} vertex")
        if list(n.inputs) != [prev]:
            raise ValueError(
                f"pipelined fit() needs a linear chain: node {n.name!r} "
                f"consumes {n.inputs} (expected [{prev!r}])")
        items.append((n.name, n.node))
        prev = n.name
    if prev != conf.outputs[0]:
        raise ValueError("the chain's last node must be the graph output")
    return items


def _items_and_bounds(model) -> Tuple[List[Tuple[Any, Any]], List[int]]:
    """(ordered (key, layer) items incl. the head, stage-start indices
    derived from the r6 stage_boundary() markers)."""
    conf = model.conf
    if hasattr(model, "topo"):  # ComputationGraph
        items = _linear_chain_items(model)
        names = [k for k, _ in items]
        bounds = []
        for name in conf.remat_stages or ():
            if name not in names:
                raise ValueError(f"stage boundary {name!r} is not a node")
            # a named node ENDS a stage: the next node starts one
            bounds.append(names.index(name) + 1)
    else:
        items = list(enumerate(model.layers))
        n = len(items)
        bounds = []
        for b in sorted(set(conf.remat_stages or ())):
            if not 0 < b < n:
                raise ValueError(
                    f"stage boundary {b} out of range (1..{n - 1})")
            bounds.append(int(b))
    return items, sorted(set(bounds))


def _updater_sig(model, key) -> str:
    u = model._updaters[key]
    try:
        return repr(u.to_dict())
    except Exception:  # noqa: BLE001 — exotic updater: identity fallback
        return repr(u)


def _layer_cfg(lyr) -> str:
    """A layer's full config signature minus its display name — the
    identity two pipeline stages must share (the stage vmap runs stage 0's
    layer code on every stage's params)."""
    try:
        d = dict(lyr.to_dict())
    except Exception:  # noqa: BLE001 — config-less layer: type identity only
        return type(lyr).__name__
    d.pop("name", None)
    return repr(sorted(d.items(), key=lambda kv: kv[0]))


def _leaf_sig(tree):
    return [(jax.tree_util.keystr(p), tuple(np.shape(l)),
             str(np.asarray(l).dtype) if not hasattr(l, "dtype")
             else str(l.dtype))
            for p, l in jax.tree_util.tree_flatten_with_path(tree)[0]]


def stage_partition(model, pipe_stages: int) -> StagePartition:
    """Partition the net at its ``stage_boundary()`` markers into
    ``pipe_stages`` structurally-identical pipeline stages (plus optional
    preamble/postamble chunks and the always-outside loss head). Loud on
    every violated precondition — a partition that cannot hold the
    equal-width stage contract must never train silently wrong."""
    S = int(pipe_stages)
    if S < 2:
        raise ValueError(f"pipe_stages must be >= 2, got {S}")
    items, bounds = _items_and_bounds(model)
    if len(items) < 2:
        raise ValueError("pipelining needs at least one body layer + head")
    head = items[-1]
    if not hasattr(head[1], "compute_loss"):
        raise ValueError("last layer must be an OutputLayer/LossLayer")
    body = items[:-1]
    m = len(body)
    bounds = [b for b in bounds if 0 < b < m]  # a bound at the head is inert
    chunks, start = [], 0
    for b in bounds:
        chunks.append(body[start:b])
        start = b
    chunks.append(body[start:])
    if len(chunks) < S:
        raise ValueError(
            f"stage_boundary() markers yield {len(chunks)} chunks; "
            f"pipe_stages={S} needs at least {S} (mark more boundaries)")

    def identical(cands: List[List[Tuple[Any, Any]]]) -> Optional[str]:
        L = len(cands[0])
        if any(len(c) != L for c in cands):
            return f"stage layer counts differ: {[len(c) for c in cands]}"
        for j in range(L):
            ref_k, ref_l = cands[0][j]
            for c in cands[1:]:
                k, lyr = c[j]
                if type(lyr) is not type(ref_l):
                    return (f"stage layer {j}: {type(ref_l).__name__} vs "
                            f"{type(lyr).__name__}")
                # FULL config equality (activation, kernel, stride, dropout,
                # ... — everything but the display name): the stage vmap
                # applies stage 0's layer OBJECTS to every stage's params,
                # so any config drift between stages would silently compute
                # the wrong model
                if _layer_cfg(lyr) != _layer_cfg(ref_l):
                    return (f"stage layer {j}: layer configs differ "
                            f"({ref_k!r} vs {k!r}: {_layer_cfg(ref_l)} vs "
                            f"{_layer_cfg(lyr)})")
                if _leaf_sig(model.params[k]) != \
                        _leaf_sig(model.params[ref_k]):
                    return (f"stage layer {j}: param shapes/dtypes differ "
                            f"({ref_k!r} vs {k!r})")
                if _updater_sig(model, k) != _updater_sig(model, ref_k):
                    return (f"stage layer {j}: updaters differ "
                            f"({ref_k!r} vs {k!r})")
        return None

    reasons = []
    for pre_k in range(0, len(chunks) - S + 1):
        post_k = len(chunks) - S - pre_k
        cands = chunks[pre_k:pre_k + S]
        why = identical(cands)
        if why is None:
            part = StagePartition(
                pre=[kv for c in chunks[:pre_k] for kv in c],
                stages=[list(c) for c in cands],
                post=[kv for c in chunks[pre_k + S:] for kv in c],
                head=head,
                order=[k for k, _ in items])
            _validate_stage_state(model, part)
            return part
        reasons.append(f"pre={pre_k}/post={post_k}: {why}")
    raise ValueError(
        f"no {S} consecutive stage_boundary() chunks are structurally "
        f"identical (equal layer stack, param shapes, updaters): "
        + "; ".join(reasons))


def _validate_stage_state(model, part: StagePartition):
    """Stage layers must carry no floating-point layer STATE: the pipeline
    applies each stage once per microbatch tick, so running statistics
    (batchnorm) would advance in schedule order — silently different from
    the unpipelined fit. Reject loudly instead."""
    for chunk in part.stages:
        for k, lyr in chunk:
            for leaf in jax.tree_util.tree_leaves(model.states[k]):
                if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                    raise ValueError(
                        f"stage layer {k!r} ({type(lyr).__name__}) holds "
                        "floating-point state (running statistics); "
                        "pipeline stages must be stateless — keep such "
                        "layers in the preamble/postamble chunks")


# ---------------------------------------------------------------------------
# the trainer
# ---------------------------------------------------------------------------


class PipelinedTrainer(ParallelWrapper):
    """``ParallelWrapper`` whose step places the model across the full
    (data, model, pipe) mesh (module docstring). Same ``fit(iterator,
    epochs)`` / ``step_batch`` / ``end_epoch`` / ``reshard`` surface — the
    elastic supervisor (parallel/elastic.py) drives it unchanged.

        conf = (builder.pipe_stages(4).n_micro(8).list()
                ...  .stage_boundary() ... )
        net = MultiLayerNetwork(conf).init()
        pt = PipelinedTrainer(net, mesh=TrainingMesh(data=2, model=2,
                                                     pipe=2))
        pt.fit(iterator, epochs=3)

    ``tp_rules``: Megatron-style [(regex, PartitionSpec)] matched against
    each layer's WITHIN-LAYER param key path (the
    ``mesh.tensor_shard_params`` convention); matching stage leaves get
    ``P('pipe', *spec)``, preamble/post/head leaves get the spec as-is.
    """

    def __init__(self, model, mesh: Optional[TrainingMesh] = None,
                 pipe_stages: Optional[int] = None,
                 n_micro: Optional[int] = None,
                 tp_rules=None, **kw):
        conf = model.conf
        S = int(pipe_stages if pipe_stages is not None
                else getattr(conf, "pipe_stages", 0) or 0)
        M = int(n_micro if n_micro is not None
                else getattr(conf, "n_micro", 0) or 0)
        if S < 2:
            raise ValueError(
                "PipelinedTrainer needs pipe_stages >= 2 (constructor arg, "
                "conf.pipe_stages, or DL4J_TPU_PIPE_STAGES)")
        self.pipe_stages = S
        self.n_micro = M if M >= 1 else S
        if getattr(conf, "tbptt_length", 0):
            raise NotImplementedError(
                "pipelined fit() does not support TBPTT segments; unset "
                "tbptt_length or use ParallelWrapper")
        if mesh is None:
            mesh = TrainingMesh()
        if S % mesh.pipe:
            raise ValueError(
                f"pipe mesh axis ({mesh.pipe}) must divide pipe_stages "
                f"({S}) — each pipe group holds a whole number of stages")
        super().__init__(model, mesh=mesh, **kw)
        self._uses_lanes = True  # the pipelined step is always lane-staged
        if self._compressor is not None:
            self._compressor.exchange_axis(self.replicas)
        self.tp_rules = list(tp_rules or [])
        if not model.params:
            raise ValueError("init() the network before PipelinedTrainer")
        self.part = stage_partition(model, S)
        head_lyr = self.part.head[1]
        if "weights" not in _sig_params(head_lyr.compute_loss):
            raise ValueError(
                f"loss head {type(head_lyr).__name__} does not accept "
                "per-example weights — required for exact ragged-batch "
                "padding (the r8 0/1-weight machinery)")
        self._is_graph = isinstance(model._updaters, dict)
        self._pp: Optional[dict] = None
        self._pp_engine = None       # pipeline-layout FusedUpdateEngine
        self._pp_param_specs = None
        self._pp_state_specs = None
        self._pp_opt_specs = None
        self._model_ids: Optional[tuple] = None
        #: stage-position updaters (validated identical across stages)
        self._stage_updaters = [
            model._updaters[k] for k, _ in self.part.stages[0]]
        #: {pp key -> updater} for the pipeline-layout fused engine
        self._pp_updaters = {}
        for i, (k, _) in enumerate(self.part.pre):
            self._pp_updaters[f"pre:{i}"] = model._updaters[k]
        for j in range(self.part.per_stage):
            self._pp_updaters[f"stage:{j}"] = self._stage_updaters[j]
        for i, (k, _) in enumerate(self.part.post):
            self._pp_updaters[f"post:{i}"] = model._updaters[k]
        self._pp_updaters["head"] = model._updaters[self.part.head[0]]
        # layer-order index of every key (RNG key assignment matches the
        # unpipelined per-layer split, so dropout-free nets are comparable
        # and dropout nets draw from the same per-layer streams)
        self._key_index = {k: i for i, k in enumerate(self.part.order)}

    # ------------------------------------------------------------ tree plumbing
    def _stack_tree(self, model_tree):
        """Model-layout (per-layer list/dict) → pipeline layout: a flat
        dict keyed ``pre:<i>`` / ``stage:<j>`` (leading S axis) /
        ``post:<i>`` / ``head``."""
        part = self.part
        # pass-through sections COPY (jnp.array): the step jits donate the
        # pipeline-layout buffers, so pp leaves must never alias the net's
        # own arrays (jnp.stack already copies the stage leaves)
        fresh = lambda t: jax.tree_util.tree_map(jnp.array, t)  # noqa: E731
        out = {}
        for i, (k, _) in enumerate(part.pre):
            out[f"pre:{i}"] = fresh(model_tree[k])
        for j in range(part.per_stage):
            per_stage = [model_tree[chunk[j][0]] for chunk in part.stages]
            out[f"stage:{j}"] = jax.tree_util.tree_map(
                lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                *per_stage)
        for i, (k, _) in enumerate(part.post):
            out[f"post:{i}"] = fresh(model_tree[k])
        out["head"] = fresh(model_tree[part.head[0]])
        return out

    def _unstack_tree(self, pp_tree, like_model_tree):
        """Pipeline layout → model layout (same container type as
        ``like_model_tree``); stack/unstack round trips bit-exactly.
        Host-side only (eager slicing of the stage axis is fine; the
        IN-JIT slice is the partitioner hazard the fused path avoids)."""
        part = self.part
        # COPY out (jnp.array): the model-layout views must survive the
        # next step's donation of the pipeline-layout buffers they came
        # from (a slice of a sharded array can alias the parent's shards)
        fresh = lambda t: jax.tree_util.tree_map(jnp.array, t)  # noqa: E731
        out = dict(like_model_tree) if isinstance(like_model_tree, dict) \
            else list(like_model_tree)
        for i, (k, _) in enumerate(part.pre):
            out[k] = fresh(pp_tree[f"pre:{i}"])
        for si, chunk in enumerate(part.stages):
            for j in range(part.per_stage):
                out[chunk[j][0]] = jax.tree_util.tree_map(
                    lambda v, _si=si: jnp.array(v[_si]),
                    pp_tree[f"stage:{j}"])
        for i, (k, _) in enumerate(part.post):
            out[k] = fresh(pp_tree[f"post:{i}"])
        out[part.head[0]] = fresh(pp_tree["head"])
        return out

    # ------------------------------------------- fused-engine state conversion
    def _convert_buffers(self, bufs, src_engine, dst_engine, to_pp: bool):
        """Convert a full set of per-group flat buffers between the net's
        model-layout engine and the pipeline-layout engine: unflatten into
        leaves, relayout (stack/unstack — a pure element permutation), and
        reflatten. Bit-exact, and deliberately HOST-side (checkpoint
        cadence): the buffers pull to numpy first, because eagerly slicing
        a mesh-sharded buffer trips the same jaxlib partitioner bug the
        in-jit path avoids (test_partitioner_slice_hazard_documented —
        observed as strided element reads on the data-sharded master)."""
        from deeplearning4j_tpu.ops import updater_ops as uo

        bufs = [np.asarray(jax.device_get(b)) for b in bufs]
        out_leaves = {k: [None] * src_engine._treedefs[k].num_leaves
                      for k in src_engine.keys}
        for g, buf in zip(src_engine.groups, bufs):
            uo.unflatten_group(g, buf, out_leaves)
        src_tree = {k: jax.tree_util.tree_unflatten(
            src_engine._treedefs[k], out_leaves[k]) for k in src_engine.keys}
        if to_pp:
            dst_tree = self._stack_tree(src_tree)
        else:
            dst_tree = self._unstack_tree(src_tree, self.model.params)
            if not isinstance(self.model.params, dict):
                dst_tree = {i: t for i, t in enumerate(dst_tree)}
        dst_leaves = {k: list(jax.tree_util.tree_leaves(dst_tree[k]))
                      for k in dst_engine.keys}
        return [uo.flatten_group(g, dst_leaves) for g in dst_engine.groups]

    def _convert_fused_state(self, state, src_engine, dst_engine,
                             to_pp: bool):
        """FusedUpdateEngine state (resident masters + per-rule moments +
        loss-scale automaton) converted between layouts. Matched by (rule
        signature, dtype) — the grouping key, unique per engine."""
        from deeplearning4j_tpu.ops import updater_ops as uo

        def gkey(g):
            return (uo.updater_signature(g.updater), str(jnp.dtype(g.dtype)))

        src_idx = {gkey(g): i for i, g in enumerate(src_engine.groups)}
        src_states = state["groups"]
        masters = self._convert_buffers(
            [gs["master"] for gs in src_states], src_engine, dst_engine,
            to_pp)
        # opt moments, batched by SLOT: each conversion call is closed per
        # group (a group's leaves never cross into another's buffers), so
        # slot s of every group converts in ONE pass — O(max slots) calls,
        # not one full G-group conversion per leaf
        src_opt_leaves = [jax.tree_util.tree_leaves(gs["opt"])
                          for gs in src_states]
        n_slots = max((len(ls) for ls in src_opt_leaves), default=0)
        slot_out = []
        for s in range(n_slots):
            bufs = [ls[s] if s < len(ls) else
                    np.zeros((src_engine.groups[i].total,), np.float32)
                    for i, ls in enumerate(src_opt_leaves)]
            slot_out.append(self._convert_buffers(bufs, src_engine,
                                                  dst_engine, to_pp))
        new_groups = []
        for dj, dg in enumerate(dst_engine.groups):
            si = src_idx[gkey(dg)]
            sgs = src_states[si]
            treedef = jax.tree_util.tree_structure(sgs["opt"])
            n = len(src_opt_leaves[si])
            new_opt = jax.tree_util.tree_unflatten(
                treedef, [slot_out[s][dj] for s in range(n)])
            new_groups.append({"opt": new_opt, "master": masters[dj]})
        new_state = {"groups": new_groups}
        if "scale" in state:
            new_state["scale"] = state["scale"]
        return new_state

    # ------------------------------------------------------------ placement
    def _tp_spec_for(self, within_key: str, shape, lead_stage: bool):
        """TP PartitionSpec for one leaf (None = no rule matched/invalid).
        Stage leaves check divisibility on their UNSTACKED dims."""
        off = 1 if lead_stage else 0
        for pat, spec in self.tp_rules:
            if not re.search(pat, within_key):
                continue
            ok = True
            for d, ax in enumerate(spec):
                if ax is None:
                    continue
                size = self.mesh.mesh.shape[ax]
                if d + off >= len(shape) or shape[d + off] % size:
                    ok = False
                    break
            return tuple(spec) if ok else None
        return None

    def _leaf_specs(self, pp_tree, kind: str):
        """NamedSharding tree for a pipeline-layout pytree. ``kind``:
        'param'/'state' (stage leaves P('pipe', *tp)) or 'opt' (adds ZeRO
        'data' sharding on the first divisible non-stage dim)."""
        mesh = self.mesh
        d = mesh.data
        zero = kind == "opt" and self.zero_optimizer

        def section(tree, lead_stage: bool):
            def spec_of(path, leaf):
                shape = tuple(np.shape(leaf))
                key = jax.tree_util.keystr(path)
                axes: List[Optional[str]] = [None] * len(shape)
                if lead_stage and shape:
                    axes[0] = "pipe"
                if kind in ("param", "state"):
                    tp = self._tp_spec_for(key, shape, lead_stage)
                    if tp is not None:
                        off = 1 if lead_stage else 0
                        for di, ax in enumerate(tp):
                            if ax is not None and di + off < len(axes):
                                axes[di + off] = ax
                if zero and int(np.prod(shape or (0,))) >= 1024:
                    start = 1 if lead_stage else 0
                    for di in range(start, len(shape)):
                        if axes[di] is None and shape[di] \
                                and shape[di] % d == 0:
                            axes[di] = "data"
                            break
                return NamedSharding(mesh.mesh, P(*axes))

            flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
            return jax.tree_util.tree_unflatten(
                treedef, [spec_of(p, l) for p, l in flat])

        return {k: section(v, k.startswith("stage:"))
                for k, v in pp_tree.items()}

    def _model_ids_now(self):
        """Identity fingerprint of the net's state for external-write
        detection: ids of every LEAF, not just the containers — transfer
        ``copy_back`` and the Keras/ONNX importers write INTO the existing
        list/dict (``net.params[i] = ...``), which leaves the container id
        unchanged. jax arrays are immutable, so any real write replaces
        leaf references and shows up here."""
        m = self.model
        return tuple(
            id(leaf)
            for tree in (m.params, m.states, m.opt_states)
            for leaf in jax.tree_util.tree_leaves(tree))

    def _build_pp_state(self):
        """(Re)build the placed pipeline-layout device state from the
        model-layout state currently on the net — at first build, and after
        any external write (checkpoint restore, rollback, transfer)."""
        model = self.model
        engine = getattr(model, "_fused", None)
        pp_params = self._stack_tree(model.params)
        pp_states = self._stack_tree(model.states)
        if engine is not None:
            if self._pp_engine is None:
                conf = model.conf
                self._pp_engine = upd.FusedUpdateEngine(
                    self._pp_updaters, pp_params,
                    loss_scale=getattr(conf, "loss_scale", "none"),
                    loss_scale_value=getattr(conf, "loss_scale_value",
                                             2.0 ** 15),
                    growth_interval=getattr(conf, "loss_scale_growth",
                                            2000))
            # model-layout engine state → pipeline-layout engine state
            # (bit-exact element permutation; masters stay resident)
            pp_opts = self._convert_fused_state(
                model.opt_states, engine, self._pp_engine, to_pp=True)
        else:
            pp_opts = self._stack_tree(model.opt_states)
        if self.mesh.n_devices > 1:
            self._pp_param_specs = self._leaf_specs(pp_params, "param")
            self._pp_state_specs = self._leaf_specs(pp_states, "state")
            if engine is not None:
                self._zero_specs = (gspmd.zero_shardings(
                    self.mesh.mesh, pp_opts) if self.zero_optimizer else None)
                self._pp_opt_specs = self._zero_specs \
                    if self._zero_specs is not None else \
                    jax.tree_util.tree_map(
                        lambda _: self.mesh.replicated(), pp_opts)
            else:
                self._pp_opt_specs = self._leaf_specs(pp_opts, "opt")
                self._zero_specs = None
            pp_params = gspmd.place_tree(pp_params, self._pp_param_specs)
            pp_states = gspmd.place_tree(pp_states, self._pp_state_specs)
            pp_opts = gspmd.place_tree(pp_opts, self._pp_opt_specs)
        else:
            self._pp_param_specs = self._pp_state_specs = None
            self._pp_opt_specs = self._zero_specs = None
            asarr = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa: E731
            pp_params, pp_states, pp_opts = (asarr(pp_params),
                                             asarr(pp_states), asarr(pp_opts))
        self._pp = {"params": pp_params, "states": pp_states,
                    "opts": pp_opts}
        self._model_ids = self._model_ids_now()

    def sync_model(self):
        """Write the live pipeline-layout state back to the net in MODEL
        layout (unstack — bit-exact), so checkpoints / the serializer / the
        elastic publish seam see current weights. Fused models additionally
        convert the pipeline-layout engine state back to the net engine's
        buffer layout — params and resident masters move TOGETHER through
        both conversions (the resync invariant, docs/KERNELS.md)."""
        if self._pp is None:
            return
        model = self.model
        # host-pull before unstacking: eager slices of mesh-sharded arrays
        # can trip the pinned partitioner bug (the _convert_buffers note);
        # numpy slicing is unconditionally exact, and sync runs at
        # checkpoint cadence where the checkpointer host-snapshots anyway
        host = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: np.asarray(jax.device_get(a)), t)
        model.params = self._unstack_tree(host(self._pp["params"]),
                                          model.params)
        model.states = self._unstack_tree(host(self._pp["states"]),
                                          model.states)
        engine = getattr(model, "_fused", None)
        if engine is not None:
            model.opt_states = self._convert_fused_state(
                self._pp["opts"], self._pp_engine, engine, to_pp=False)
        else:
            model.opt_states = self._unstack_tree(host(self._pp["opts"]),
                                                  model.opt_states)
        self._model_ids = self._model_ids_now()

    def _adopt_model_state(self):
        """Identity-checked per step: when someone swapped the net's state
        from outside the step loop (checkpoint restore, rollback,
        transfer), re-stack and re-place; free when nothing changed."""
        if self._pp is None or self._model_ids != self._model_ids_now():
            self._build_pp_state()

    # ------------------------------------------------------------------ build
    def _build(self):
        model = self.model
        if not model.params:
            raise ValueError("model must be init()ed first")
        self._build_pp_state()
        if self._compressor is not None:
            self._place_compression_state()
        self._sharded_step = self._build_pipe_step()
        self._publish_layout()

    def _comp_template(self):
        """ONE worker's gradient template for the compression state: the
        PIPELINE-layout engine's flat buffers (the encode then runs on
        exactly what ZeRO reduce-scatters), or the pipeline-layout gradient
        tree (the stacked stage leaves are what the lanes emit)."""
        model = self.model
        engine = getattr(model, "_fused", None)
        if engine is not None:
            return [np.zeros((g.total,), np.float32)
                    for g in self._pp_engine.groups]
        f32 = lambda p: np.zeros(np.shape(p), np.float32)  # noqa: E731
        return jax.tree_util.tree_map(f32, self._pp["params"])

    # ----------------------------------------------------------- lane body
    def _make_pipe_lane_vg(self):
        model = self.model
        part = self.part
        M, L = self.n_micro, part.per_stage
        scaled = gspmd._lane_scaled(model)
        n_keys = len(part.order)
        key_index = self._key_index
        tags = self._layer_tag_map()
        remat_wrap, remat_policy = self._resolve_remat()
        head_key, head_lyr = part.head
        cast = model._cast
        cast_params = self._cast_pp_params

        def pipe_loss(pp_params, pp_states, x, y, keys, weights):
            h = cast(x)
            cp = cast_params(pp_params)
            new_states = dict(pp_states)
            for i, (k, lyr) in enumerate(part.pre):
                with cmod.layer_scope(tags[k]):
                    h, ns = lyr.apply(cp[f"pre:{i}"], pp_states[f"pre:{i}"],
                                      h, training=True,
                                      key=keys[key_index[k]])
                new_states[f"pre:{i}"] = ns
            # lane batch -> (n_micro, mb, ...) microbatches
            mb = h.shape[0] // M
            micro = h.reshape(M, mb, *h.shape[1:])
            # per-(stage, position) RNG keys, stacked over the stage axis
            stage_keys = [
                jnp.stack([keys[key_index[chunk[j][0]]]
                           for chunk in part.stages])
                for j in range(L)]
            stage_layers = [lyr for (_, lyr) in part.stages[0]]

            def stage_apply(packed, xm):
                sp, ss, sk = packed
                hh = xm
                for j, lyr in enumerate(stage_layers):
                    hh, _ = lyr.apply(sp[j], ss[j], hh, training=True,
                                      key=sk[j])
                return hh

            if remat_wrap:
                body = jax.checkpoint(stage_apply, policy=remat_policy)
            else:
                body = stage_apply
            packed = ([cp[f"stage:{j}"] for j in range(L)],
                      [pp_states[f"stage:{j}"] for j in range(L)],
                      stage_keys)
            with cmod.layer_scope("pipe_stages"):
                outs = gpipe_scan(body, packed, micro)
            h = outs.reshape(M * mb, *outs.shape[2:])
            for i, (k, lyr) in enumerate(part.post):
                with cmod.layer_scope(tags[k]):
                    h, ns = lyr.apply(cp[f"post:{i}"],
                                      pp_states[f"post:{i}"], h,
                                      training=True, key=keys[key_index[k]])
                new_states[f"post:{i}"] = ns
            loss_kw = {} if weights is None else {"weights": weights}
            with cmod.layer_scope(tags[head_key]):
                loss = head_lyr.compute_loss(
                    cp["head"], pp_states["head"], h, y, training=True,
                    key=keys[key_index[head_key]], **loss_kw)
            reg = jnp.asarray(0.0)
            for i, (k, lyr) in enumerate(part.pre):
                reg = reg + lyr.regularization(pp_params[f"pre:{i}"])
            for j in range(L):
                # stacked leaves: one reduction over all S stages (equal in
                # value; association differs from the per-layer sum at ~ulp
                # when l1/l2 are active — docs/DISTRIBUTED.md)
                reg = reg + part.stages[0][j][1].regularization(
                    pp_params[f"stage:{j}"])
            for i, (k, lyr) in enumerate(part.post):
                reg = reg + lyr.regularization(pp_params[f"post:{i}"])
            reg = reg + head_lyr.regularization(pp_params["head"])
            return loss.astype(jnp.float32) + reg, new_states

        def lane(pp_params, pp_states, x, y, key, weights, scale=None):
            keys = list(jax.random.split(key, n_keys))
            with model._kscope():
                loss, new_states, grads = gspmd._lane_value_and_grad(
                    pipe_loss, scaled,
                    (pp_params, pp_states, x, y, keys, weights), scale)
            wsum = jnp.sum(weights) if weights is not None \
                else jnp.asarray(1.0, jnp.float32)
            return (loss, wsum), (new_states, grads)

        return lane

    def _layer_tag_map(self):
        model = self.model
        if hasattr(model, "_layer_tags"):  # MLN: index-keyed
            return {i: t for i, t in enumerate(model._layer_tags)}
        if hasattr(model, "_node_tags"):   # CG: name-keyed
            return dict(model._node_tags)
        return {k: cmod.sanitize_tag(str(k)) for k in self.part.order}

    def _resolve_remat(self):
        from deeplearning4j_tpu.util import xla_tuning

        policy = getattr(self.model.conf, "remat_policy", None)
        if policy in (None, "none"):
            return False, None
        return xla_tuning.resolve_policy(policy)

    def _cast_pp_params(self, pp_params):
        model = self.model
        if getattr(model.conf, "compute_dtype", "float32") != "bfloat16":
            return pp_params
        return jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, pp_params)

    # ------------------------------------------------------------- the step
    def _pipe_combine_fns(self):
        sspecs = self._pp_state_specs
        comp = self._compressor
        cspecs = self._comp_specs
        model = self.model
        pp_engine = self._pp_engine
        comp_flat = comp is not None and pp_engine is not None
        zspecs = self._zero_specs
        pspecs = self._pp_param_specs
        ospecs = self._pp_opt_specs
        part = self.part
        stage_updaters = self._stage_updaters

        def combine(loss_s, s_l, states_l, scaled_g):
            total = gspmd.pairwise_sum(s_l)
            inv = 1.0 / jnp.where(total == 0.0, 1.0, total)
            # fused models combine in FLAT-BUFFER space (per-lane flatten
            # first, pairwise-sum the buffers): the same add layout the
            # compressed path uses, so threshold→0 compression is
            # bit-identical to the uncompressed fused fit (the r15 proof
            # shape), and the stage axis is never sliced in-jit
            payload = (jax.vmap(pp_engine.flatten_grads)(scaled_g)
                       if pp_engine is not None else scaled_g)
            grads = jax.tree_util.tree_map(
                lambda t: gspmd.pairwise_sum(t) * inv.astype(t.dtype),
                payload)
            loss = gspmd.pairwise_sum(loss_s) * inv
            new_states = gspmd.combine_states(states_l)
            if sspecs is not None:
                new_states = gspmd.constrain_tree(new_states, sspecs)
            return loss, grads, new_states

        def combine_compressed(loss_s, s_l, states_l, scaled_g, comp_state):
            total = gspmd.pairwise_sum(s_l)
            inv = 1.0 / jnp.where(total == 0.0, 1.0, total)
            # fused: flatten each lane's pipeline-layout grads into the
            # pp engine's group buffers (reshape-only — the stacked stage
            # axis is never sliced in-jit) so the encode runs on exactly
            # what ZeRO reduce-scatters
            payload = (jax.vmap(pp_engine.flatten_grads)(scaled_g)
                       if comp_flat else scaled_g)
            grads, new_comp, stats = comp.encode_combine(
                payload, comp_state, inv)
            loss = gspmd.pairwise_sum(loss_s) * inv
            new_states = gspmd.combine_states(states_l)
            if sspecs is not None:
                new_states = gspmd.constrain_tree(new_states, sspecs)
            if cspecs is not None:
                new_comp = gspmd.constrain_tree(new_comp, cspecs)
            return loss, grads, new_states, new_comp, stats

        def update(pp_params, opts, grads, iteration):
            if zspecs is not None:
                opts = gspmd.constrain_tree(opts, zspecs)
            if pp_engine is not None:
                # grads are ALWAYS the pp engine's flat group buffers here
                # (combine flattens per lane on both the compressed and
                # uncompressed paths)
                with cmod.optimizer_scope():
                    new_params, new_opts = pp_engine.apply_flat(
                        pp_params, grads, opts, iteration)
            else:
                new_params, new_opts = {}, {}
                with cmod.optimizer_scope():
                    for i, (k, _) in enumerate(part.pre):
                        new_params[f"pre:{i}"], new_opts[f"pre:{i}"] = \
                            _apply_or_keep(
                                model._updaters[k], pp_params[f"pre:{i}"],
                                grads[f"pre:{i}"], opts[f"pre:{i}"],
                                iteration)
                    for j in range(part.per_stage):
                        new_params[f"stage:{j}"], new_opts[f"stage:{j}"] = \
                            _apply_or_keep(
                                stage_updaters[j], pp_params[f"stage:{j}"],
                                grads[f"stage:{j}"], opts[f"stage:{j}"],
                                iteration)
                    for i, (k, _) in enumerate(part.post):
                        new_params[f"post:{i}"], new_opts[f"post:{i}"] = \
                            _apply_or_keep(
                                model._updaters[k], pp_params[f"post:{i}"],
                                grads[f"post:{i}"], opts[f"post:{i}"],
                                iteration)
                    hk = part.head[0]
                    new_params["head"], new_opts["head"] = _apply_or_keep(
                        model._updaters[hk], pp_params["head"],
                        grads["head"], opts["head"], iteration)
            if pspecs is not None:
                new_params = gspmd.constrain_tree(new_params, pspecs)
            if pp_engine is not None:
                if zspecs is not None:
                    new_opts = gspmd.constrain_tree(new_opts, zspecs)
            elif ospecs is not None:
                new_opts = gspmd.constrain_tree(new_opts, ospecs)
            return new_params, new_opts

        j_combine = (jax.jit(combine_compressed, donate_argnums=(4,))
                     if comp is not None else jax.jit(combine))
        return j_combine, jax.jit(update, donate_argnums=(0, 1))

    def _build_pipe_step(self):
        lane_vg = self._make_pipe_lane_vg()
        compressed = self._compressor is not None

        def lanes(pp_params, pp_states, x, y, keys, w, scale):
            (loss_l, s_l), (states_l, grads_l) = jax.vmap(
                lane_vg, in_axes=(None, None, 0, 0, 0, 0, None)
            )(pp_params, pp_states, x, y, keys, w, scale)
            loss_s, scaled = self._lane_scale(loss_l, s_l, grads_l)
            return loss_s, s_l, states_l, scaled

        j_lanes = jax.jit(lanes)
        j_combine, j_update = self._pipe_combine_fns()
        self._stage_jits = (j_lanes, j_combine, j_update)

        def step(params, states, opts, iteration, x, y, keys, w):
            loss_s, s_l, states_l, scaled = j_lanes(
                params, states, x, y, keys, w, self._loss_scale_arg())
            if compressed:
                loss, grads, new_states = self._run_compressed_combine(
                    j_combine, (loss_s, s_l, states_l, scaled))
            else:
                loss, grads, new_states = j_combine(loss_s, s_l, states_l,
                                                    scaled)
            new_params, new_opts = j_update(params, opts, grads, iteration)
            return new_params, new_states, new_opts, loss

        return step

    def _loss_scale_arg(self):
        engine = self._pp_engine
        if engine is None or engine.loss_scale == "none":
            return None
        return engine.current_scale(self._pp["opts"])

    # -------------------------------------------------------------- stepping
    def _shard(self, x, y):
        return self.mesh.pad_lane_batch(x, y, self.replicas,
                                        micro=self.n_micro)

    def step_batch(self, ds):
        if self._sharded_step is None:
            self._build()
        self._adopt_model_state()
        self._adopt_compression_state()
        model = self.model
        if getattr(ds, "features_mask", None) is not None or \
                getattr(ds, "labels_mask", None) is not None:
            raise NotImplementedError(
                "pipelined fit() does not thread sequence masks; use "
                "ParallelWrapper for masked batches")
        x, y, w = self._shard(ds.features, ds.labels)
        model._rng_key, sub = jax.random.split(model._rng_key)
        keys = self._lane_keys(sub)
        pp = self._pp
        import time as _time

        t0 = _time.time_ns()
        with tm.span("parallel.pipe_step", iteration=model.iteration,
                     stages=self.pipe_stages, n_micro=self.n_micro):
            new_p, new_s, new_o, loss = self._sharded_step(
                pp["params"], pp["states"], pp["opts"],
                jnp.asarray(model.iteration), x, y, keys, w)
        self._pp = {"params": new_p, "states": new_s, "opts": new_o}
        model.score_value = loss
        model.iteration += 1
        tm.counter("train.steps_total", model="pipelined")
        if (self.skew_every and tm.enabled()
                and model.iteration % self.skew_every == 0):
            # the parent's window-cadence contract: per-replica completion
            # spans + the straggler-skew gauge (a deliberate sync point)
            self._probe_replica_skew(loss, t0)
            self._publish_compression_stats()
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration, model.epoch)
        return loss

    # ----------------------------------------------------------- memory/layout
    def param_bytes_per_device(self) -> int:
        """Bytes of PARAMS one device holds under the pipeline placement
        (stage leaves pipe-sharded) — with :meth:`opt_state_bytes_per_device`
        the ``pipeline_param_bytes_per_device`` bench metric."""
        if self._pp is None:
            self._build()
        return gspmd.tree_bytes_per_device(self._pp["params"])

    def opt_state_bytes_per_device(self) -> int:
        if self._pp is None:
            self._build()
        return gspmd.tree_bytes_per_device(self._pp["opts"])

    def train_state_bytes_per_device(self) -> int:
        """params + optimizer state, per device — the "does the model fit
        one chip's budget" number the acceptance contract gates."""
        return self.param_bytes_per_device() \
            + self.opt_state_bytes_per_device()

    @property
    def bubble_fraction(self) -> float:
        return bubble_fraction(self.pipe_stages, self.n_micro)

    def _publish_layout(self):
        mesh = self.mesh
        self._publish_mesh_gauges()
        tm.gauge("parallel.pipe_stages", self.pipe_stages)
        tm.gauge("parallel.pipeline_n_micro", self.n_micro)
        tm.gauge("parallel.pipeline_bubble_fraction", self.bubble_fraction)
        tm.gauge("parallel.opt_state_bytes_per_device",
                 self.opt_state_bytes_per_device())
        tm.gauge("parallel.param_bytes_per_device",
                 self.param_bytes_per_device())
        comp = self._compressor
        self.layout = {
            "signature": mesh.layout_signature(
                extra=("pipe", self.pipe_stages, self.n_micro,
                       self.zero_optimizer, self.replicas,
                       (comp.scheme, comp.hosts) if comp else None)),
            "params": gspmd.describe_shardings(self._pp["params"]),
            "opt_states": gspmd.describe_shardings(self._pp["opts"]),
            "pipeline": {
                "stages": self.pipe_stages,
                "n_micro": self.n_micro,
                "bubble_fraction": self.bubble_fraction,
                "layers_per_stage": self.part.per_stage,
                "pre": [str(k) for k, _ in self.part.pre],
                "post": [str(k) for k, _ in self.part.post],
            },
        }
        if comp is not None:
            tm.gauge("parallel.grad_compression_hosts", comp.hosts)
            self.layout["grad_compression"] = {
                "scheme": comp.scheme, "hosts": comp.hosts,
                "residual": gspmd.describe_shardings(
                    self._comp_state["residual"]),
            }

    # --------------------------------------------------------------- reshard
    def reshard(self, mesh: Optional[TrainingMesh] = None):
        """Elastic-regroup hook: sync the stacked state back to the net in
        model layout, pull it to host, re-derive the mesh from the current
        device view (keeping the model/seq/pipe factors when they still
        fit — pipe collapses to 1 rather than leaving stages unplaceable),
        and rebuild. The stacked stage state migrates bit-exactly: stack ∘
        unstack is the identity."""
        self.sync_model()
        model = self.model
        model.params = jax.tree_util.tree_map(np.asarray, model.params)
        model.states = jax.tree_util.tree_map(np.asarray, model.states)
        model.opt_states = jax.tree_util.tree_map(np.asarray,
                                                  model.opt_states)
        if self._comp_state is not None:
            model._grad_comp_state = jax.tree_util.tree_map(
                np.asarray, self._comp_state)
            self._comp_state = None
        if mesh is None:
            devices = jax.devices()
            model_ax, seq_ax, pipe_ax = (self.mesh.model, self.mesh.seq,
                                         self.mesh.pipe)
            if len(devices) % (model_ax * seq_ax * pipe_ax) \
                    or self.pipe_stages % pipe_ax:
                pipe_ax = 1
            if len(devices) % (model_ax * seq_ax * pipe_ax):
                model_ax = seq_ax = 1
            mesh = TrainingMesh(
                data=len(devices) // (model_ax * seq_ax * pipe_ax),
                model=model_ax, seq=seq_ax, pipe=pipe_ax, devices=devices)
        if self.pipe_stages % mesh.pipe:
            raise ValueError(
                f"pipe mesh axis ({mesh.pipe}) must divide pipe_stages "
                f"({self.pipe_stages})")
        self.mesh = mesh
        self._sharded_step = None
        self._pp = None
        self._comp_specs = None
        self._zero_specs = None
        self._build()
        tm.counter("parallel.reshards_total")
        return self

    # ---------------------------------------------------------------- warmup
    def warmup(self, batch_sizes, input_shape=None, label_shape=None):
        """AOT warmup on zero-valued shadow pipeline-layout state (params
        and the compression residual are donated — the real trajectory is
        never perturbed). One throwaway step per global batch size."""
        if self._sharded_step is None:
            self._build()
        model = self.model
        in_shape = tuple(input_shape or self._conf_input_shape() or ())
        if not in_shape:
            raise ValueError("warmup() needs input_shape "
                             "(or conf.input_shape)")
        out_shape = tuple(label_shape or getattr(model, "_output_shape", ())
                          or ())
        if not out_shape:
            raise ValueError("warmup() needs label_shape")
        zeros = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jnp.zeros(jnp.shape(a), a.dtype), t)
        real_pp = self._pp
        real_comp = self._comp_state
        real_stats = self._comp_stats
        primed = 0
        try:
            for b in batch_sizes:
                x = np.zeros((int(b),) + in_shape, np.float32)
                y = np.zeros((int(b),) + out_shape, np.float32)
                xs, ys, w = self._shard(x, y)
                shadow = {k: zeros(v) for k, v in real_pp.items()}
                if real_comp is not None:
                    sh = zeros(real_comp)
                    if self._comp_specs is not None:
                        sh = gspmd.place_tree(sh, self._comp_specs)
                    self._comp_state = sh
                keys = self._lane_keys(jax.random.PRNGKey(0))
                self._sharded_step(shadow["params"], shadow["states"],
                                   shadow["opts"], jnp.asarray(0), xs, ys,
                                   keys, w)
                primed += 1
        finally:
            self._pp = real_pp
            self._comp_state = real_comp
            self._comp_stats = real_stats
            if real_comp is not None:
                self.model._grad_comp_state = real_comp
        return primed

    def _conf_input_shape(self):
        conf = self.model.conf
        shape = getattr(conf, "input_shape", None)
        if shape is None:
            shapes = getattr(conf, "input_shapes", None)
            shape = shapes[0] if shapes else None
        return shape

    # ----------------------------------------------------------- cost report
    def cost_report(self, batch_size=None, *, shape=None, dtype=jnp.float32,
                    name: str = "pipelined", publish: bool = True):
        """Per-layer cost table for ONE pipelined train step: lowers all
        three stage jits with the fit-time shapes/shardings, sums their
        per-device totals, and merges attributions. The pipeline's scan
        body carries ONE ``pipe_stages`` scope (all S stages execute in a
        single vmapped program — per-stage scopes cannot survive the stage
        vmap); the stages are structurally identical by contract, so the
        report splits that scope's cost into S equal per-stage rows
        ``pipe:stage<i>`` (docs/OBSERVABILITY.md honesty note)."""
        model = self.model
        if self._sharded_step is None:
            self._build()
        conf = model.conf
        if shape is None:
            in_shape = self._conf_input_shape()
            if in_shape is None:
                raise ValueError("cost_report() needs shape= or "
                                 "conf.input_shape")
            shape = ((int(batch_size or self.replicas * self.n_micro),)
                     + tuple(in_shape))
        shape = tuple(int(d) for d in shape)
        b, R = shape[0], self.replicas
        if b % (R * self.n_micro):
            raise ValueError(
                f"global batch {b} must divide lanes*n_micro "
                f"({R}*{self.n_micro})")

        def struct(t):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    jnp.shape(a), jnp.asarray(a).dtype,
                    sharding=getattr(a, "sharding", None)), t)

        lane_shape = (R, b // R) + tuple(shape[1:])
        lsh = (self.mesh.spec("data", *([None] * (len(lane_shape) - 1)))
               if self.mesh.n_devices > 1 else None)
        x_s = jax.ShapeDtypeStruct(lane_shape, dtype, sharding=lsh)
        out_shape = tuple(getattr(model, "_output_shape", ()) or ())
        y_shape = (R, b // R) + out_shape
        y_s = jax.ShapeDtypeStruct(
            y_shape, jnp.float32,
            sharding=(self.mesh.spec("data", *([None] * (len(y_shape) - 1)))
                      if self.mesh.n_devices > 1 else None))
        w_s = jax.ShapeDtypeStruct(
            (R, b // R), jnp.float32,
            sharding=(self.mesh.spec("data", None)
                      if self.mesh.n_devices > 1 else None))
        keys_s = struct(self._lane_keys(jax.random.PRNGKey(0)))
        scale = self._loss_scale_arg()
        scale_s = None if scale is None else struct(scale)
        pp = self._pp
        p_s, s_s, o_s = (struct(pp["params"]), struct(pp["states"]),
                         struct(pp["opts"]))
        it_s = jax.ShapeDtypeStruct((), jnp.int32)

        j_lanes, j_combine, j_update = self._stage_jits
        lanes_args = (p_s, s_s, x_s, y_s, keys_s, w_s, scale_s)
        lanes_out = jax.eval_shape(j_lanes, *lanes_args)
        if self._compressor is not None:
            comb_args = tuple(lanes_out) + (struct(self._comp_state),)
            _loss, grads_s = jax.eval_shape(j_combine, *comb_args)[:2]
        else:
            comb_args = tuple(lanes_out)
            _loss, grads_s, _st = jax.eval_shape(j_combine, *comb_args)
        upd_args = (p_s, o_s, grads_s, it_s)

        tags = self._layer_tag_map()
        params_by_tag = {}
        for k, _lyr in self.part.pre + self.part.post + [self.part.head]:
            params_by_tag[tags[k]] = int(sum(
                int(np.prod(np.shape(l))) for l in
                jax.tree_util.tree_leaves(model.params[k])))
        stage_params = int(sum(
            int(np.prod(np.shape(l)))
            for j in range(self.part.per_stage)
            for l in jax.tree_util.tree_leaves(
                self._pp["params"][f"stage:{j}"])))
        totals: dict = {}
        merged = None
        source = "analytic"
        try:
            for fn, args in ((j_lanes, lanes_args), (j_combine, comb_args),
                             (j_update, upd_args)):
                compiled = fn.lower(*args).compile()
                for k, v in cmod.compiled_totals(compiled).items():
                    totals[k] = totals.get(k, 0.0) + v
                att = cmod.attribute_hlo(cmod.compiled_text(compiled))
                if merged is None:
                    merged = att
                else:
                    for key, costs in att.by_layer.items():
                        dst = merged.by_layer.setdefault(key, {})
                        for ck, cv in costs.items():
                            dst[ck] = dst.get(ck, 0.0) + cv
                    merged.flops_total += att.flops_total
                    merged.transcendentals_total += att.transcendentals_total
                    merged.bytes_total += att.bytes_total
                    merged.inst_map.update(att.inst_map)
            source = "xla"
        except cmod.CostAnalysisUnavailable:
            totals, merged = {}, None
        rows = (cmod.rows_from_attribution(merged, params_by_tag, None)
                if merged is not None else [])
        rows = self._split_stage_rows(rows, stage_params)
        report = cmod.CostReport(
            rows=rows, totals=totals, batch=b,
            params_total=model.num_params(), source=source, model=str(name),
            peak_flops=cmod.peak_flops_from_env(
                getattr(conf, "compute_dtype", None)),
            devices=self.mesh.n_devices)
        if publish:
            cmod.publish_report(str(name), report)
        return report

    def _split_stage_rows(self, rows, stage_params_total: int):
        """Replace the single ``pipe_stages`` scope row with S equal
        per-stage rows (structurally identical stages — the honest split)."""
        S = self.pipe_stages
        out = []
        for row in rows:
            if row.layer != "pipe_stages":
                out.append(row)
                continue
            for si in range(S):
                out.append(cmod.CostRow(
                    layer=f"pipe:stage{si}",
                    params=stage_params_total // S,
                    flops_fwd=row.flops_fwd / S,
                    flops_bwd=row.flops_bwd / S,
                    transcendentals=row.transcendentals / S,
                    bytes_accessed=row.bytes_accessed / S,
                    source=row.source))
        return out


def _sig_params(fn):
    import inspect

    try:
        return inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return {}


def _apply_or_keep(updater, params, grads, opt, iteration):
    """One updater application, skipping empty param trees (layers with no
    trainable params — activations etc.)."""
    if not jax.tree_util.tree_leaves(params):
        return params, opt
    return upd.apply_updater(updater, params, grads, opt, iteration)
