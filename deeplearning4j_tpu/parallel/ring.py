"""Ring attention: sequence/context parallelism, expressed as GSPMD.

The reference has NO long-context story — SURVEY.md §5.7: no ring attention,
no sequence/context parallelism anywhere; long sequences are handled by
truncated BPTT only. This module is the TPU-native extension the brief makes
first-class: shard the sequence axis over a mesh axis and rotate K/V blocks
around the ring while each block of queries accumulates its online-softmax
state (Liu et al., Ring Attention with Blockwise Transformers — PAPERS.md).

GSPMD formulation (no per-device mapped functions — ROADMAP item 1): the sequence axis is
reshaped to an explicit block axis ``[n, B, H, S/n, D]`` annotated with
``PartitionSpec(axis_name)``; each hop updates ALL query blocks against the
current K/V blocks (a ``vmap`` over the block axis — per-device that is its
own resident blocks) and then rotates K/V one block with ``jnp.roll`` on the
sharded axis, which the partitioner lowers to the ring's collective-permute.
Each device's live working set is its own q/k/v blocks plus one in-flight
block — the S×S score matrix never materializes on any one device — and the
hop's collective overlaps the local block's compute under XLA's async
collective scheduling. Numerically this is the same online-softmax update
order as the classic per-device formulation (exact vs
``dot_product_attention`` up to fp association, and differentiable — AD
reverses the rolls).

Layout: [batch, heads, seq, head_dim], sharded P(None, None, axis, None).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ops.attention import _NEG_BIG, online_softmax_update


@functools.lru_cache(maxsize=64)
def _ring_program(mesh: Mesh, axis_name: str, n: int, scale: float,
                  causal: bool):
    """One jitted SPMD ring-attention program per (mesh, axis, blocks,
    scale, causal) — shapes key jit's own cache."""
    block_spec = NamedSharding(mesh, P(axis_name))

    def constrain(t):
        return jax.lax.with_sharding_constraint(t, block_spec)

    # causal / non-causal vmapped block updates (q_pos/k_pos are per-block
    # 1-D vectors; None cannot ride a vmapped axis, hence two variants)
    upd_causal = jax.vmap(online_softmax_update,
                          in_axes=(0, 0, 0, 0, 0, 0, None, 0, 0))
    upd_plain = jax.vmap(
        lambda q, k, v, m, l, a, s: online_softmax_update(q, k, v, m, l, a, s),
        in_axes=(0, 0, 0, 0, 0, 0, None))

    def run(q, k, v):
        b, h, s, d = q.shape
        blk = s // n

        def to_blocks(t):
            # [B,H,S,D] -> [n,B,H,blk,D], block axis sharded over the ring
            t = t.reshape(b, h, n, blk, d).transpose(2, 0, 1, 3, 4)
            return constrain(t)

        qb, kb, vb = to_blocks(q), to_blocks(k), to_blocks(v)
        m = jnp.full((n, b, h, blk), _NEG_BIG, jnp.float32)
        l = jnp.zeros((n, b, h, blk), jnp.float32)
        acc = jnp.zeros((n, b, h, blk, d), jnp.float32)
        blocks = jnp.arange(n)
        offs = jnp.arange(blk)
        q_pos = blocks[:, None] * blk + offs[None, :]  # (n, blk)
        for i in range(n):
            # after i hops block j holds the K/V that started at (j - i)
            if causal:
                src = (blocks - i) % n
                k_pos = src[:, None] * blk + offs[None, :]
                m, l, acc = upd_causal(qb, kb, vb, m, l, acc, scale,
                                       q_pos, k_pos)
            else:
                m, l, acc = upd_plain(qb, kb, vb, m, l, acc, scale)
            if i + 1 < n:
                kb = constrain(jnp.roll(kb, 1, axis=0))
                vb = constrain(jnp.roll(vb, 1, axis=0))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = (acc / safe_l[..., None]).astype(q.dtype)
        return out.transpose(1, 2, 0, 3, 4).reshape(b, h, s, d)

    return jax.jit(run)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    axis_name: str = "seq",
    scale: Optional[float] = None,
    causal: bool = False,
):
    """Sequence-parallel attention: [B,H,S,D] with S sharded over ``axis_name``.

    Exact (up to fp) equivalence with ``dot_product_attention``; per-device
    memory and compute are O(S/n · S) with the S×S matrix never materialized
    on any one device. Differentiable (JAX AD reverses the block rotation).
    Sequence length must divide the ring size.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n = mesh.shape[axis_name]
    if q.shape[2] % n:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by "
            f"{axis_name!r} axis size {n}")
    return _ring_program(mesh, axis_name, int(n), float(scale),
                         bool(causal))(q, k, v)


def shard_sequence(x, mesh: Mesh, axis_name: str = "seq", dim: int = 2):
    """Place an array with its ``dim`` axis sharded over ``axis_name``."""
    spec = [None] * x.ndim
    spec[dim] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
