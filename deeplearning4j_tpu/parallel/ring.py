"""Ring attention: sequence/context parallelism over the device mesh.

The reference has NO long-context story — SURVEY.md §5.7: no ring attention,
no sequence/context parallelism anywhere; long sequences are handled by
truncated BPTT only. This module is the TPU-native extension the brief makes
first-class: shard the sequence axis across a mesh axis and rotate K/V blocks
around the ring with ``ppermute`` while each device accumulates its queries'
online-softmax state (Liu et al., Ring Attention with Blockwise Transformers —
PAPERS.md). Collectives ride ICI; each hop overlaps with the local block's
compute under XLA's async collective scheduling.

Layout: [batch, heads, seq, head_dim], sharded P(None, None, axis, None).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ops.attention import _NEG_BIG, online_softmax_update


def _ring_body(q, k, v, src_block, n_local, scale, causal, axis_name, m, l, acc):
    """One online-softmax update of the local queries against one K/V block."""
    q_pos = k_pos = None
    if causal:
        my = lax.axis_index(axis_name)
        q_pos = my * n_local + jnp.arange(n_local)
        k_pos = src_block * n_local + jnp.arange(n_local)
    return online_softmax_update(q, k, v, m, l, acc, scale, q_pos=q_pos, k_pos=k_pos)


def _ring_attention_local(q, k, v, *, axis_name, axis_size, scale, causal):
    """Per-device body under shard_map: local q stays put, k/v ring-rotate."""
    b, h, sl, d = q.shape
    m = jnp.full((b, h, sl), _NEG_BIG, jnp.float32)
    l = jnp.zeros((b, h, sl), jnp.float32)
    acc = jnp.zeros((b, h, sl, d), jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    my = lax.axis_index(axis_name)
    for i in range(axis_size):
        # after i hops this device holds the block that started at (my - i)
        src = (my - i) % axis_size
        m, l, acc = _ring_body(q, k, v, src, sl, scale, causal, axis_name, m, l, acc)
        if i + 1 < axis_size:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l[..., None]).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    axis_name: str = "seq",
    scale: Optional[float] = None,
    causal: bool = False,
):
    """Sequence-parallel attention: [B,H,S,D] with S sharded over ``axis_name``.

    Exact (up to fp) equivalence with ``dot_product_attention``; memory and
    compute per device are O(S/n · S) with the S×S matrix never materialized
    on any one device. Differentiable (JAX AD through ppermute reverses the
    ring). Sequence length must divide the axis size.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    axis_size = mesh.shape[axis_name]
    spec = P(None, None, axis_name, None)
    fn = partial(
        _ring_attention_local,
        axis_name=axis_name,
        axis_size=axis_size,
        scale=float(scale),
        causal=bool(causal),
    )
    shmap = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return shmap(q, k, v)


def shard_sequence(x, mesh: Mesh, axis_name: str = "seq", dim: int = 2):
    """Place an array with its ``dim`` axis sharded over ``axis_name``."""
    spec = [None] * x.ndim
    spec[dim] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
