"""TrainingMesh — named device mesh + sharding helpers.

The TPU-native replacement for the reference's device-topology plumbing
(CudaEnvironment/affinity in nd4j-cuda, MeshOrganizer spanning-tree in the
parameter server — path-cite, mount empty this round): a
``jax.sharding.Mesh`` with canonical axis names

- ``data``  — batch (DP); gradients all-reduce over ICI
- ``model`` — tensor parallelism (sharded matmuls)
- ``seq``   — sequence/context parallelism (ring attention)
- ``pipe``  — pipeline parallelism (stage-stacked params; parallel/pipelined.py)

Multi-host: the same mesh spans hosts (DCN between slices); construction is
identical — jax.distributed bootstrap happens in parallel.distributed.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class TrainingMesh:
    AXES = ("data", "model", "seq", "pipe")

    def __init__(self, data: int = 0, model: int = 1, seq: int = 1,
                 pipe: int = 1, devices: Optional[Sequence] = None):
        devices = list(devices) if devices is not None else jax.devices()
        n = len(devices)
        fixed = model * seq * pipe
        if data <= 0:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by model*seq*pipe={fixed}")
            data = n // fixed
        total = data * fixed
        if total > n:
            raise ValueError(f"mesh {data}x{model}x{seq}x{pipe} needs "
                             f"{total} devices, have {n}")
        grid = np.array(devices[:total]).reshape(data, model, seq, pipe)
        self.mesh = Mesh(grid, axis_names=self.AXES)
        self.data, self.model, self.seq, self.pipe = data, model, seq, pipe

    # -- shardings ---------------------------------------------------------
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        """Shard dim 0 over 'data'."""
        return NamedSharding(self.mesh, P("data", *([None] * (ndim - 1))))

    def spec(self, *axes) -> NamedSharding:
        return NamedSharding(self.mesh, P(*axes))

    def shard_batch(self, *arrays):
        """Place host arrays with the batch dim sharded over 'data'."""
        out = tuple(
            jax.device_put(a, self.batch_sharding(np.ndim(a))) for a in arrays
        )
        return out if len(out) > 1 else out[0]

    @staticmethod
    def _pad_ragged(x, y, divisor: int, extras):
        """Shared host-side ragged-batch padding: pad (x, y, extras) to
    ``divisor`` divisibility by repeating the last row, with a 0/1 loss-
    weight vector over the padded rows so a weighted loss divides by the
    REAL example count — gradients stay exact for ragged batches. Returns
    (xs, ys, w, extras, multi_x, multi_y). The ONE implementation behind
    both the flat-batch and the lane-decomposed placements, so the padding
    semantics can never drift between them (the deterministic mode's
    bit-identity contract rides on this)."""
        multi_x = isinstance(x, (list, tuple))
        multi_y = isinstance(y, (list, tuple))
        xs = [np.asarray(v) for v in (x if multi_x else [x])]
        ys = [np.asarray(v) for v in (y if multi_y else [y])]
        n = len(xs[0])
        pad = (divisor - n % divisor) % divisor
        w = np.ones(n + pad, np.float32)
        rep = lambda v: np.concatenate(  # noqa: E731
            [v, np.repeat(v[-1:], pad, axis=0)], axis=0)
        if pad:
            xs = [rep(v) for v in xs]
            ys = [rep(v) for v in ys]
            w[n:] = 0.0
        if extras is not None:
            extras = jax.tree_util.tree_map(
                lambda v: rep(np.asarray(v)) if pad else np.asarray(v),
                extras)
        return xs, ys, w, extras, multi_x, multi_y

    def pad_shard_batch(self, x, y, extras=None):
        """Pad (x, y) to 'data'-axis divisibility and shard; returns
        (x, y, weights) with 0-weighted padding rows (see ``_pad_ragged``).
        ``x``/``y`` may each be a list/tuple of arrays (multi-input/multi-
        output ComputationGraphs); the matching return slot is then a
        tuple, sharded leaf-wise. ``extras``: optional pytree of (B, ...)
        arrays (sequence masks etc.) padded/sharded the same way —
        returned as a 4th element when given."""
        xs, ys, w, extras, multi_x, multi_y = self._pad_ragged(
            x, y, self.data, extras)
        sharded = self.shard_batch(*xs, *ys, w)
        sx, sy, sw = sharded[: len(xs)], sharded[len(xs):-1], sharded[-1]
        out = (sx if multi_x else sx[0], sy if multi_y else sy[0], sw)
        if extras is None:
            return out
        ex = jax.tree_util.tree_map(lambda v: self.shard_batch(v), extras)
        return out + (ex,)

    def replicate(self, tree, keep_existing: bool = True):
        """Place a pytree fully replicated. Leaves already carrying a
        NamedSharding on THIS mesh keep their placement (so tensor-parallel
        shardings set on individual params survive ParallelWrapper setup)."""
        sharding = self.replicated()

        def place(x):
            if (
                keep_existing
                and hasattr(x, "sharding")
                and isinstance(x.sharding, NamedSharding)
                and x.sharding.mesh == self.mesh
            ):
                return x
            return jax.device_put(x, sharding)

        return jax.tree_util.tree_map(place, tree)

    def pad_lane_batch(self, x, y, replicas: int, extras=None,
                       micro: int = 1):
        """Lane-decomposed variant of :meth:`pad_shard_batch` (the
        deterministic GSPMD path — parallel/gspmd.py): the same ragged
        padding (``_pad_ragged``), then every array reshapes to
        ``(replicas, b, ...)`` with the LANE axis sharded over 'data'.
        Returns (x, y, weights[, extras]) with weights shaped
        ``(replicas, b)``. The lane count is fixed by the caller — not by
        the device count — which is what makes a fit reproducible across
        mesh sizes. ``micro > 1`` (the pipelined trainer's microbatch
        count — parallel/pipelined.py) pads to ``replicas * micro``
        divisibility so each lane's batch further splits into ``micro``
        equal microbatches; the extra rows carry weight 0 exactly like
        every other ragged pad (the r8 0/1-weight machinery)."""
        xs, ys, w, extras, multi_x, multi_y = self._pad_ragged(
            x, y, replicas * max(1, int(micro)), extras)
        lane = lambda v: np.reshape(  # noqa: E731
            v, (replicas, v.shape[0] // replicas) + v.shape[1:])
        place = lambda v: jax.device_put(  # noqa: E731
            v, NamedSharding(self.mesh, P("data", *([None] * (v.ndim - 1)))))
        sx = tuple(place(lane(v)) for v in xs)
        sy = tuple(place(lane(v)) for v in ys)
        sw = place(lane(w))
        out = (sx if multi_x else sx[0], sy if multi_y else sy[0], sw)
        if extras is None:
            return out
        ex = jax.tree_util.tree_map(lambda v: place(lane(v)), extras)
        return out + (ex,)

    def tensor_shard_params(self, tree, rules):
        """Tensor parallelism as pure annotation (SNIPPETS.md [3]): place
        param leaves whose key path matches a rule regex with the rule's
        PartitionSpec on THIS mesh; everything else is left untouched (a
        later :meth:`replicate` keeps the TP placements). ``rules``:
        iterable of (pattern, PartitionSpec) — e.g.
        ``[(r"W1$", P(None, "model")), (r"W2$", P("model", None))]``.
        Leaves whose matched dimension is not divisible by the axis size
        are skipped (annotation must never change semantics)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for path, leaf in flat:
            key = jax.tree_util.keystr(path)
            placed = leaf
            for pattern, spec in rules:
                if not re.search(pattern, key):
                    continue
                ok = True
                for d, ax in enumerate(spec):
                    if ax is None:
                        continue
                    size = self.mesh.shape[ax]
                    if d >= np.ndim(leaf) or np.shape(leaf)[d] % size:
                        ok = False
                        break
                if ok:
                    placed = jax.device_put(
                        leaf, NamedSharding(self.mesh, spec))
                break
            out.append(placed)
        return jax.tree_util.tree_unflatten(treedef, out)

    def dcn_hosts(self) -> int:
        """The DCN factor of the 'data' axis: how many process (host)
        groups the data-parallel workers span. ``jax.devices()`` orders
        devices by process, and the mesh grid reshapes that order as
        (data, model, seq) — so on a multi-host pod the OUTER factor of
        the data axis IS the host dimension, which is what the
        hierarchical compressed all-reduce treats as the expensive seam
        (``ParallelWrapper(compression_hosts="auto")`` —
        docs/DISTRIBUTED.md#gradient-compression). Single-process (and any
        layout where the process count does not divide the data axis):
        1, i.e. no DCN seam to compress differently."""
        from deeplearning4j_tpu.parallel.distributed import host_count

        n = host_count()
        if n > 1 and self.data % n == 0:
            return int(n)
        return 1

    def layout_signature(self, extra=None) -> str:
        """Stable layout key for compile-cache / AOT-export keying
        (parallel/gspmd.py:layout_signature)."""
        from deeplearning4j_tpu.parallel import gspmd

        return gspmd.layout_signature(self.mesh, extra=extra)

    @property
    def n_devices(self) -> int:
        return self.data * self.model * self.seq * self.pipe

    def __repr__(self):
        return (f"TrainingMesh(data={self.data}, model={self.model}, "
                f"seq={self.seq}, pipe={self.pipe})")
