"""Elastic fault-tolerant training runtime (docs/FAULT_TOLERANCE.md).

ROADMAP item 3's missing composition: the pieces existed — the r7 DCN
bootstrap control plane (parallel/distributed.py), sharded checkpoints
(util/checkpoint.py), the training health monitor (util/health.py) — but a
killed ETL worker, a preempted host, or a NaN step still ended the run.
This module is the supervisor that makes worker loss survivable, the
TPU-native shape of the reference's SharedTrainingMaster deployment story
(workers fall over and rejoin; the Spark driver reschedules partitions):

- :class:`FileMembership` — heartbeat-based membership over a shared
  directory (the natural DCN-adjacent medium: every TPU pod host mounts
  shared storage; on one host it is simply a tmpdir, which is how the
  2-process SIGKILL test drives it). Members heartbeat on a thread;
  the lowest-id live member coordinates; **epoch-boundary regroup**
  shrinks the world when a member misses N heartbeats (and re-admits a
  restarted one at the next boundary), with coordinator failover when
  the coordinator itself dies. The data pipeline re-shards
  deterministically on regroup: batch ``i`` belongs to
  ``i % world == rank`` under the NEW view.
- :class:`ElasticTrainer` — the supervised loop around ``fit()``:
  checkpoint-auto-resume (periodic atomic checkpoints carrying RNG key +
  iterator cursor; on start, restore the newest GOOD checkpoint and
  fast-forward the iterator — proven bit-identical to an uninterrupted
  run), SIGTERM/preemption graceful drain (finish the in-flight step,
  checkpoint, leave the membership, return cleanly), and a ``rollback``
  recovery for health anomalies (util/health.py RollbackSignal): restore
  the last good checkpoint and re-enter the loop instead of raising.
- Fault-injection seams (util/faults.py) are consulted on the real code
  paths — NaN poisoning of a real batch, SIGKILL of the real process —
  so tests and the CI fault-smoke leg prove each recovery actually fires.

CPU-backend honesty (same stance as the r7 DCN dryrun): with world > 1 each
process steps its own replica — this jaxlib's CPU backend rejects
cross-process collectives, so membership/checkpoint/regroup (the control
plane this module adds) is what the multi-process tests prove; on real
ICI/DCN hardware the data plane is the GSPMD all-reduce underneath
ParallelWrapper, bootstrapped by ``distributed.initialize``.

    trainer = ElasticTrainer(net, "/ckpts/run1", checkpoint_every=200)
    trainer.fit(iterator, epochs=10)       # resumes automatically
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.util import faults as fl
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.checkpoint import ShardedCheckpointer
from deeplearning4j_tpu.util.faults import RetryPolicy
from deeplearning4j_tpu.util.health import RollbackSignal, TrainingHealthMonitor


class MembershipError(RuntimeError):
    """Membership protocol failure: barrier deadline exhausted, or this
    member was evicted from the published view (presumed dead while alive —
    rejoin at the next epoch boundary with a fresh trainer)."""


@dataclass(frozen=True)
class MembershipView:
    """One agreed epoch-scoped membership: sorted member ids, this member's
    rank within them. ``world`` is the new world size the data pipeline
    re-shards to (batch i belongs to ``i % world == rank``)."""

    epoch: int
    members: tuple
    rank: int

    @property
    def world(self) -> int:
        return len(self.members)

    def owns_batch(self, index: int) -> bool:
        return index % self.world == self.rank


def _atomic_write(path: str, payload: dict):
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


class FileMembership:
    """Heartbeat membership over a shared directory.

    Each member atomically rewrites ``hb-<id>.json`` (id, seq, wall ts)
    every ``heartbeat_interval`` seconds from a daemon thread; a member
    whose newest heartbeat is older than ``miss_threshold x interval`` (or
    who posted a ``left-<id>`` marker — graceful leave) is dead. The
    ``drop_heartbeat`` fault (util/faults.py) makes the thread skip beats,
    which is exactly what a wedged host looks like from outside.

    :meth:`regroup` is the epoch-boundary join/leave barrier: every member
    posts ``ready-<epoch>-<id>``; the lowest-id LIVE member coordinates,
    waiting (bounded by ``barrier_timeout``) until every live member is
    ready — a member that dies while awaited is dropped — then publishes
    ``view-<epoch>.json``; everyone adopts it. If the coordinator dies
    mid-barrier the next-lowest live member notices (stale heartbeat) and
    takes over, so a SIGKILLed coordinator cannot hang the survivors.
    """

    def __init__(self, directory: str, process_id: int, world_size: int = 1,
                 heartbeat_interval: float = 0.5, miss_threshold: int = 4,
                 barrier_timeout: float = 120.0,
                 join_grace: Optional[float] = None,
                 injector=None, log_fn=print):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.process_id = int(process_id)
        #: members expected at the INITIAL join barrier; the coordinator
        #: holds the first view open for them up to ``join_grace`` seconds
        #: (default: half the barrier timeout), so a slow-booting member is
        #: not evicted before its first heartbeat lands
        self.world_size = int(world_size)
        self.heartbeat_interval = heartbeat_interval
        self.miss_threshold = miss_threshold
        self.barrier_timeout = barrier_timeout
        self.join_grace = (join_grace if join_grace is not None
                           else barrier_timeout / 2)
        #: fault source for the beat thread (tests hand one member a private
        #: injector so drop_heartbeat targets exactly that member)
        self.injector = injector if injector is not None else fl.get_injector()
        self.log = log_fn
        self.view: Optional[MembershipView] = None
        self.regroups = 0
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._skip_beats = 0

    # ------------------------------------------------------------ heartbeats
    def _hb_path(self, member: int) -> str:
        return os.path.join(self.directory, f"hb-{member}.json")

    def _beat(self):
        self._seq += 1
        _atomic_write(self._hb_path(self.process_id),
                      {"id": self.process_id, "seq": self._seq,
                       "ts": time.time()})
        tm.counter("elastic.heartbeats_total")

    def _beat_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            fault = self.injector.fire(fl.DROP_HEARTBEAT)
            if fault is not None:
                # a dropped-heartbeat window long enough to be declared dead
                self._skip_beats = int(fault.arg or (self.miss_threshold + 2))
            if self._skip_beats > 0:
                self._skip_beats -= 1
                tm.counter("elastic.heartbeats_dropped_total")
                continue
            self._beat()

    def start(self) -> "FileMembership":
        left = os.path.join(self.directory, f"left-{self.process_id}")
        if os.path.exists(left):  # rejoin after a previous graceful leave
            os.unlink(left)
        self._beat()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._beat_loop, name="dl4j-tpu-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self, graceful: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if graceful:
            _atomic_write(os.path.join(
                self.directory, f"left-{self.process_id}"),
                {"id": self.process_id, "ts": time.time()})

    # -------------------------------------------------------------- liveness
    def alive(self) -> List[int]:
        """Member ids with a fresh heartbeat and no leave marker. Always
        includes self (a process that is asking is alive by definition).

        Freshness compares heartbeat-file MTIMES against each other — all
        stamps come from the one filesystem clock the members share — with
        this member's own latest beat as the "now" reference, so cross-host
        wall-clock skew cannot declare a live member dead. One interval of
        slack covers the reference's own age."""
        fresh_s = (self.miss_threshold + 1) * self.heartbeat_interval
        stamps = {}
        for name in os.listdir(self.directory):
            if not name.startswith("hb-") or ".tmp-" in name:
                continue
            try:
                member = int(name[len("hb-"):].split(".")[0])
                stamps[member] = os.stat(
                    os.path.join(self.directory, name)).st_mtime
            except (OSError, ValueError):
                continue  # mid-replace race: treat as missing this scan
        ref = stamps.get(self.process_id, max(stamps.values(), default=0.0))
        out = {self.process_id}
        for member, ts in stamps.items():
            if os.path.exists(os.path.join(self.directory, f"left-{member}")):
                continue
            if ref - ts <= fresh_s:
                out.add(member)
        return sorted(out)

    # --------------------------------------------------------------- regroup
    def _view_path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"view-{epoch}.json")

    def _ready_ids(self, epoch: int) -> List[int]:
        prefix = f"ready-{epoch}-"
        out = []
        for n in os.listdir(self.directory):
            if not n.startswith(prefix):
                continue
            try:
                out.append(int(n[len(prefix):]))
            except ValueError:
                continue  # a peer's in-flight ".tmp-<pid>" atomic write
        return sorted(out)

    def regroup(self, epoch: int,
                timeout: Optional[float] = None) -> MembershipView:
        """Epoch-boundary barrier + view agreement (see class docstring)."""
        _atomic_write(os.path.join(
            self.directory, f"ready-{epoch}-{self.process_id}"),
            {"id": self.process_id, "ts": time.time()})
        t0 = time.monotonic()
        deadline = t0 + (timeout or self.barrier_timeout)
        with tm.span("elastic.regroup", epoch=epoch):
            while True:
                view = self._try_adopt(epoch)
                if view is None and min(self.alive()) == self.process_id:
                    view = self._coordinate(epoch, time.monotonic() - t0)
                if view is not None:
                    return self._install(view)
                if time.monotonic() > deadline:
                    raise MembershipError(
                        f"member {self.process_id}: no view for epoch "
                        f"{epoch} within {timeout or self.barrier_timeout}s "
                        f"(alive={self.alive()}, "
                        f"ready={self._ready_ids(epoch)})")
                time.sleep(self.heartbeat_interval / 4)

    def _try_adopt(self, epoch: int) -> Optional[MembershipView]:
        path = self._view_path(epoch)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None  # mid-replace read; next poll sees it whole
        members = tuple(sorted(int(m) for m in data["members"]))
        if self.process_id not in members:
            raise MembershipError(
                f"member {self.process_id} evicted from epoch-{epoch} view "
                f"{members} (presumed dead); rejoin at the next boundary")
        return MembershipView(epoch=epoch, members=members,
                              rank=members.index(self.process_id))

    def _coordinate(self, epoch: int,
                    elapsed: float = 0.0) -> Optional[MembershipView]:
        """Coordinator body for one poll: publish the view once every LIVE
        member is ready (the dead are dropped by their stale heartbeats).
        Returns None while still waiting on a live, not-yet-ready member."""
        alive = set(self.alive())
        ready = set(self._ready_ids(epoch))
        if not (alive <= ready):
            return None  # someone live has not reached the barrier yet
        if (self.view is None and len(alive) < self.world_size
                and elapsed < self.join_grace):
            # initial join barrier: expected members may not have booted
            # far enough to write a first heartbeat — hold the view open
            return None
        members = tuple(sorted(alive))
        # exclusive-create publish: if two members momentarily both believe
        # they are the lowest live id (liveness scans race), the SECOND
        # publish fails and that coordinator adopts the existing view
        # instead — one view per epoch can ever exist, so a split brain
        # degrades to (at worst) a loud eviction, never two conflicting
        # views silently training overlapping shards
        payload = {"epoch": epoch, "members": list(members),
                   "coordinator": self.process_id, "ts": time.time()}
        path = self._view_path(epoch)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        try:
            try:
                os.link(tmp, path)  # atomic full-content fail-if-exists
            except FileExistsError:
                return self._try_adopt(epoch)  # lost the race: adopt theirs
            except OSError:
                # no hard links on this mount (object-store FUSE): portable
                # exclusive create — readers tolerate a partial JSON by
                # re-polling, so non-atomic content is benign
                try:
                    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    return self._try_adopt(epoch)
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return MembershipView(epoch=epoch, members=members,
                              rank=members.index(self.process_id))

    def _install(self, view: MembershipView) -> MembershipView:
        prev = self.view
        if prev is not None and prev.members != view.members:
            self.regroups += 1
            tm.counter("elastic.regroups_total")
            tm.instant("elastic.regroup_event", epoch=view.epoch,
                       world=view.world, members=str(list(view.members)))
            if self.log:
                self.log(f"ELASTIC regroup at epoch {view.epoch}: "
                         f"{list(prev.members)} -> {list(view.members)} "
                         f"(rank {view.rank}/{view.world})")
        self.view = view
        # world/rank Prometheus series come ONLY from the scrape-time
        # collector (collect_elastic_gauges) — pushing stored gauges here
        # too would emit a second, label-less series for the same fact
        tm.set_health("elastic.membership", True,
                      f"epoch {view.epoch}: rank {view.rank}/{view.world}")
        # sweep only READY litter from two epochs back; published VIEW
        # files are kept for the run's lifetime (a few bytes per epoch):
        # a member rolling back 2+ epochs after an anomaly re-adopts the
        # historical view instantly instead of deadlocking at a barrier
        # no peer will ever re-post ready markers for
        for name in os.listdir(self.directory):
            if name.startswith("ready-"):
                try:
                    old = int(name[len("ready-"):].split("-")[0])
                except ValueError:
                    continue
                if old <= view.epoch - 2:
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:
                        pass
        return view

    def status(self) -> dict:
        v = self.view
        return {
            "process_id": self.process_id,
            "alive": self.alive(),
            "world": v.world if v else None,
            "rank": v.rank if v else None,
            "members": list(v.members) if v else None,
            "epoch": v.epoch if v else None,
            "regroups": self.regroups,
            "heartbeat_interval_s": self.heartbeat_interval,
            "miss_threshold": self.miss_threshold,
        }


# --------------------------------------------------------------- publisher
class _ArchivePublisher:
    """Single background writer for the train→serve publish seam
    (docs/SERVING.md#resilience): the training thread drops a same-step
    host-array ``ModelSerializer.snapshot`` and returns to stepping; this
    thread pays the DEFLATE + atomic replace. ONE pending slot, latest
    wins — a disk slower than the checkpoint cadence collapses
    intermediate publishes instead of queueing behind them (the watcher
    only ever wants the newest weights anyway)."""

    def __init__(self, path: str, log_fn=None):
        self.path = path
        self.log = log_fn
        self._cv = threading.Condition()
        self._pending = None  # (snapshot, step) | None
        self._busy = False
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="elastic-publish")
        self._thread.start()

    def publish(self, snap: dict, step: int):
        with self._cv:
            self._pending = (snap, step)
            self._cv.notify_all()

    def _loop(self):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        while True:
            with self._cv:
                while self._pending is None and not self._stop:
                    self._cv.wait(timeout=0.2)
                if self._pending is None:
                    return  # stopped with nothing left to write
                (snap, step), self._pending = self._pending, None
                self._busy = True
            try:
                with tm.span("elastic.publish", step=step):
                    ModelSerializer.write_snapshot(snap, self.path)
                tm.counter("elastic.publishes_total")
                tm.gauge("elastic.last_publish_step", step)
            except Exception as e:  # noqa: BLE001 — serving seam
                tm.counter("elastic.publish_errors_total")
                if self.log:
                    self.log(f"ELASTIC publish to {self.path} failed at "
                             f"step {step}: {e!r}")
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def flush(self, timeout: float = 60.0) -> bool:
        """Block until everything handed to :meth:`publish` is on disk —
        fit() calls this before returning so the FINAL weights' archive is
        durable when training ends."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while (self._pending is not None or self._busy) \
                    and time.monotonic() < deadline:
                self._cv.wait(timeout=0.1)
            return self._pending is None and not self._busy

    def stop(self, timeout: float = 60.0):
        """Flush, then end the writer thread. Each fit() tears its
        publisher down (and lazily recreates on the next publish) so a
        process that builds trainers repeatedly does not accumulate idle
        publisher threads. A flush that times out is LOUD — the
        "final weights durable when fit() returns" contract just broke,
        and the watcher would otherwise serve stale weights with zero
        signal."""
        if not self.flush(timeout=timeout):
            tm.counter("elastic.publish_flush_timeouts_total")
            if self.log:
                self.log(f"ELASTIC publish flush timed out after "
                         f"{timeout}s — the final archive at {self.path} "
                         "may be stale")
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout)


# ----------------------------------------------------------------- trainer
_ACTIVE: "weakref.WeakValueDictionary[int, ElasticTrainer]" = \
    weakref.WeakValueDictionary()
_ACTIVE_SEQ = 0


def current_status() -> Dict[str, dict]:
    """Live elastic-runtime status for /healthz's membership section
    (util/ui_server.py) and the telemetry default collector."""
    return {f"trainer-{k}": t.status() for k, t in sorted(_ACTIVE.items())}


class ElasticTrainer:
    """Supervised elastic training loop (module docstring has the story).

    ``model``: a MultiLayerNetwork / ComputationGraph, or a ParallelWrapper
    (the wrapper's sharded step is supervised; its inner model is what gets
    checkpointed). ``membership=None`` runs single-member (world 1) with
    every other protection — auto-resume, drain, rollback — still active.

    Knobs: ``checkpoint_every`` steps between periodic checkpoints
    (asynchronous by default: the commit I/O overlaps the next steps;
    ``async_checkpoint=False`` forces blocking saves); ``monitor`` a
    TrainingHealthMonitor to install (default: one with ``action="rollback"``
    when ``rollback_on_anomaly``); ``max_rollbacks`` bounds restore loops so
    a deterministically-NaN model still fails loudly; ``drain_signals`` are
    trapped for graceful drain (finish step -> checkpoint -> leave), the
    SIGTERM every preemption notice delivers.
    """

    def __init__(self, model, directory: str, checkpoint_every: int = 200,
                 keep: int = 3, membership: Optional[FileMembership] = None,
                 monitor=None, rollback_on_anomaly: bool = True,
                 max_rollbacks: int = 3, async_checkpoint: bool = True,
                 initial_checkpoint: bool = True,
                 retry: Optional[RetryPolicy] = None,
                 publish_archive: Optional[str] = None,
                 drain_signals=(signal.SIGTERM,), log_fn=print):
        global _ACTIVE_SEQ
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        self.wrapper = model if isinstance(model, ParallelWrapper) else None
        self.net = model.model if self.wrapper is not None else model
        # retry=None means "checkpointer default" (_IO_RETRY), not "no
        # retry" — passing None through would silently disable the retried
        # checkpoint I/O this runtime's whole contract depends on
        if retry is None:
            self.ckpt = ShardedCheckpointer(directory, keep=keep,
                                            log_fn=log_fn)
        else:
            self.ckpt = ShardedCheckpointer(directory, keep=keep,
                                            retry=retry, log_fn=log_fn)
        self.checkpoint_every = checkpoint_every
        self.membership = membership
        self.rollback_on_anomaly = rollback_on_anomaly
        self.max_rollbacks = max_rollbacks
        self.async_checkpoint = async_checkpoint
        #: blocking save at fit() start guaranteeing a rollback target
        #: before the first anomaly can hit; False skips it (a startup-cost
        #: escape hatch when rollback protection is not wanted)
        self.initial_checkpoint = initial_checkpoint
        #: train→serve seam (docs/SERVING.md#resilience): every checkpoint
        #: cadence ALSO publishes a ModelSerializer archive here (atomic
        #: tmp+os.replace — a watching ModelRouter.watch() poller reloads
        #: it under live traffic, never reading a torn file). The training
        #: thread captures a same-step HOST snapshot right at the
        #: checkpoint point (the device→host copy is mandatory — the next
        #: step donates the param buffers, the checkpointer's
        #: _host_snapshot rule); a background publisher thread pays the
        #: DEFLATE + write, so the step loop never stalls on compression
        #: (latest-wins: a slow disk collapses intermediate publishes
        #: instead of queueing behind them).
        self.publish_archive = publish_archive
        self._publisher: Optional[_ArchivePublisher] = None
        if self.publish_archive is not None:
            # commit correlation for the serving watcher's trace: one
            # instant per durable checkpoint commit (async commits fire
            # this from the background committer)
            self.ckpt.add_commit_hook(
                lambda step: tm.instant("elastic.commit", step=step,
                                        publish=str(self.publish_archive)))
        self.drain_signals = tuple(drain_signals)
        self.log = log_fn
        if monitor is None and rollback_on_anomaly:
            monitor = TrainingHealthMonitor(action="rollback", log_fn=log_fn)
        self.monitor = monitor

        self.state = "idle"
        self.rollbacks = 0
        self.resumed_from: Optional[int] = None
        self.drained = False
        self._drain_requested = False
        self._batch_in_epoch = 0
        self._steps_since_ckpt = 0
        self._view: Optional[MembershipView] = None
        self._is_graph = hasattr(self.net, "topo")
        _ACTIVE_SEQ += 1
        _ACTIVE[_ACTIVE_SEQ] = self

    # ------------------------------------------------------------- stepping
    def _step(self, ds):
        if self.wrapper is not None:
            self.wrapper.step_batch(ds)
        elif self._is_graph:
            from deeplearning4j_tpu.nn.computation_graph import _mask_dict

            feats = (list(ds.features)
                     if isinstance(ds.features, (list, tuple))
                     else [ds.features])
            labs = (list(ds.labels) if isinstance(ds.labels, (list, tuple))
                    else [ds.labels])
            self.net._fit_batch(
                feats, labs,
                mask=_mask_dict(ds, self.net.conf.inputs,
                                "features_mask", "features_masks"),
                label_mask=_mask_dict(ds, self.net.conf.outputs,
                                      "labels_mask", "labels_masks"))
        else:
            self.net._fit_batch(
                ds.features, ds.labels,
                mask=getattr(ds, "features_mask", None),
                label_mask=getattr(ds, "labels_mask", None))

    def _end_epoch(self):
        if self.wrapper is not None:
            self.wrapper.end_epoch()
        else:
            self.net._end_epoch()

    @staticmethod
    def _poison(ds):
        """inject_nan: a REAL poisoned batch — the NaN flows through the
        actual forward/backward so the detection and rollback exercised are
        the production ones, not a simulation of them."""
        import copy

        bad = copy.copy(ds)
        feats = ds.features
        if isinstance(feats, (list, tuple)):
            bad.features = [np.full(np.shape(f), np.nan, np.float32)
                            for f in feats]
        else:
            bad.features = np.full(np.shape(feats), np.nan, np.float32)
        return bad

    # ---------------------------------------------------------- checkpoints
    def _checkpoint(self, block: bool = False):
        # under sync_every>1 per-step losses are queued: flush so the
        # monitor evaluates (and can veto, via RollbackSignal) every step
        # up to this point BEFORE it is committed as a "good" checkpoint
        disp = getattr(self.net, "_dispatcher", None)
        if disp is not None:
            disp.flush()
        # pipelined trainers keep the live state stage-stacked on device
        # (parallel/pipelined.py); pull it back into the net's model layout
        # (bit-exact unstack) so the checkpoint — and the publish snapshot
        # right after — carry the CURRENT weights
        sync = getattr(self.wrapper, "sync_model", None)
        if sync is not None:
            sync()
        meta = {
            "batch_in_epoch": self._batch_in_epoch,
            "epoch": self.net.epoch,
            "world": self._view.world if self._view else 1,
            "rank": self._view.rank if self._view else 0,
        }
        self.ckpt.save(self.net.iteration, self.net, extra_meta=meta,
                       block=block or not self.async_checkpoint)
        if self.publish_archive is not None:
            self._publish()
        self._steps_since_ckpt = 0

    def _publish(self):
        """Hand this checkpoint's weights to the background publisher: the
        HOST snapshot is captured HERE on the training thread so archive
        and checkpoint carry the same step (and so no device ref outlives
        the next step's donation); the DEFLATE + atomic write happen on
        the publisher thread. A publish failure is loud but must not kill
        training — the checkpoint itself already committed; the watcher
        simply keeps serving the previous version."""
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        try:
            snap = ModelSerializer.snapshot(self.net)
        except Exception as e:  # noqa: BLE001 — serving seam, not training
            tm.counter("elastic.publish_errors_total")
            if self.log:
                self.log(f"ELASTIC publish snapshot failed at step "
                         f"{self.net.iteration}: {e!r}")
            return
        if self._publisher is None:
            self._publisher = _ArchivePublisher(self.publish_archive,
                                                log_fn=self.log)
        self._publisher.publish(snap, self.net.iteration)

    def _resume(self) -> Optional[int]:
        step = self.ckpt.restore_latest_good(self.net)
        if step is None:
            return None
        meta = self.ckpt.load_meta(step)
        self._batch_in_epoch = int(meta.get("batch_in_epoch", 0))
        self.resumed_from = step
        tm.counter("elastic.resumes_total")
        tm.instant("elastic.resume", step=step, epoch=self.net.epoch,
                   batch_in_epoch=self._batch_in_epoch)
        if self.log:
            self.log(f"ELASTIC resume from checkpoint step {step} "
                     f"(epoch {self.net.epoch}, "
                     f"batch {self._batch_in_epoch})")
        return step

    def _rollback(self, sig: RollbackSignal):
        if self.rollbacks >= self.max_rollbacks:
            raise RuntimeError(
                f"elastic rollback budget exhausted "
                f"({self.max_rollbacks}); last anomaly: {sig}") from sig
        self.ckpt.wait_until_finished()
        step = self.ckpt.restore_latest_good(self.net)
        if step is None:
            raise RuntimeError(
                "health anomaly with no checkpoint to roll back to"
            ) from sig
        self.rollbacks += 1
        meta = self.ckpt.load_meta(step)
        self._batch_in_epoch = int(meta.get("batch_in_epoch", 0))
        self._steps_since_ckpt = 0
        if self.monitor is not None:
            self.monitor.reset()  # bands described the poisoned run
        tm.counter("elastic.rollbacks_total")
        tm.instant("elastic.rollback", step=step, kind=sig.kind)
        tm.set_health("elastic.rollback", True,
                      f"rolled back to step {step} after {sig.kind}")
        if self.log:
            self.log(f"ELASTIC rollback to checkpoint step {step} after "
                     f"{sig.kind} ({sig.detail}); "
                     f"{self.max_rollbacks - self.rollbacks} budget left")

    # ---------------------------------------------------------------- drain
    def _on_drain_signal(self, signum, frame):
        self._drain_requested = True
        tm.counter("elastic.drain_signals_total")
        if self.log:
            self.log(f"ELASTIC drain requested (signal {signum}): finishing "
                     "the in-flight step, checkpointing, leaving")

    # ------------------------------------------------------------------ fit
    def fit(self, iterator, epochs: int = 1):
        """Supervised fit: resume -> (regroup -> shard -> step/checkpoint)*
        -> final checkpoint. Returns the model. ``self.drained`` tells a
        CLI wrapper to exit 0 (preemption honored, work saved).

        NOTE: unlike ``MultiLayerNetwork.fit`` (which runs ``epochs`` MORE
        epochs), ``epochs`` here is the ABSOLUTE target epoch count — the
        loop runs until ``model.epoch == epochs``. That is what makes
        resume idempotent: however many times the process is killed and
        restarted with the same call, the total work is the same. A model
        already at the target trains zero steps."""
        injector = fl.get_injector()
        net = self.net
        self.state = "running"
        self._drain_requested = False
        self.drained = False

        installed_monitor = False
        if self.monitor is not None and self.monitor not in net.listeners:
            net.listeners.append(self.monitor)
            installed_monitor = True
        old_handlers = {}
        if threading.current_thread() is threading.main_thread():
            for sig in self.drain_signals:
                old_handlers[sig] = signal.signal(sig, self._on_drain_signal)
        if self.membership is not None:
            self.membership.start()
        try:
            resumed = self._resume()
            if resumed is None:
                self._batch_in_epoch = 0
                if self.initial_checkpoint:
                    # a rollback target exists before the first anomaly can
                    # hit; after a resume the restored checkpoint already IS
                    # that target — re-saving it would be pure startup I/O
                    self._checkpoint(block=True)
            while net.epoch < epochs:
                if self.membership is not None:
                    prev_view = self._view
                    self._view = self.membership.regroup(net.epoch)
                    if (self.wrapper is not None and prev_view is not None
                            and self._view is not None
                            and self._view.world != prev_view.world):
                        # world changed at the barrier: re-place model state
                        # and recompile the GSPMD step onto the CURRENT
                        # device view (reshard() with no mesh re-derives it
                        # from jax.devices(), which on a real pod reflects
                        # the survivors) — the sharding layout is part of
                        # the compile key, so the shrunken mesh gets its
                        # own executable (docs/DISTRIBUTED.md). On one host
                        # the local device set is unchanged and this is a
                        # cheap re-placement; on a real pod it is the
                        # data-plane half of the regroup.
                        self.wrapper.reshard()
                        tm.instant("elastic.reshard", epoch=net.epoch,
                                   world=self._view.world)
                try:
                    done = self._run_epoch(iterator, injector)
                    if done:
                        self._batch_in_epoch = 0
                        # under sync_every>1 the coalesced dispatcher
                        # flushes HERE, so the monitor's anomaly for a
                        # late-window step can surface from _end_epoch —
                        # it must land in the same rollback catch
                        self._end_epoch()
                        self._checkpoint(block=False)
                except RollbackSignal as sig:
                    self._rollback(sig)
                    continue
                if not done:  # drained mid-epoch
                    break
            self.ckpt.wait_until_finished()
            try:
                self._checkpoint(block=True)
            except RollbackSignal as sig:
                # a drain interrupted a window whose pending losses carry
                # an anomaly: restore the good state, then save THAT
                self._rollback(sig)
                self._checkpoint(block=True)
            if self._drain_requested:
                self.drained = True
                self.state = "drained"
                tm.counter("elastic.drains_total")
                tm.set_health("elastic.drained", True,
                              f"drained at step {net.iteration}")
                if self.log:
                    self.log(f"ELASTIC drained at step {net.iteration} "
                             f"(epoch {net.epoch}); checkpoint committed")
            else:
                self.state = "completed"
            return net
        except BaseException:
            self.state = "failed"
            raise
        finally:
            for sig, h in old_handlers.items():
                signal.signal(sig, h)
            if self.membership is not None:
                self.membership.stop(graceful=True)
            try:
                self.ckpt.wait_until_finished()
            except Exception:  # noqa: BLE001 — don't mask the real error
                pass
            if self._publisher is not None:
                # the final weights' archive must be durable when fit()
                # returns (the watcher's "follows training" contract);
                # stop() also ends the writer thread — the next fit()
                # lazily recreates it
                try:
                    self._publisher.stop()
                except Exception:  # noqa: BLE001 — don't mask the error
                    pass
                self._publisher = None
            if installed_monitor and self.monitor in net.listeners:
                net.listeners.remove(self.monitor)

    def _run_epoch(self, iterator, injector) -> bool:
        """One epoch under the current view. Returns False when a drain
        interrupted it (cursor checkpointed), True when it completed."""
        net = self.net
        if hasattr(iterator, "reset"):
            iterator.reset()
        cursor = self._batch_in_epoch  # batches already done before resume
        for i, ds in enumerate(iterator):
            if i < cursor:
                continue  # fast-forward: the checkpoint covers these
            if self._view is not None and not self._view.owns_batch(i):
                self._batch_in_epoch = i + 1
                continue
            if injector.fire(fl.SIGKILL_HOST, step=net.iteration):
                os.kill(os.getpid(), signal.SIGKILL)  # hard host loss
            fault = injector.fire(fl.INJECT_NAN, step=net.iteration)
            if fault is not None:
                ds = self._poison(ds)
            with tm.span("elastic.step", iteration=net.iteration):
                self._step(ds)
            self._batch_in_epoch = i + 1
            self._steps_since_ckpt += 1
            if self._steps_since_ckpt >= self.checkpoint_every:
                self._checkpoint(block=False)
            if self._drain_requested:
                return False
        return True

    # ---------------------------------------------------------------- status
    def status(self) -> dict:
        out = {
            "state": self.state,
            "epoch": self.net.epoch,
            "iteration": self.net.iteration,
            "checkpoint_dir": self.ckpt.directory,
            "last_checkpoint_step": self.ckpt.latest_step(),
            "checkpoint_every": self.checkpoint_every,
            "rollbacks": self.rollbacks,
            "resumed_from": self.resumed_from,
            "drained": self.drained,
            "publish_archive": self.publish_archive,
        }
        comp = getattr(self.wrapper, "_compressor", None) \
            if self.wrapper is not None else None
        if comp is not None:
            # encoded-collectives surface (docs/DISTRIBUTED.md#gradient-
            # compression): scheme + whether the residual state a regroup/
            # resume must migrate is currently resident. Stats stay
            # device-side here — /healthz must never force a sync.
            out["grad_compression"] = {
                "scheme": comp.scheme,
                "hosts": comp.hosts,
                "residual_resident": self.wrapper._comp_state is not None,
            }
        if self.membership is not None:
            out["membership"] = self.membership.status()
        else:
            out["membership"] = {"world": 1, "rank": 0, "members": [0]}
        return out


def bootstrap_elastic(membership_dir: str, process_id: int,
                      num_processes: int, coordinator: Optional[str] = None,
                      retry: Optional[RetryPolicy] = None,
                      **membership_kw) -> FileMembership:
    """Compose the r7 DCN bootstrap with the membership layer: run
    ``distributed.initialize`` (PJRT gRPC control plane) under the retried
    handshake, then stand up heartbeats over ``membership_dir``. On real
    multi-host hardware this is the full stack — GSPMD collectives for the
    data plane, file heartbeats + epoch regroup for supervision; with
    ``coordinator=None`` (single process / membership-only tests) the jax
    bootstrap is skipped and only the membership layer starts."""
    from deeplearning4j_tpu.parallel import distributed

    if coordinator is not None:
        distributed.initialize(
            coordinator=coordinator, num_processes=num_processes,
            process_id=process_id,
            retry=retry if retry is not None else distributed.BOOTSTRAP_RETRY)
    return FileMembership(membership_dir, process_id=process_id,
                          world_size=num_processes, **membership_kw)


def collect_elastic_gauges() -> list:
    """Telemetry default-collector hook: scrape-time elastic gauges
    (util/telemetry.py install_default_collectors)."""
    out = []
    for name, st in current_status().items():
        lab = {"trainer": name}
        m = st.get("membership") or {}
        if m.get("world") is not None:
            out.append(("elastic.world_size", lab, float(m["world"])))
        if m.get("alive"):
            out.append(("elastic.alive_members", lab, float(len(m["alive"]))))
        out.append(("elastic.rollbacks", lab, float(st["rollbacks"])))
        out.append(("elastic.drained", lab, 1.0 if st["drained"] else 0.0))
    return out
