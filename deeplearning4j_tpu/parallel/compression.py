"""Encoded gradient collectives for the DP hot path (docs/DISTRIBUTED.md).

The source paper's signature distributed feature is
``EncodedGradientsAccumulator`` — threshold/bitmap-encoded gradient sharing
with error-feedback residuals (SURVEY.md §2.2 J16, §3.4). r12 reproduced it
inside ``SharedTrainingMaster``'s vmapped lane; this module brings it to the
DEFAULT ``ParallelWrapper`` DP path: the ONE jit-compiled GSPMD step runs

    per-worker encode(grad + residual) → all-reduce(quantized) → decode
    → update

with the residual and the adaptive threshold living as worker-sharded
RESIDENT donated state — the same invariant as the fused update engine's
master buffers (docs/KERNELS.md): only the encode output moves per step;
the residual never leaves its worker.

Schemes (``grad_compression`` knob, env ``DL4J_TPU_GRAD_COMPRESSION``):

- ``threshold`` — Strom-style threshold quantization: transmit ±t for
  |carried| > t, sparse int32 wire format (4 B/transmitted element). The
  threshold adapts toward ``target_sparsity`` (AdaptiveThresholdAlgorithm
  semantics) and is snapped to a power of two at encode time, which makes
  the error-feedback conservation invariant BIT-EXACT
  (ops/compression.pow2_floor has the numerics argument).
- ``bitmap`` — the same quantized values on libnd4j's dense 2-bit bitmap
  wire format (16 codes per int32): nnz-independent ~1/16 ratio.
- ``onebit`` — Seide/Strom 1-bit sign quantization: per-tensor
  power-of-two scale from mean |carried| each step (no adaptive state),
  bitmap wire format + one scale word per tensor.
- ``none`` — off (the uncompressed partitioner-inserted all-reduce).

``threshold <= 0`` is the exact identity encode (everything transmits at
full precision, residual stays zero) — proven bit-identical to the
uncompressed deterministic lane path in tests/test_compression.py.

Hierarchical two-level mode (``hosts > 1``): the worker lanes factor as
(hosts, lanes_per_host); the intra-host combine stays FULL-PRECISION (the
ICI reduce-scatter r12 built — cheap bandwidth), and only the per-host
partial gradient is encoded and exchanged across the ``hosts`` axis — the
DCN seam whose control plane r7 bootstrapped. With power-of-two factors the
grouped pairwise-tree association equals the flat tree, so ``hosts`` does
not change the t→0 identity. Wire accounting then prices the CROSS-HOST
payload only (that is the scarce link).

CPU-backend honesty (the r6 convention): this container cannot measure DCN
wall-clock — what CPU proves is the conservation invariant, the t→0
bit-identity, the deterministic wire-bytes ratio, and convergence parity;
the wire-bytes accounting computes what the encoded transport ships, it is
not a packet capture. Rankings belong to real hardware.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.compression import (
    onebit_encode,
    threshold_encode_exact,
)
from deeplearning4j_tpu.parallel import gspmd

SCHEMES = ("none", "threshold", "bitmap", "onebit")


def validate_scheme(scheme: Optional[str]) -> Optional[str]:
    """None passes through (defer to conf/env); anything else must be one
    of SCHEMES — fail at construction, not at trace time."""
    if scheme is None:
        return None
    if scheme not in SCHEMES:
        raise ValueError(
            f"grad_compression must be one of {SCHEMES}, got {scheme!r}")
    return scheme


def resolve_scheme(explicit: Optional[str], conf) -> str:
    """Wrapper-arg > conf.grad_compression > DL4J_TPU_GRAD_COMPRESSION env
    default (already folded into new confs by nn/conf.py) > 'none'."""
    if explicit is not None:
        return validate_scheme(explicit)
    from_conf = getattr(conf, "grad_compression", None) or "none"
    return validate_scheme(from_conf)


def _tmap(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def sparse_wire_bytes(n_leaves: int, nnz, workers):
    """ONE participant's sparse threshold-format payload: one int32 per
    transmitted element (sign folded into the index sign bit —
    ops/compression.sparse_pack) plus a per-leaf (length, threshold)
    header. The single definition of the wire format's byte math, shared
    by GradCompressor and SharedTrainingMaster's gauges."""
    return (nnz / jnp.asarray(float(workers), jnp.float32)) * 4.0 \
        + 8.0 * float(n_leaves)


@dataclasses.dataclass(frozen=True)
class GradCompressor:
    """Pure-function encode → combine → decode core of the compressed
    all-reduce. Stateless itself; the residual/threshold live in the step's
    donated state (``init_state`` builds them, ``encode_combine`` threads
    them). Everything is jittable and vmap-free — the worker axis is the
    leading dimension of every array, exactly how the wrapper's lane
    machinery stacks it."""

    scheme: str = "threshold"
    initial_threshold: float = 1e-3
    #: desired fraction of transmitted elements (threshold/bitmap adapt
    #: toward it with the AdaptiveThresholdAlgorithm rule: ×decay when
    #: >3x target, ÷decay when <target/3)
    target_sparsity: float = 1e-3
    decay: float = 1.2
    min_threshold: float = 1e-8
    max_threshold: float = 1.0
    #: >1 = hierarchical two-level mode: intra-host full-precision combine
    #: over lanes_per_host, encode only across the ``hosts`` axis
    hosts: int = 1

    def __post_init__(self):
        if self.scheme not in SCHEMES or self.scheme == "none":
            raise ValueError(f"GradCompressor needs an active scheme "
                             f"(threshold|bitmap|onebit), got {self.scheme!r}")
        if self.hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {self.hosts}")

    # ------------------------------------------------------------------ state
    def exchange_axis(self, replicas: int) -> int:
        """How many participants exchange encoded payloads: the hosts axis
        in hierarchical mode, every worker lane otherwise."""
        if self.hosts > 1:
            if replicas % self.hosts:
                raise ValueError(
                    f"hierarchical compression needs hosts ({self.hosts}) "
                    f"to divide the lane count ({replicas})")
            return self.hosts
        return replicas

    def init_state(self, grads_template, replicas: int):
        """Residual (zeros, stacked over the exchange axis) + threshold
        scalar. ``grads_template``: ONE worker's gradient pytree (or the
        fused engine's list of flat group buffers) — leaf shapes without
        the worker axis."""
        w = self.exchange_axis(replicas)
        residual = _tmap(
            lambda g: jnp.zeros((w,) + tuple(np.shape(g)),
                                jnp.asarray(g).dtype), grads_template)
        return {"residual": residual,
                "threshold": jnp.asarray(self.initial_threshold, jnp.float32)}

    def state_matches(self, state, grads_template, replicas: int) -> bool:
        """Whether a restored/migrated state tree fits this compressor's
        shapes (lane-count and scheme changes make it unusable)."""
        try:
            want = self.init_state(grads_template, replicas)
        except ValueError:
            return False
        ws = jax.tree_util.tree_structure(want)
        hs = jax.tree_util.tree_structure(state)
        if ws != hs:
            return False
        return all(tuple(np.shape(a)) == tuple(np.shape(b))
                   for a, b in zip(jax.tree_util.tree_leaves(want),
                                   jax.tree_util.tree_leaves(state)))

    # ----------------------------------------------------------------- encode
    def _encode_leaf(self, carried, threshold):
        if self.scheme in ("threshold", "bitmap"):
            return threshold_encode_exact(carried, threshold)
        # onebit: per-(worker, tensor) scale from mean |carried|, derived
        # each step; keep the worker axis, reduce everything else
        axes = tuple(range(1, carried.ndim))
        s = jnp.mean(jnp.abs(carried), axis=axes, keepdims=True) \
            if axes else jnp.abs(carried)
        q, r, _ = onebit_encode(carried, s)
        return q, r

    def encode_combine(self, stacked_grads, state, inv):
        """One compressed exchange: per-worker error-feedback encode, the
        deterministic pairwise-tree combine of the quantized payloads (the
        all-reduce), dense decode, weighted-mean normalization by ``inv``.

        ``stacked_grads``: pytree of (R, ...) lane-stacked (weight-scaled)
        gradients. Returns ``(combined, new_state, stats)`` where
        ``combined`` matches the uncompressed combine's tree structure and
        ``stats`` carries the deterministic wire-bytes accounting
        (device scalars — fetch at window cadence, not per step)."""
        leaves = jax.tree_util.tree_leaves(stacked_grads)
        if not leaves:
            raise ValueError("encode_combine: empty gradient tree")
        replicas = int(leaves[0].shape[0])
        w = self.exchange_axis(replicas)
        if w != replicas:
            local = replicas // w
            # intra-host FULL-PRECISION combine (the ICI leg): grouped
            # pairwise tree — with pow2 factors the association equals the
            # flat pairwise tree, preserving the t→0 identity
            contrib = _tmap(
                lambda v: jax.vmap(gspmd.pairwise_sum)(
                    v.reshape((w, local) + v.shape[1:])), stacked_grads)
        else:
            contrib = stacked_grads
        carried = _tmap(lambda g, r: g + r, contrib, state["residual"])
        t = state["threshold"]
        enc = _tmap(lambda c: self._encode_leaf(c, t), carried)
        is_pair = lambda x: isinstance(x, tuple)  # noqa: E731
        quant = jax.tree_util.tree_map(lambda x: x[0], enc, is_leaf=is_pair)
        new_res = jax.tree_util.tree_map(lambda x: x[1], enc,
                                         is_leaf=is_pair)

        q_leaves = jax.tree_util.tree_leaves(quant)
        nnz = sum(jnp.sum(q != 0).astype(jnp.float32) for q in q_leaves)
        elems = sum(int(np.prod(q.shape[1:] or (1,))) for q in q_leaves)
        sparsity = nnz / jnp.asarray(float(w * elems), jnp.float32)
        new_t = self._update_threshold(t, sparsity)

        combined = _tmap(
            lambda v: gspmd.pairwise_sum(v) * inv.astype(v.dtype), quant)
        stats = self._wire_stats(q_leaves, nnz, w, t)
        return combined, {"residual": new_res, "threshold": new_t}, stats

    def _update_threshold(self, t, sparsity):
        if self.scheme == "onebit":
            return t  # scale derives per step; no adaptive state
        too_dense = sparsity > self.target_sparsity * 3.0
        too_sparse = sparsity < self.target_sparsity / 3.0
        adapted = jnp.where(
            too_dense, t * self.decay,
            jnp.where(too_sparse, t / self.decay, t))
        adapted = jnp.clip(adapted, self.min_threshold, self.max_threshold)
        # t <= 0 is the pinned identity mode: never adapt out of it
        return jnp.where(t > 0, adapted, t)

    # ------------------------------------------------------------ wire bytes
    def _wire_stats(self, q_leaves, nnz, workers, t):
        """Deterministic accounting of ONE participant's encoded payload vs
        its dense fp32 payload (what the r6 convention lets CPU claim: the
        byte math, not the wall clock)."""
        n_leaves = float(len(q_leaves))
        dense = float(sum(
            int(np.prod(q.shape[1:] or (1,)))
            * jnp.dtype(q.dtype).itemsize for q in q_leaves))
        elems = float(sum(int(np.prod(q.shape[1:] or (1,)))
                          for q in q_leaves))
        if self.scheme == "threshold":
            wire = sparse_wire_bytes(len(q_leaves), nnz, workers)
            # identity mode ships dense fp32
            wire = jnp.where(t > 0, wire, dense)
        else:
            # 2-bit bitmap: 16 codes per int32 word, one scale/threshold
            # word per leaf (onebit ships its per-tensor scale the same way)
            words = float(sum(-(-int(np.prod(q.shape[1:] or (1,))) // 16)
                              for q in q_leaves))
            wire = jnp.asarray(words * 4.0 + 4.0 * n_leaves, jnp.float32)
            if self.scheme == "bitmap":
                wire = jnp.where(t > 0, wire, dense)
        wire = jnp.asarray(wire, jnp.float32)
        return {
            "wire_bytes": wire,
            "dense_bytes": jnp.asarray(dense, jnp.float32),
            "ratio": wire / jnp.asarray(dense, jnp.float32),
            "nnz": nnz,
            "elements": jnp.asarray(elems, jnp.float32),
            "workers": jnp.asarray(float(workers), jnp.float32),
        }
