"""ParallelWrapper + ParallelInference — multi-device training/serving parity.

Reference: org/deeplearning4j/parallelism/{ParallelWrapper,ParallelInference}
.java (SURVEY.md §3.5: thread-per-GPU replicas, gradient averaging or
threshold-encoded sharing through EncodedGradientsAccumulator, round-robin
inference replicas) — path-cite, mount empty this round.

TPU-native collapse: there are no replicas, no trainer threads, no
accumulator. The SAME jitted train step as single-device, compiled with the
batch sharded over the mesh 'data' axis and params replicated — GSPMD inserts
one fused gradient ``all-reduce`` over ICI per step. Synchronous averaging
every iteration (the reference's averaging mode with frequency=1) is exact
here and costs one collective; the async/compressed machinery existed to hide
slow interconnects that ICI does not have. The encoded-gradient machinery
survives as the ``grad_compression`` knob (parallel/compression.py,
docs/DISTRIBUTED.md#gradient-compression): per-worker error-feedback
encode → all-reduce(quantized) → decode inside the lane-decomposed step,
for the DCN-bound regimes where wire bytes are the scarce resource.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel import gspmd
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.util import telemetry as tm


class ParallelWrapper:
    """Data-parallel fit over a device mesh (ParallelWrapper.fit parity).

    Usage:
        pw = ParallelWrapper(net)            # all local devices
        pw.fit(iterator, epochs=2)
        # net.params are updated in place (replicated arrays)

    Two execution modes, both ONE ``jit``-compiled GSPMD program per step
    (docs/DISTRIBUTED.md):

    - default: the model's own step with the batch sharded over 'data' and
      params replicated; the partitioner inserts the fused gradient
      all-reduce. With ``zero_optimizer=True`` (default) the optimizer
      moments are additionally ZeRO-sharded over 'data'
      (``with_sharding_constraint`` — arXiv:2004.13336): the weight update
      becomes reduce-scatter → 1/N-sharded update → all-gather, cutting
      per-chip optimizer memory and update compute ~Nx.
    - ``deterministic=True``: the batch is decomposed into a fixed number of
      ``replicas`` lanes (vmapped, lane axis sharded) and cross-lane
      combines use explicit pairwise-tree adds (parallel/gspmd.py), making
      the fit BIT-identical across mesh sizes — an 8-device sharded fit
      reproduces the single-device fit exactly (params, Adam moments, RNG
      key), proven in tests/test_gspmd_identity.py. TBPTT segments are
      supported on MultiLayerNetworks.

    Telemetry: every step records a ``parallel.step`` dispatch span; every
    ``skew_every`` steps a completion probe watches each replica's loss
    shard become ready, emits one ``parallel.replica_step`` span per replica
    row on the merged trace, and publishes the max−min completion spread as the
    ``parallel.straggler_skew_seconds`` gauge (per-replica timing/skew
    visibility — arxiv 2004.13336's prerequisite for scaling the
    distributed path). The probe is a deliberate sync point, which is why
    it runs at window cadence, not per step; ``skew_every=0`` disables it.
    On a single-host CPU mesh the compiled all-reduce has already
    synchronized the replicas, so the skew reads ≈0 there — the gauge is
    meaningful on real multi-chip ICI. ``_build`` additionally publishes
    the mesh axis sizes, the ZeRO sharded fraction, and the per-device
    optimizer-state bytes as gauges, and keeps the full per-leaf layout
    table on ``self.layout``.
    """

    def __init__(self, model, workers: Optional[int] = None,
                 mesh: Optional[TrainingMesh] = None, prefetch: int = 2,
                 skew_every: int = 10, zero_optimizer: bool = True,
                 deterministic: bool = False, replicas: Optional[int] = None,
                 grad_compression=None,
                 compression_threshold: Optional[float] = None,
                 compression_target_sparsity: Optional[float] = None,
                 compression_hosts: Optional[int] = None):
        from deeplearning4j_tpu.parallel import compression as _comp

        self.model = model
        if mesh is None:
            devices = jax.devices()[: workers or len(jax.devices())]
            mesh = TrainingMesh(data=len(devices), devices=devices)
        self.mesh = mesh
        self.prefetch = prefetch
        self.skew_every = skew_every
        self.zero_optimizer = zero_optimizer
        self.deterministic = deterministic
        if deterministic and (mesh.model != 1 or mesh.seq != 1
                              or mesh.pipe != 1):
            raise ValueError(
                "deterministic lane mode is a data-parallel contract; use a "
                "data-only mesh (model=seq=pipe=1). PipelinedTrainer is "
                "deterministic by construction — its pipe contract is "
                "documented separately (docs/DISTRIBUTED.md)")
        # lane count: fixed at construction so a fit is reproducible across
        # device counts (pass the same replicas on every topology)
        self.replicas = int(replicas if replicas is not None else mesh.data)
        # Encoded gradient collectives (docs/DISTRIBUTED.md#gradient-
        # compression): grad_compression is a scheme name
        # (none|threshold|bitmap|onebit), a prebuilt GradCompressor, or
        # None (defer to conf.grad_compression, which folds in the
        # DL4J_TPU_GRAD_COMPRESSION env default). An active scheme routes
        # the step through the lane decomposition — per-worker gradients
        # are what the error-feedback encode needs, and the lane path's
        # deterministic combine is what makes the t→0 bit-identity and the
        # wire-ratio tests exact.
        if isinstance(grad_compression, _comp.GradCompressor):
            self._compressor = grad_compression
        else:
            scheme = _comp.resolve_scheme(grad_compression, model.conf)
            if scheme == "none":
                self._compressor = None
            else:
                conf = model.conf
                hosts = compression_hosts
                if hosts in (None, "auto"):
                    hosts = self.mesh.dcn_hosts() \
                        if hosts == "auto" else 1
                self._compressor = _comp.GradCompressor(
                    scheme=scheme,
                    initial_threshold=(
                        compression_threshold
                        if compression_threshold is not None
                        else getattr(conf, "grad_compression_threshold",
                                     1e-3)),
                    target_sparsity=(
                        compression_target_sparsity
                        if compression_target_sparsity is not None
                        else getattr(conf, "grad_compression_target", 1e-3)),
                    hosts=int(hosts))
        if self._compressor is not None:
            self._compressor.exchange_axis(self.replicas)  # fail fast
            engine = getattr(model, "_fused", None)
            if engine is not None and engine.loss_scale == "dynamic":
                raise ValueError(
                    "grad_compression with loss_scale='dynamic' is not "
                    "supported: the residual accumulates in scaled units, "
                    "so a scale change mid-run would silently re-weight "
                    "the carried error — use loss_scale='static' (the "
                    "residual then lives consistently in scaled units) or "
                    "compression 'none'")
        #: compression forces the lane-decomposed step (per-worker grads)
        self._uses_lanes = bool(deterministic or self._compressor)
        self._sharded_step = None
        self._tbptt_step = None
        self._zero_specs = None
        self._param_specs = self._state_specs = self._opt_specs = None
        self._comp_state = None
        self._comp_specs = None
        self._comp_stats = None
        self._stage_jits = None
        self.layout: dict = {}

    def _build(self):
        model = self.model
        if model._train_step is None and not self._uses_lanes:
            raise ValueError("model must be init()ed first")
        if not model.params:
            raise ValueError("model must be init()ed first")
        if self.zero_optimizer and self.mesh.n_devices > 1:
            self._zero_specs = gspmd.zero_shardings(
                self.mesh.mesh, model.opt_states)
        # replicate current model state across the mesh (TP-sharded leaves
        # placed on this mesh keep their sharding); ZeRO places the
        # optimizer state sharded over 'data'
        model.params = self.mesh.replicate(model.params)
        model.states = self.mesh.replicate(model.states)
        if self._zero_specs is not None:
            model.opt_states = gspmd.place_tree(
                model.opt_states, self._zero_specs)
        else:
            model.opt_states = self.mesh.replicate(model.opt_states)
        # pin each step's OUTPUT layouts to the placement just made:
        # without this the partitioner propagates the ZeRO-sharded moments
        # into the updated params, the next step's inputs arrive with a
        # different (partially sharded) layout, and the program silently
        # re-partitions — layout must be a fixed point across steps
        if self.mesh.n_devices > 1:
            from jax.sharding import NamedSharding

            def spec_of(leaf):
                s = getattr(leaf, "sharding", None)
                return s if isinstance(s, NamedSharding) \
                    else self.mesh.replicated()

            self._param_specs = jax.tree_util.tree_map(
                spec_of, model.params)
            self._state_specs = jax.tree_util.tree_map(
                spec_of, model.states)
            self._opt_specs = (self._zero_specs
                               if self._zero_specs is not None
                               else jax.tree_util.tree_map(
                                   spec_of, model.opt_states))
        else:
            self._param_specs = self._state_specs = self._opt_specs = None
        if self._compressor is not None:
            self._place_compression_state()
        self._sharded_step = (self._build_lane_step() if self._uses_lanes
                              else self._build_fast_step())
        self._publish_layout()

    # ------------------------------------------------- compression state
    def _comp_template(self):
        """ONE worker's gradient template: the fused engine's flat group
        buffers when the model fuses its update (the encode then runs on
        exactly what ZeRO reduce-scatters), the param-shaped tree
        otherwise."""
        model = self.model
        engine = getattr(model, "_fused", None)
        if engine is not None:
            return [np.zeros((g.total,), np.float32) for g in engine.groups]
        f32 = lambda p: np.zeros(np.shape(p), np.float32)  # noqa: E731
        if isinstance(model._updaters, dict):
            return {k: jax.tree_util.tree_map(f32, v)
                    for k, v in model.params.items()}
        return [jax.tree_util.tree_map(f32, p) for p in model.params]

    def _place_compression_state(self):
        """Adopt (checkpoint-restored / reshard-migrated) or initialize the
        residual + threshold, place them on the mesh (residual sharded over
        'data' when the exchange axis divides it — worker-sharded RESIDENT
        state, the fused-master invariant), and pin the layout specs the
        step re-asserts every iteration."""
        comp = self._compressor
        template = self._comp_template()
        prior = getattr(self.model, "_grad_comp_state", None)
        if prior is not None and not comp.state_matches(
                prior, template, self.replicas):
            raise ValueError(
                "restored grad-compression state does not match this "
                "wrapper's layout (scheme/replicas/hosts changed between "
                "runs?) — clear model._grad_comp_state to reinitialize, "
                "losing the carried residual")
        state = prior if prior is not None \
            else comp.init_state(template, self.replicas)
        if self.mesh.n_devices > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            d = self.mesh.data

            def spec_of(leaf):
                shape = np.shape(leaf)
                if shape and shape[0] % d == 0:
                    return NamedSharding(
                        self.mesh.mesh,
                        P("data", *([None] * (len(shape) - 1))))
                return self.mesh.replicated()

            self._comp_specs = jax.tree_util.tree_map(spec_of, state)
            state = gspmd.place_tree(state, self._comp_specs)
        else:
            self._comp_specs = None
            state = jax.tree_util.tree_map(jnp.asarray, state)
        self._comp_state = state
        self.model._grad_comp_state = state

    def _adopt_compression_state(self):
        """Re-place the model-side compression state when someone swapped
        it from outside the step loop — a checkpoint restore
        (util/checkpoint.py sets ``model._grad_comp_state``) or a rollback.
        Identity-checked per step: free when nothing changed."""
        if self._compressor is None:
            return
        if getattr(self.model, "_grad_comp_state", None) is self._comp_state:
            return
        self._place_compression_state()

    def _build_fast_step(self):
        # The model's own step function (weighted variant for exact ragged-
        # batch masking), jitted over sharded operands: params replicated,
        # batch split over 'data'. jit infers the SPMD partition from operand
        # shardings (set by device_put in fit); the gradient all-reduce is
        # emitted by the partitioner, not written here.
        base = self.model.make_step_fn(weighted=True)
        zspecs = self._zero_specs
        if self._param_specs is None:
            return jax.jit(base, donate_argnums=(0, 1, 2))
        pspecs, sspecs, ospecs = (self._param_specs, self._state_specs,
                                  self._opt_specs)

        def step(params, states, opts, iteration, x, y, key, w):
            # assert the ZeRO layout on entry and every layout on exit: the
            # partitioner then emits reduce-scatter(grads) -> sharded
            # update -> all-gather(params) instead of N redundant full
            # updates, and the step's output layout equals its input
            # layout (donation-exact, stable across steps)
            if zspecs is not None:
                opts = gspmd.constrain_tree(opts, zspecs)
            p, s, o, loss = base(params, states, opts, iteration, x, y,
                                 key, w)
            return (gspmd.constrain_tree(p, pspecs),
                    gspmd.constrain_tree(s, sspecs),
                    gspmd.constrain_tree(o, ospecs), loss)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    # Determinism note (pinned by tests/test_gspmd_identity.py): the lane
    # step is THREE jit programs, not one. LLVM's FMA contraction fuses a
    # multiply into a following add WITHIN one compiled kernel (and
    # ``optimization_barrier`` does not reach that level), so a lane-weight
    # multiply living in the same kernel as the cross-lane add tree rounds
    # differently on 1 device (fused mul+add) than on 8 (the adds cross
    # device boundaries and cannot contract). Splitting at jit boundaries
    # forces materialization: stage A ends in multiplies (no consumer
    # adds), stage B is slices+adds with post-multiplies only (no
    # contractible mul→add), stage C is the elementwise updater — each
    # stage is topology-invariant, so the composition is bit-identical on
    # every mesh size.
    def _lane_combine_fns(self):
        sspecs = self._state_specs
        comp = self._compressor
        cspecs = self._comp_specs
        model = self.model
        engine = getattr(model, "_fused", None)
        comp_flat = comp is not None and engine is not None

        def combine(loss_s, s_l, states_l, scaled_g):
            total = gspmd.pairwise_sum(s_l)
            inv = 1.0 / jnp.where(total == 0.0, 1.0, total)
            grads = jax.tree_util.tree_map(
                lambda t: gspmd.pairwise_sum(t) * inv.astype(t.dtype),
                scaled_g)
            loss = gspmd.pairwise_sum(loss_s) * inv
            new_states = gspmd.combine_states(states_l)
            if sspecs is not None:
                new_states = gspmd.constrain_tree(new_states, sspecs)
            return loss, grads, new_states

        def combine_compressed(loss_s, s_l, states_l, scaled_g, comp_state):
            """The combine stage with the encoded exchange spliced in
            where the cross-lane gradient sum used to be: per-worker
            error-feedback encode → deterministic pairwise all-reduce of
            the quantized payloads → dense decode → weighted-mean
            normalization. With the fused engine, the per-lane gradients
            flatten FIRST (vmapped) so the encode runs on the flat
            per-(rule, dtype) buffers ZeRO reduce-scatters."""
            total = gspmd.pairwise_sum(s_l)
            inv = 1.0 / jnp.where(total == 0.0, 1.0, total)
            payload = (jax.vmap(engine.flatten_grads)(scaled_g)
                       if comp_flat else scaled_g)
            grads, new_comp, stats = comp.encode_combine(
                payload, comp_state, inv)
            loss = gspmd.pairwise_sum(loss_s) * inv
            new_states = gspmd.combine_states(states_l)
            if sspecs is not None:
                new_states = gspmd.constrain_tree(new_states, sspecs)
            if cspecs is not None:
                new_comp = gspmd.constrain_tree(new_comp, cspecs)
            return loss, grads, new_states, new_comp, stats

        zspecs = self._zero_specs
        pspecs = self._param_specs

        def update(params, opts, grads, iteration):
            if zspecs is not None:
                opts = gspmd.constrain_tree(opts, zspecs)
            if comp_flat:
                # decode output IS the flat buffer list — feed the fused
                # update directly, no per-leaf round trip
                new_params, new_opts = gspmd.apply_updaters_flat(
                    model, params, grads, opts, iteration)
            else:
                new_params, new_opts = gspmd.apply_updaters(
                    model, params, grads, opts, iteration,
                    scaled_grads=True)
            # pin the output layout to the input layout (see _build): the
            # updated params must come back replicated even though the
            # ZeRO-sharded moments fed the update
            if pspecs is not None:
                new_params = gspmd.constrain_tree(new_params, pspecs)
            if zspecs is not None:
                new_opts = gspmd.constrain_tree(new_opts, zspecs)
            return new_params, new_opts

        j_combine = (jax.jit(combine_compressed, donate_argnums=(4,))
                     if comp is not None else jax.jit(combine))
        return j_combine, jax.jit(update, donate_argnums=(0, 1))

    @staticmethod
    def _lane_scale(loss_l, s_l, grads_l):
        """Lane-side weighting — multiplies whose only consumers are jit
        outputs (the cross-lane adds live in the next jit)."""
        scale = jax.tree_util.tree_map(
            lambda t: t * s_l.reshape(
                s_l.shape + (1,) * (t.ndim - 1)).astype(t.dtype), grads_l)
        return loss_l * s_l, scale

    def _loss_scale_arg(self):
        """The loss-scale multiplier the lane stage multiplies into the
        loss this step (None when the model has no scaling policy): read
        from the CURRENT opt state so the dynamic automaton's value is the
        one this step's gradients are scaled by — the fused apply unscales
        with the same state."""
        engine = getattr(self.model, "_fused", None)
        if engine is None or engine.loss_scale == "none":
            return None
        return engine.current_scale(self.model.opt_states)

    def _run_compressed_combine(self, j_combine, combine_args):
        """Thread the resident compression state through the combine jit
        and keep both wrapper- and model-side references current (the
        model-side one is what checkpoints carry — util/checkpoint.py)."""
        loss, grads, new_states, self._comp_state, self._comp_stats = \
            j_combine(*combine_args, self._comp_state)
        self.model._grad_comp_state = self._comp_state
        return loss, grads, new_states

    def _build_lane_step(self):
        model = self.model
        lane_vg = gspmd.make_lane_value_and_grad(model)
        compressed = self._compressor is not None

        def lanes(params, states, x, y, keys, w, scale):
            # the SAME vmapped program on every topology: on one device it
            # executes unpartitioned, on N the lane axis is sharded — the
            # per-lane values are identical either way (pinned exceptions:
            # conv filter grads and >=1024-wide gemm contractions, whose
            # XLA:CPU lowering is fold-dependent; docs/DISTRIBUTED.md)
            (loss_l, s_l), (states_l, grads_l) = jax.vmap(
                lane_vg, in_axes=(None, None, 0, 0, 0, 0, None, None, None)
            )(params, states, x, y, keys, w, None, None, scale)
            loss_s, scaled = self._lane_scale(loss_l, s_l, grads_l)
            return loss_s, s_l, states_l, scaled

        j_lanes = jax.jit(lanes)
        j_combine, j_update = self._lane_combine_fns()
        self._stage_jits = (j_lanes, j_combine, j_update)

        def step(params, states, opts, iteration, x, y, keys, w):
            loss_s, s_l, states_l, scaled = j_lanes(
                params, states, x, y, keys, w, self._loss_scale_arg())
            if compressed:
                loss, grads, new_states = self._run_compressed_combine(
                    j_combine, (loss_s, s_l, states_l, scaled))
            else:
                loss, grads, new_states = j_combine(loss_s, s_l, states_l,
                                                    scaled)
            new_params, new_opts = j_update(params, opts, grads, iteration)
            return new_params, new_states, new_opts, loss

        return step

    def _build_tbptt_step(self):
        model = self.model
        lane_vg = gspmd.make_lane_tbptt_value_and_grad(model)
        compressed = self._compressor is not None

        def lanes(params, states, carries, x, y, keys, w, fm, lm, scale):
            (loss_l, s_l), (states_l, carries_l, grads_l) = jax.vmap(
                lane_vg, in_axes=(None, None, 0, 0, 0, 0, 0, 0, 0, None)
            )(params, states, carries, x, y, keys, w, fm, lm, scale)
            loss_s, scaled = self._lane_scale(loss_l, s_l, grads_l)
            return loss_s, s_l, states_l, carries_l, scaled

        j_lanes = jax.jit(lanes)
        j_combine, j_update = self._lane_combine_fns()

        def step(params, states, opts, carries, iteration, x, y, keys, w,
                 fm, lm):
            loss_s, s_l, states_l, carries_l, scaled = j_lanes(
                params, states, carries, x, y, keys, w, fm, lm,
                self._loss_scale_arg())
            if compressed:
                loss, grads, new_states = self._run_compressed_combine(
                    j_combine, (loss_s, s_l, states_l, scaled))
            else:
                loss, grads, new_states = j_combine(loss_s, s_l, states_l,
                                                    scaled)
            new_params, new_opts = j_update(params, opts, grads, iteration)
            return new_params, new_states, new_opts, carries_l, loss

        return step

    def _lane_keys(self, sub):
        keys = jax.random.split(sub, self.replicas)
        if self.mesh.n_devices > 1:
            keys = jax.device_put(keys, self.mesh.spec("data"))
        return keys

    def step_batch(self, ds):
        """Run ONE sharded train step on a DataSet (listeners included) —
        the unit the elastic supervisor (parallel/elastic.py) wraps with
        checkpoint/drain/rollback handling. Returns the device loss."""
        import time as _time

        if self._sharded_step is None:
            self._build()
        self._adopt_compression_state()
        model = self.model
        if (self._uses_lanes
                and getattr(model.conf, "tbptt_length", None)
                and not isinstance(model._updaters, dict)
                and np.ndim(ds.features) == 3 and np.ndim(ds.labels) == 3
                and np.shape(ds.features)[1] > model.conf.tbptt_length):
            return self._step_batch_tbptt(ds)
        x, y, w = self._shard(ds.features, ds.labels)
        model._rng_key, sub = jax.random.split(model._rng_key)
        key_arg = self._lane_keys(sub) if self._uses_lanes else sub
        t0 = _time.time_ns()
        with tm.span("parallel.step", iteration=model.iteration,
                     replicas=self.mesh.data):
            model.params, model.states, model.opt_states, loss = (
                self._sharded_step(
                    model.params, model.states, model.opt_states,
                    jnp.asarray(model.iteration), x, y, key_arg, w,
                )
            )
        model.score_value = loss
        model.iteration += 1
        tm.counter("train.steps_total", model="parallel")
        if (self.skew_every and tm.enabled()
                and model.iteration % self.skew_every == 0):
            self._probe_replica_skew(loss, t0)
            self._publish_compression_stats()
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration, model.epoch)
        return loss

    def _step_batch_tbptt(self, ds):
        """Deterministic sharded TBPTT (MultiLayerNetwork): the segment
        loop of ``doTruncatedBPTT`` with every segment one lane-decomposed
        SPMD step — carries stay lane-stacked across segments, gradients
        truncate at segment boundaries, one update per segment."""
        model = self.model
        k = model.conf.tbptt_length
        R = self.replicas
        fm = getattr(ds, "features_mask", None)
        lm = getattr(ds, "labels_mask", None)
        x, y, w, (fm, lm) = self.mesh.pad_lane_batch(
            ds.features, ds.labels, R, extras=(fm, lm))
        if self._tbptt_step is None:
            self._tbptt_step = self._build_tbptt_step()
        b = x.shape[1]
        dtype = model._cast(x).dtype
        carries = jax.tree_util.tree_map(
            lambda c: jnp.broadcast_to(c[None], (R,) + c.shape),
            model._init_carries(b, dtype))
        T = x.shape[2]
        losses = []
        for s in range(0, T, k):
            xs = x[:, :, s:s + k]
            ys = y[:, :, s:s + k] if y.ndim == 4 else y
            ms = None if fm is None else fm[:, :, s:s + k]
            lms = None if lm is None else lm[:, :, s:s + k]
            model._rng_key, sub = jax.random.split(model._rng_key)
            keys = self._lane_keys(sub)
            with tm.span("parallel.tbptt_step", iteration=model.iteration,
                         segment_start=s):
                (model.params, model.states, model.opt_states, carries,
                 loss) = self._tbptt_step(
                    model.params, model.states, model.opt_states, carries,
                    jnp.asarray(model.iteration), xs, ys, keys, w, ms, lms)
            model.iteration += 1
            losses.append(loss)
        model.score_value = float(jnp.mean(jnp.stack(losses)))
        tm.counter("train.steps_total", model="parallel")
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration, model.epoch)
        return model.score_value

    def end_epoch(self):
        """Advance the epoch counter + epoch-end callbacks (the tail of one
        fit() epoch, split out for the elastic supervisor)."""
        model = self.model
        model.epoch += 1
        for lst in model.listeners:
            if hasattr(lst, "on_epoch_end"):
                lst.on_epoch_end(model)

    def fit(self, iterator, epochs: int = 1):
        if self._sharded_step is None:
            self._build()
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                self.step_batch(ds)
            self.end_epoch()
        return self.model

    def _shard(self, x, y):
        if self._uses_lanes:
            return self.mesh.pad_lane_batch(x, y, self.replicas)
        return self.mesh.pad_shard_batch(x, y)

    # --------------------------------------------------- compression stats
    def compression_stats(self) -> Optional[dict]:
        """Latest step's deterministic wire accounting as plain floats
        (one host sync — window-cadence material, not per-step), also
        pushed to the ``parallel.allreduce_*`` telemetry gauges. None when
        compression is off or no compressed step ran yet."""
        if self._comp_stats is None:
            return None
        stats = {k: float(v) for k, v in self._comp_stats.items()}
        thr = self._comp_state.get("threshold") \
            if self._comp_state is not None else None
        if thr is not None:
            stats["threshold"] = float(jax.device_get(thr))
        if tm.enabled():
            tm.gauge("parallel.allreduce_wire_bytes", stats["wire_bytes"])
            tm.gauge("parallel.allreduce_dense_bytes", stats["dense_bytes"])
            tm.gauge("parallel.allreduce_compression_ratio", stats["ratio"])
            tm.counter("parallel.allreduce_wire_bytes_total",
                       value=stats["wire_bytes"])
            tm.counter("parallel.allreduce_exchanges_total")
        return stats

    def _publish_compression_stats(self):
        if self._comp_stats is not None and tm.enabled():
            self.compression_stats()

    # ------------------------------------------------------- layout plumbing
    def _publish_mesh_gauges(self):
        """One gauge per canonical mesh axis — the ONE loop shared with the
        pipelined trainer's layout publisher, so a future axis cannot be
        threaded into one and silently missed in the other."""
        mesh = self.mesh
        for axis in TrainingMesh.AXES:
            tm.gauge("parallel.mesh_axis_size", getattr(mesh, axis),
                     axis=axis)

    def _publish_layout(self):
        """Telemetry gauges + the per-leaf layout table (satellite:
        telemetry reports per-device layouts; docs/OBSERVABILITY.md)."""
        mesh = self.mesh
        self._publish_mesh_gauges()
        frac = (gspmd.sharded_fraction(self._zero_specs)
                if self._zero_specs is not None else 0.0)
        tm.gauge("parallel.zero_state_sharded_fraction", frac)
        tm.gauge("parallel.opt_state_bytes_per_device",
                 self.opt_state_bytes_per_device())
        comp = self._compressor
        self.layout = {
            "signature": mesh.layout_signature(
                extra=(self.zero_optimizer, self.deterministic,
                       self.replicas,
                       (comp.scheme, comp.hosts) if comp else None)),
            "params": gspmd.describe_shardings(self.model.params),
            "opt_states": gspmd.describe_shardings(self.model.opt_states),
        }
        if comp is not None:
            tm.gauge("parallel.grad_compression_hosts", comp.hosts)
            self.layout["grad_compression"] = {
                "scheme": comp.scheme, "hosts": comp.hosts,
                "residual": gspmd.describe_shardings(
                    self._comp_state["residual"]),
            }

    def opt_state_bytes_per_device(self) -> int:
        """Bytes of optimizer state ONE device holds — the ZeRO memory
        number (~1/N of the replicated total when sharded; bench.py
        ``zero_optimizer_memory_bytes_per_device``)."""
        return gspmd.tree_bytes_per_device(self.model.opt_states)

    def reshard(self, mesh: Optional[TrainingMesh] = None):
        """Re-place model state and re-build the compiled step on a NEW
        mesh — the elastic regroup hook (parallel/elastic.py): after worker
        loss the survivors form a shrunken mesh and the same program
        recompiles onto it (the sharding layout is part of the compile
        key). Deterministic mode keeps its lane count across the re-shard,
        so the fit trajectory is preserved up to lane-fold fp association
        (docs/DISTRIBUTED.md)."""
        model = self.model
        # pull state off the old placement (host round trip — regroup-rare)
        model.params = jax.tree_util.tree_map(np.asarray, model.params)
        model.states = jax.tree_util.tree_map(np.asarray, model.states)
        model.opt_states = jax.tree_util.tree_map(np.asarray,
                                                  model.opt_states)
        if self._comp_state is not None:
            # residual/threshold migrate with the regroup: the lane count
            # is fixed at construction, so the worker-stacked shapes are
            # mesh-independent and the re-placed fit continues the SAME
            # error-feedback trajectory (trajectory-exact regroup —
            # tests/test_compression.py)
            model._grad_comp_state = jax.tree_util.tree_map(
                np.asarray, self._comp_state)
            self._comp_state = None
        if mesh is None:
            # re-derive from the CURRENT device view (after worker loss the
            # survivors), keeping the model/seq factors when they still fit
            devices = jax.devices()
            model_ax, seq_ax, pipe_ax = (self.mesh.model, self.mesh.seq,
                                         self.mesh.pipe)
            if len(devices) % (model_ax * seq_ax * pipe_ax):
                model_ax = seq_ax = pipe_ax = 1
            mesh = TrainingMesh(
                data=len(devices) // (model_ax * seq_ax * pipe_ax),
                model=model_ax, seq=seq_ax, pipe=pipe_ax, devices=devices)
        if self.deterministic and (mesh.model != 1 or mesh.seq != 1
                                   or mesh.pipe != 1):
            raise ValueError("deterministic lane mode needs a data-only mesh")
        self.mesh = mesh
        self._sharded_step = None
        self._tbptt_step = None
        self._zero_specs = None
        self._comp_specs = None
        self._build()
        tm.counter("parallel.reshards_total")
        return self

    # --------------------------------------------------------- cost report
    def cost_report(self, batch_size=None, *, shape=None, dtype=jnp.float32,
                    name: str = "parallel", publish: bool = True):
        """Per-layer cost table for ONE GSPMD-sharded train step.
        ``cost_analysis()`` totals of a partitioned executable are
        PER-DEVICE — the report carries ``devices`` and exposes both
        per-device and global FLOPs/bytes (``totals_global``), keeping the
        reconciliation semantics honest under sharding
        (docs/OBSERVABILITY.md#cost-attribution--mfu)."""
        from deeplearning4j_tpu.util import cost_model as _cm

        model = self.model
        if self._sharded_step is None:
            self._build()
        if self._uses_lanes:
            return self._cost_report_lanes(
                batch_size=batch_size, shape=shape, dtype=dtype, name=name,
                publish=publish)
        conf = model.conf
        if shape is None:
            if getattr(conf, "input_shape", None) is None:
                raise ValueError("cost_report() needs shape= or "
                                 "conf.input_shape")
            shape = ((int(batch_size or 8 * self.mesh.data),)
                     + tuple(conf.input_shape))
        shape = tuple(int(d) for d in shape)
        b = shape[0]
        if b % self.mesh.data:
            raise ValueError(f"global batch {b} must divide the data axis "
                             f"({self.mesh.data})")

        def struct(t):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=getattr(a, "sharding", None)),
                t)

        p_s, s_s, o_s = (struct(model.params), struct(model.states),
                         struct(model.opt_states))
        it_s = jax.ShapeDtypeStruct((), jnp.int32)
        key_s = struct(model._rng_key)
        bsh = self.mesh.batch_sharding(len(shape))
        x_s = jax.ShapeDtypeStruct(shape, dtype, sharding=bsh)
        y_s = jax.ShapeDtypeStruct((b,) + tuple(model._output_shape),
                                   jnp.float32,
                                   sharding=self.mesh.batch_sharding(
                                       1 + len(model._output_shape)))
        w_s = jax.ShapeDtypeStruct((b,), jnp.float32,
                                   sharding=self.mesh.batch_sharding(1))
        compiled = self._sharded_step.lower(
            p_s, s_s, o_s, it_s, x_s, y_s, key_s, w_s).compile()
        params_by_tag = {}
        if hasattr(model, "_layer_tags"):
            params_by_tag = {
                t: int(sum(int(np.prod(l.shape))
                           for l in jax.tree_util.tree_leaves(p)))
                for t, p in zip(model._layer_tags, model.params)}
        totals, attrib, source = {}, None, "analytic"
        try:
            totals = _cm.compiled_totals(compiled)
            attrib = _cm.attribute_hlo(_cm.compiled_text(compiled))
            source = "xla"
        except _cm.CostAnalysisUnavailable:
            pass
        if attrib is not None:
            rows = _cm.rows_from_attribution(attrib, params_by_tag, None)
        else:
            rows = []
        report = _cm.CostReport(
            rows=rows, totals=totals, batch=b,
            params_total=model.num_params(), source=source, model=str(name),
            peak_flops=_cm.peak_flops_from_env(
                getattr(self.model.conf, "compute_dtype", None)),
            devices=self.mesh.n_devices)
        if publish:
            _cm.publish_report(str(name), report)
        return report

    def _cost_report_lanes(self, batch_size=None, *, shape=None,
                           dtype=jnp.float32, name: str = "parallel",
                           publish: bool = True):
        """Cost report for the LANE-DECOMPOSED step (deterministic mode and
        the compressed-DP path): the step is deliberately staged as three
        jit programs (lanes / combine / update — the FMA-contraction
        determinism note above), so the report lowers ALL THREE with the
        fit-time shapes/shardings, sums their per-device totals, and merges
        their per-layer attributions — the lanes program carries the
        ``layer:*`` scopes, the update program the ``(optimizer)`` row, the
        combine (and encode, when compressing) lands in ``(untagged)``."""
        from deeplearning4j_tpu.util import cost_model as _cm

        model = self.model
        conf = model.conf
        if shape is None:
            if getattr(conf, "input_shape", None) is None:
                raise ValueError("cost_report() needs shape= or "
                                 "conf.input_shape")
            shape = ((int(batch_size or 8 * self.mesh.data),)
                     + tuple(conf.input_shape))
        shape = tuple(int(d) for d in shape)
        b, R = shape[0], self.replicas
        if b % R:
            raise ValueError(f"global batch {b} must divide the lane count "
                             f"({R})")

        def struct(t):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    jnp.shape(a), jnp.asarray(a).dtype,
                    sharding=getattr(a, "sharding", None)), t)

        lane_shape = (R, b // R) + tuple(shape[1:])
        lsh = (self.mesh.spec("data", *([None] * (len(lane_shape) - 1)))
               if self.mesh.n_devices > 1 else None)
        x_s = jax.ShapeDtypeStruct(lane_shape, dtype, sharding=lsh)
        y_shape = (R, b // R) + tuple(model._output_shape)
        y_s = jax.ShapeDtypeStruct(
            y_shape, jnp.float32,
            sharding=(self.mesh.spec("data", *([None] * (len(y_shape) - 1)))
                      if self.mesh.n_devices > 1 else None))
        w_s = jax.ShapeDtypeStruct(
            (R, b // R), jnp.float32,
            sharding=(self.mesh.spec("data", None)
                      if self.mesh.n_devices > 1 else None))
        keys_s = struct(self._lane_keys(jax.random.PRNGKey(0)))
        scale = self._loss_scale_arg()
        scale_s = None if scale is None else struct(scale)
        p_s, s_s, o_s = (struct(model.params), struct(model.states),
                         struct(model.opt_states))
        it_s = jax.ShapeDtypeStruct((), jnp.int32)

        j_lanes, j_combine, j_update = self._stage_jits
        lanes_args = (p_s, s_s, x_s, y_s, keys_s, w_s, scale_s)
        lanes_out = jax.eval_shape(j_lanes, *lanes_args)
        if self._compressor is not None:
            comb_args = tuple(lanes_out) + (struct(self._comp_state),)
            _loss, grads_s = jax.eval_shape(j_combine, *comb_args)[:2]
        else:
            comb_args = tuple(lanes_out)
            _loss, grads_s, _st = jax.eval_shape(j_combine, *comb_args)
        upd_args = (p_s, o_s, grads_s, it_s)

        params_by_tag = {}
        if hasattr(model, "_layer_tags"):
            params_by_tag = {
                t: int(sum(int(np.prod(l.shape))
                           for l in jax.tree_util.tree_leaves(p)))
                for t, p in zip(model._layer_tags, model.params)}
        totals: dict = {}
        merged: Optional[_cm.HloAttribution] = None
        source = "analytic"
        try:
            for fn, args in ((j_lanes, lanes_args), (j_combine, comb_args),
                             (j_update, upd_args)):
                compiled = fn.lower(*args).compile()
                for k, v in _cm.compiled_totals(compiled).items():
                    totals[k] = totals.get(k, 0.0) + v
                att = _cm.attribute_hlo(_cm.compiled_text(compiled))
                if merged is None:
                    merged = att
                else:
                    for key, costs in att.by_layer.items():
                        dst = merged.by_layer.setdefault(key, {})
                        for ck, cv in costs.items():
                            dst[ck] = dst.get(ck, 0.0) + cv
                    merged.flops_total += att.flops_total
                    merged.transcendentals_total += att.transcendentals_total
                    merged.bytes_total += att.bytes_total
                    merged.inst_map.update(att.inst_map)
            source = "xla"
        except _cm.CostAnalysisUnavailable:
            totals, merged = {}, None
        rows = (_cm.rows_from_attribution(merged, params_by_tag, None)
                if merged is not None else [])
        report = _cm.CostReport(
            rows=rows, totals=totals, batch=b,
            params_total=model.num_params(), source=source, model=str(name),
            peak_flops=_cm.peak_flops_from_env(
                getattr(conf, "compute_dtype", None)),
            devices=self.mesh.n_devices)
        if publish:
            _cm.publish_report(str(name), report)
        return report

    def _probe_replica_skew(self, loss, dispatch_t0_ns: int):
        """Record when each replica's loss shard became ready: one
        ``parallel.replica_step`` span per replica (from dispatch to that
        replica's completion, on a synthetic per-replica trace row) and the
        max−min spread as the straggler-skew gauge. Completion is observed
        by POLLING ``is_ready()`` across all shards so arrival order is
        captured regardless of index — blocking shard-by-shard would charge
        a low-index straggler's wait to every later replica and read ~0
        skew exactly when the straggler exists."""
        import time as _time

        shards = getattr(loss, "addressable_shards", None)
        if not shards:
            return
        done_ns = [0] * len(shards)
        if all(hasattr(sh.data, "is_ready") for sh in shards):
            pending = set(range(len(shards)))
            deadline = _time.monotonic() + 60.0
            while pending and _time.monotonic() < deadline:
                for i in list(pending):
                    if shards[i].data.is_ready():
                        done_ns[i] = _time.time_ns()
                        pending.discard(i)
                if pending:
                    _time.sleep(5e-5)
            for i in pending:  # deadline hit: block out the stragglers
                jax.block_until_ready(shards[i].data)
                done_ns[i] = _time.time_ns()
        else:  # older jax: sequential fallback (index-order bias documented)
            for i, sh in enumerate(shards):
                jax.block_until_ready(sh.data)
                done_ns[i] = _time.time_ns()
        tele = tm.get_telemetry()
        for i, (sh, t1) in enumerate(zip(shards, done_ns)):
            tele.event("parallel.replica_step", dispatch_t0_ns, t1,
                       tid=10_000 + i,
                       tname=f"replica {i} ({sh.device})",
                       replica=i)
        skew = (max(done_ns) - min(done_ns)) / 1e9
        tm.gauge("parallel.straggler_skew_seconds", skew)
        tm.gauge("parallel.replicas", len(shards))

    def average_model(self):
        """No-op for API parity: params are kept consistent every step by the
        compiled all-reduce (averaging mode with frequency=1, exact)."""
        return self.model

    def warmup(self, batch_sizes, input_shape=None, label_shape=None):
        """AOT warmup of the sharded train step for each GLOBAL batch size
        (docs/COMPILE_CACHE.md): runs one throwaway step per size on
        zero-valued shadow state (params are donated — the real model state
        is never touched), so the first real fit() batch executes a warm
        executable. Shapes default to the model conf. Returns the number of
        signatures primed."""
        import numpy as np_

        if self._sharded_step is None:
            self._build()
        model = self.model
        conf = model.conf
        in_shape = tuple(input_shape or conf.input_shape or ())
        if not in_shape:
            raise ValueError("warmup() needs input_shape (or conf.input_shape)")
        out_shape = tuple(label_shape or getattr(model, "_output_shape", ()))
        if not out_shape:
            raise ValueError("warmup() needs label_shape")
        zeros = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, a.dtype), t)
        # the compressed step donates (and advances) the resident
        # residual/threshold through self._comp_state: park the REAL state
        # and run warmup on a shadow copy, so priming executables never
        # perturbs the error-feedback trajectory
        real_comp = self._comp_state
        real_stats = self._comp_stats
        primed = 0
        try:
            for b in batch_sizes:
                x = np_.zeros((int(b),) + in_shape, np_.float32)
                y = np_.zeros((int(b),) + out_shape, np_.float32)
                xs, ys, w = self._shard(x, y)
                # shadow state, same shardings as the real one (params/
                # states replicated, optimizer state ZeRO-sharded when
                # enabled — the warm executable must match the fit-time
                # layout, which is part of jit's dispatch key and the
                # persistent compile-cache key)
                p = self.mesh.replicate(zeros(model.params),
                                        keep_existing=False)
                s = self.mesh.replicate(zeros(model.states),
                                        keep_existing=False)
                o = zeros(model.opt_states)
                o = (gspmd.place_tree(o, self._zero_specs)
                     if self._zero_specs is not None
                     else self.mesh.replicate(o, keep_existing=False))
                if real_comp is not None:
                    shadow = zeros(real_comp)
                    if self._comp_specs is not None:
                        shadow = gspmd.place_tree(shadow, self._comp_specs)
                    self._comp_state = shadow
                key = (self._lane_keys(jax.random.PRNGKey(0))
                       if self._uses_lanes else jax.random.PRNGKey(0))
                self._sharded_step(p, s, o, jnp.asarray(0), xs, ys, key, w)
                primed += 1
        finally:
            self._comp_state = real_comp
            self._comp_stats = real_stats
            if real_comp is not None:
                self.model._grad_comp_state = real_comp
        return primed


class ParallelInference:
    """Throughput serving over the mesh (ParallelInference parity).

    The reference round-robins requests over model replicas and coalesces
    batches on a queue; here a replicated-params, batch-sharded jitted forward
    serves the full mesh in one call. ``output`` accepts any batch size and
    pads to mesh divisibility.
    """

    def __init__(self, model, mesh: Optional[TrainingMesh] = None,
                 batch_limit: int = 1024, batch_timeout_ms: float = 3.0,
                 queue_limit: int = 256, bucketing=None):
        from deeplearning4j_tpu.data.bucketing import BucketingPolicy

        self.model = model
        self.mesh = mesh or TrainingMesh(data=len(jax.devices()))
        self.batch_limit = batch_limit
        self.batch_timeout_ms = batch_timeout_ms
        # Shape bucketing for serving (docs/COMPILE_CACHE.md): request
        # batches round up to a bucket BEFORE mesh padding, bounding the
        # number of compiled forward signatures under arbitrary traffic.
        # Defaults to the model conf's policy; pass a BucketingPolicy, a
        # spec string ("pow2" / "batch=8,16,32"), or False to disable.
        if bucketing is None:
            bucketing = BucketingPolicy.from_conf(
                getattr(model, "conf", None))
        elif bucketing is False:
            bucketing = None
        elif isinstance(bucketing, str):
            bucketing = BucketingPolicy.from_spec(bucketing)
        self.bucketing = bucketing
        self._params = self.mesh.replicate(model.params)
        self._states = self.mesh.replicate(model.states)
        self._fwd = jax.jit(model.make_forward_fn())
        self._queue: "queue.Queue[Tuple[np.ndarray, Future]]" = queue.Queue(
            maxsize=queue_limit)
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._stop = threading.Event()
        self._shut_down = False

    def output(self, x):
        x = np.asarray(x)
        n = len(x)
        if self.bucketing is not None:
            # ONE bucket plan for every request size (data/bucketing.py
            # plan_serving_batch, shared with the serving scheduler —
            # docs/SERVING.md): sizes between buckets pad up to the next
            # bucket, sizes above the largest bucket chunk into
            # largest-bucket pieces — a novel request size NEVER traces a
            # new program once warmup() has primed the buckets
            plan = self.bucketing.plan_serving_batch(n, cap=self.batch_limit)
            if len(plan) > 1:
                chunks, off = [], 0
                for take, padded in plan:
                    chunks.append(self._output_one(x[off:off + take],
                                                   padded))
                    off += take
                return np.concatenate(chunks, axis=0)
            return self._output_one(x, plan[0][1])
        if n > self.batch_limit:
            # chunk to bound per-call device memory (the reference's queue
            # coalescing bounds batches the same way)
            chunks = [
                self._output_one(x[i : i + self.batch_limit])
                for i in range(0, n, self.batch_limit)
            ]
            return np.concatenate(chunks, axis=0)
        return self._output_one(x)

    def _output_one(self, x, target=None):
        """One device call, padded to ``target`` rows (the plan's padded
        size — which the plan may deliberately leave UNPADDED when
        batch_limit excludes every bucket, honoring the memory bound) then
        to mesh divisibility. Without a plan, buckets first then
        mesh-pads."""
        n = len(x)
        d = self.mesh.data
        if target is None:
            # bucket first, then mesh-divisibility: one compiled forward per
            # bucket instead of one per distinct (padded) request size
            target = (n if self.bucketing is None
                      else self.bucketing.bucket_batch(n))
        target += (d - target % d) % d
        pad = target - n
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        xs = self.mesh.shard_batch(x)
        out = self._fwd(self._params, self._states, xs)
        return np.asarray(out)[:n]

    def warmup(self, batch_sizes=None, input_shape=None):
        """Pre-compile the serving forward for every bucket before traffic
        (ParallelInference.warmup — docs/COMPILE_CACHE.md): one zero-batch
        call per size primes the dispatch cache, so first-request latency is
        execution-only. ``batch_sizes`` defaults to the explicit
        ``batch_buckets`` list of the bucketing policy; ``input_shape``
        (excl. batch) defaults to the model conf. Returns the number of
        signatures primed."""
        if batch_sizes is None:
            if (self.bucketing is None
                    or not isinstance(self.bucketing.batch_buckets, tuple)):
                raise ValueError(
                    "warmup() without batch_sizes needs an explicit "
                    "batch_buckets bucketing policy")
            batch_sizes = self.bucketing.batch_buckets
        conf = getattr(self.model, "conf", None)
        shape = tuple(input_shape
                      or getattr(conf, "input_shape", None)
                      or (getattr(conf, "input_shapes", None) or [()])[0])
        if not shape:
            raise ValueError("warmup() needs input_shape (or conf.input_shape)")
        primed = 0
        for b in batch_sizes:
            self.output(np.zeros((int(b),) + shape, np.float32))
            primed += 1
        return primed

    # ----------------------------------------------------- dynamic batching
    def output_async(self, x) -> "Future":
        """Queue a request; a background thread coalesces pending requests
        into one device batch (the reference's observable-queue batching in
        ParallelInference.java). Returns a Future of the predictions."""
        with self._worker_lock:
            if self._shut_down:
                raise RuntimeError("ParallelInference shut down")
            if self._worker is None:
                self._start_worker()
            fut: Future = Future()
            self._queue.put((np.asarray(x), fut))
        return fut

    @staticmethod
    def _resolve(fut: Future, value=None, exc=None):
        """Set a future's outcome, tolerating caller-side cancel()."""
        if not fut.set_running_or_notify_cancel():
            return  # cancelled before we got to it
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)

    def _start_worker(self):
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                batch: List[Tuple[np.ndarray, Future]] = [first]
                total = len(first[0])
                deadline = self.batch_timeout_ms / 1e3
                t0 = time.monotonic()
                while total < self.batch_limit:
                    remaining = deadline - (time.monotonic() - t0)
                    if remaining <= 0:
                        break
                    try:
                        item = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    batch.append(item)
                    total += len(item[0])
                # the WHOLE batch body is guarded: a bad request (wrong
                # rank/width) must fail its batch, never kill the worker
                try:
                    xs = np.concatenate([b[0] for b in batch], axis=0)
                    preds = self.output(xs)
                    off = 0
                    for arr, fut in batch:
                        self._resolve(fut, value=preds[off:off + len(arr)])
                        off += len(arr)
                except Exception as e:
                    for _, fut in batch:
                        if not fut.done():
                            self._resolve(fut, exc=e)

        self._worker = threading.Thread(target=run, daemon=True)
        self._worker.start()

    def shutdown(self):
        """Stop the batching worker (failing any queued requests); later
        output_async calls raise instead of hanging."""
        with self._worker_lock:
            self._shut_down = True
            self._stop.set()
            worker = self._worker
            self._worker = None
        if worker is not None:
            worker.join(timeout=2.0)
        while True:
            try:
                _, fut = self._queue.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                self._resolve(fut, exc=RuntimeError("ParallelInference shut down"))
