"""ParallelWrapper + ParallelInference — multi-device training/serving parity.

Reference: org/deeplearning4j/parallelism/{ParallelWrapper,ParallelInference}
.java (SURVEY.md §3.5: thread-per-GPU replicas, gradient averaging or
threshold-encoded sharing through EncodedGradientsAccumulator, round-robin
inference replicas) — path-cite, mount empty this round.

TPU-native collapse: there are no replicas, no trainer threads, no
accumulator. The SAME jitted train step as single-device, compiled with the
batch sharded over the mesh 'data' axis and params replicated — GSPMD inserts
one fused gradient ``all-reduce`` over ICI per step. Synchronous averaging
every iteration (the reference's averaging mode with frequency=1) is exact
here and costs one collective; the async/compressed machinery existed to hide
slow interconnects that ICI does not have (threshold compression survives as
an opt-in for DCN in parallel.compression).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.util import telemetry as tm


class ParallelWrapper:
    """Data-parallel fit over a device mesh (ParallelWrapper.fit parity).

    Usage:
        pw = ParallelWrapper(net)            # all local devices
        pw.fit(iterator, epochs=2)
        # net.params are updated in place (replicated arrays)

    Telemetry: every step records a ``parallel.step`` dispatch span; every
    ``skew_every`` steps a completion probe watches each replica's loss
    shard become ready, emits one ``parallel.replica_step`` span per replica
    row on the merged trace, and publishes the max−min completion spread as the
    ``parallel.straggler_skew_seconds`` gauge (per-replica timing/skew
    visibility — arxiv 2004.13336's prerequisite for scaling the
    distributed path). The probe is a deliberate sync point, which is why
    it runs at window cadence, not per step; ``skew_every=0`` disables it.
    On a single-host CPU mesh the compiled all-reduce has already
    synchronized the replicas, so the skew reads ≈0 there — the gauge is
    meaningful on real multi-chip ICI.
    """

    def __init__(self, model, workers: Optional[int] = None,
                 mesh: Optional[TrainingMesh] = None, prefetch: int = 2,
                 skew_every: int = 10):
        self.model = model
        if mesh is None:
            devices = jax.devices()[: workers or len(jax.devices())]
            mesh = TrainingMesh(data=len(devices), devices=devices)
        self.mesh = mesh
        self.prefetch = prefetch
        self.skew_every = skew_every
        self._sharded_step = None

    def _build(self):
        if self.model._train_step is None:
            raise ValueError("model must be init()ed first")
        # The model's own step function (weighted variant for exact ragged-
        # batch masking), jitted over sharded operands: params replicated,
        # batch split over 'data'. jit infers the SPMD partition from operand
        # shardings (set by device_put in fit); the gradient all-reduce is
        # emitted by the partitioner, not written here.
        self._sharded_step = jax.jit(
            self.model.make_step_fn(weighted=True), donate_argnums=(0, 1, 2)
        )
        # replicate current model state across the mesh (TP-sharded leaves
        # placed on this mesh keep their sharding)
        self.model.params = self.mesh.replicate(self.model.params)
        self.model.states = self.mesh.replicate(self.model.states)
        self.model.opt_states = self.mesh.replicate(self.model.opt_states)

    def step_batch(self, ds):
        """Run ONE sharded train step on a DataSet (listeners included) —
        the unit the elastic supervisor (parallel/elastic.py) wraps with
        checkpoint/drain/rollback handling. Returns the device loss."""
        import time as _time

        if self._sharded_step is None:
            self._build()
        model = self.model
        x, y, w = self._shard(ds.features, ds.labels)
        model._rng_key, sub = jax.random.split(model._rng_key)
        t0 = _time.time_ns()
        with tm.span("parallel.step", iteration=model.iteration,
                     replicas=self.mesh.data):
            model.params, model.states, model.opt_states, loss = (
                self._sharded_step(
                    model.params, model.states, model.opt_states,
                    jnp.asarray(model.iteration), x, y, sub, w,
                )
            )
        model.score_value = loss
        model.iteration += 1
        tm.counter("train.steps_total", model="parallel")
        if (self.skew_every and tm.enabled()
                and model.iteration % self.skew_every == 0):
            self._probe_replica_skew(loss, t0)
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration, model.epoch)
        return loss

    def end_epoch(self):
        """Advance the epoch counter + epoch-end callbacks (the tail of one
        fit() epoch, split out for the elastic supervisor)."""
        model = self.model
        model.epoch += 1
        for lst in model.listeners:
            if hasattr(lst, "on_epoch_end"):
                lst.on_epoch_end(model)

    def fit(self, iterator, epochs: int = 1):
        if self._sharded_step is None:
            self._build()
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                self.step_batch(ds)
            self.end_epoch()
        return self.model

    def _shard(self, x, y):
        return self.mesh.pad_shard_batch(x, y)

    def _probe_replica_skew(self, loss, dispatch_t0_ns: int):
        """Record when each replica's loss shard became ready: one
        ``parallel.replica_step`` span per replica (from dispatch to that
        replica's completion, on a synthetic per-replica trace row) and the
        max−min spread as the straggler-skew gauge. Completion is observed
        by POLLING ``is_ready()`` across all shards so arrival order is
        captured regardless of index — blocking shard-by-shard would charge
        a low-index straggler's wait to every later replica and read ~0
        skew exactly when the straggler exists."""
        import time as _time

        shards = getattr(loss, "addressable_shards", None)
        if not shards:
            return
        done_ns = [0] * len(shards)
        if all(hasattr(sh.data, "is_ready") for sh in shards):
            pending = set(range(len(shards)))
            deadline = _time.monotonic() + 60.0
            while pending and _time.monotonic() < deadline:
                for i in list(pending):
                    if shards[i].data.is_ready():
                        done_ns[i] = _time.time_ns()
                        pending.discard(i)
                if pending:
                    _time.sleep(5e-5)
            for i in pending:  # deadline hit: block out the stragglers
                jax.block_until_ready(shards[i].data)
                done_ns[i] = _time.time_ns()
        else:  # older jax: sequential fallback (index-order bias documented)
            for i, sh in enumerate(shards):
                jax.block_until_ready(sh.data)
                done_ns[i] = _time.time_ns()
        tele = tm.get_telemetry()
        for i, (sh, t1) in enumerate(zip(shards, done_ns)):
            tele.event("parallel.replica_step", dispatch_t0_ns, t1,
                       tid=10_000 + i,
                       tname=f"replica {i} ({sh.device})",
                       replica=i)
        skew = (max(done_ns) - min(done_ns)) / 1e9
        tm.gauge("parallel.straggler_skew_seconds", skew)
        tm.gauge("parallel.replicas", len(shards))

    def average_model(self):
        """No-op for API parity: params are kept consistent every step by the
        compiled all-reduce (averaging mode with frequency=1, exact)."""
        return self.model

    def warmup(self, batch_sizes, input_shape=None, label_shape=None):
        """AOT warmup of the sharded train step for each GLOBAL batch size
        (docs/COMPILE_CACHE.md): runs one throwaway step per size on
        zero-valued shadow state (params are donated — the real model state
        is never touched), so the first real fit() batch executes a warm
        executable. Shapes default to the model conf. Returns the number of
        signatures primed."""
        import numpy as np_

        if self._sharded_step is None:
            self._build()
        model = self.model
        conf = model.conf
        in_shape = tuple(input_shape or conf.input_shape or ())
        if not in_shape:
            raise ValueError("warmup() needs input_shape (or conf.input_shape)")
        out_shape = tuple(label_shape or getattr(model, "_output_shape", ()))
        if not out_shape:
            raise ValueError("warmup() needs label_shape")
        zeros = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, a.dtype), t)
        primed = 0
        for b in batch_sizes:
            x = np_.zeros((int(b),) + in_shape, np_.float32)
            y = np_.zeros((int(b),) + out_shape, np_.float32)
            xs, ys, w = self._shard(x, y)
            # shadow state, same shardings as the real one (replicated)
            p = self.mesh.replicate(zeros(model.params), keep_existing=False)
            s = self.mesh.replicate(zeros(model.states), keep_existing=False)
            o = self.mesh.replicate(zeros(model.opt_states),
                                    keep_existing=False)
            self._sharded_step(p, s, o, jnp.asarray(0),
                               xs, ys, jax.random.PRNGKey(0), w)
            primed += 1
        return primed


class ParallelInference:
    """Throughput serving over the mesh (ParallelInference parity).

    The reference round-robins requests over model replicas and coalesces
    batches on a queue; here a replicated-params, batch-sharded jitted forward
    serves the full mesh in one call. ``output`` accepts any batch size and
    pads to mesh divisibility.
    """

    def __init__(self, model, mesh: Optional[TrainingMesh] = None,
                 batch_limit: int = 1024, batch_timeout_ms: float = 3.0,
                 queue_limit: int = 256, bucketing=None):
        from deeplearning4j_tpu.data.bucketing import BucketingPolicy

        self.model = model
        self.mesh = mesh or TrainingMesh(data=len(jax.devices()))
        self.batch_limit = batch_limit
        self.batch_timeout_ms = batch_timeout_ms
        # Shape bucketing for serving (docs/COMPILE_CACHE.md): request
        # batches round up to a bucket BEFORE mesh padding, bounding the
        # number of compiled forward signatures under arbitrary traffic.
        # Defaults to the model conf's policy; pass a BucketingPolicy, a
        # spec string ("pow2" / "batch=8,16,32"), or False to disable.
        if bucketing is None:
            bucketing = BucketingPolicy.from_conf(
                getattr(model, "conf", None))
        elif bucketing is False:
            bucketing = None
        elif isinstance(bucketing, str):
            bucketing = BucketingPolicy.from_spec(bucketing)
        self.bucketing = bucketing
        self._params = self.mesh.replicate(model.params)
        self._states = self.mesh.replicate(model.states)
        self._fwd = jax.jit(model.make_forward_fn())
        self._queue: "queue.Queue[Tuple[np.ndarray, Future]]" = queue.Queue(
            maxsize=queue_limit)
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._stop = threading.Event()
        self._shut_down = False

    def output(self, x):
        x = np.asarray(x)
        n = len(x)
        if n > self.batch_limit:
            # chunk to bound per-call device memory (the reference's queue
            # coalescing bounds batches the same way)
            chunks = [
                self.output(x[i : i + self.batch_limit])
                for i in range(0, n, self.batch_limit)
            ]
            return np.concatenate(chunks, axis=0)
        d = self.mesh.data
        target = len(x)
        if self.bucketing is not None:
            # bucket first, then mesh-divisibility: one compiled forward per
            # bucket instead of one per distinct (padded) request size
            target = self.bucketing.bucket_batch(target)
        target += (d - target % d) % d
        pad = target - n
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        xs = self.mesh.shard_batch(x)
        out = self._fwd(self._params, self._states, xs)
        return np.asarray(out)[:n]

    def warmup(self, batch_sizes=None, input_shape=None):
        """Pre-compile the serving forward for every bucket before traffic
        (ParallelInference.warmup — docs/COMPILE_CACHE.md): one zero-batch
        call per size primes the dispatch cache, so first-request latency is
        execution-only. ``batch_sizes`` defaults to the explicit
        ``batch_buckets`` list of the bucketing policy; ``input_shape``
        (excl. batch) defaults to the model conf. Returns the number of
        signatures primed."""
        if batch_sizes is None:
            if (self.bucketing is None
                    or not isinstance(self.bucketing.batch_buckets, tuple)):
                raise ValueError(
                    "warmup() without batch_sizes needs an explicit "
                    "batch_buckets bucketing policy")
            batch_sizes = self.bucketing.batch_buckets
        conf = getattr(self.model, "conf", None)
        shape = tuple(input_shape
                      or getattr(conf, "input_shape", None)
                      or (getattr(conf, "input_shapes", None) or [()])[0])
        if not shape:
            raise ValueError("warmup() needs input_shape (or conf.input_shape)")
        primed = 0
        for b in batch_sizes:
            self.output(np.zeros((int(b),) + shape, np.float32))
            primed += 1
        return primed

    # ----------------------------------------------------- dynamic batching
    def output_async(self, x) -> "Future":
        """Queue a request; a background thread coalesces pending requests
        into one device batch (the reference's observable-queue batching in
        ParallelInference.java). Returns a Future of the predictions."""
        with self._worker_lock:
            if self._shut_down:
                raise RuntimeError("ParallelInference shut down")
            if self._worker is None:
                self._start_worker()
            fut: Future = Future()
            self._queue.put((np.asarray(x), fut))
        return fut

    @staticmethod
    def _resolve(fut: Future, value=None, exc=None):
        """Set a future's outcome, tolerating caller-side cancel()."""
        if not fut.set_running_or_notify_cancel():
            return  # cancelled before we got to it
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)

    def _start_worker(self):
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                batch: List[Tuple[np.ndarray, Future]] = [first]
                total = len(first[0])
                deadline = self.batch_timeout_ms / 1e3
                t0 = time.monotonic()
                while total < self.batch_limit:
                    remaining = deadline - (time.monotonic() - t0)
                    if remaining <= 0:
                        break
                    try:
                        item = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    batch.append(item)
                    total += len(item[0])
                # the WHOLE batch body is guarded: a bad request (wrong
                # rank/width) must fail its batch, never kill the worker
                try:
                    xs = np.concatenate([b[0] for b in batch], axis=0)
                    preds = self.output(xs)
                    off = 0
                    for arr, fut in batch:
                        self._resolve(fut, value=preds[off:off + len(arr)])
                        off += len(arr)
                except Exception as e:
                    for _, fut in batch:
                        if not fut.done():
                            self._resolve(fut, exc=e)

        self._worker = threading.Thread(target=run, daemon=True)
        self._worker.start()

    def shutdown(self):
        """Stop the batching worker (failing any queued requests); later
        output_async calls raise instead of hanging."""
        with self._worker_lock:
            self._shut_down = True
            self._stop.set()
            worker = self._worker
            self._worker = None
        if worker is not None:
            worker.join(timeout=2.0)
        while True:
            try:
                _, fut = self._queue.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                self._resolve(fut, exc=RuntimeError("ParallelInference shut down"))
