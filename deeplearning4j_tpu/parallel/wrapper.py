"""ParallelWrapper + ParallelInference — multi-device training/serving parity.

Reference: org/deeplearning4j/parallelism/{ParallelWrapper,ParallelInference}
.java (SURVEY.md §3.5: thread-per-GPU replicas, gradient averaging or
threshold-encoded sharing through EncodedGradientsAccumulator, round-robin
inference replicas) — path-cite, mount empty this round.

TPU-native collapse: there are no replicas, no trainer threads, no
accumulator. The SAME jitted train step as single-device, compiled with the
batch sharded over the mesh 'data' axis and params replicated — GSPMD inserts
one fused gradient ``all-reduce`` over ICI per step. Synchronous averaging
every iteration (the reference's averaging mode with frequency=1) is exact
here and costs one collective; the async/compressed machinery existed to hide
slow interconnects that ICI does not have (threshold compression survives as
an opt-in for DCN in parallel.compression).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import TrainingMesh


class ParallelWrapper:
    """Data-parallel fit over a device mesh (ParallelWrapper.fit parity).

    Usage:
        pw = ParallelWrapper(net)            # all local devices
        pw.fit(iterator, epochs=2)
        # net.params are updated in place (replicated arrays)
    """

    def __init__(self, model, workers: Optional[int] = None,
                 mesh: Optional[TrainingMesh] = None, prefetch: int = 2):
        self.model = model
        if mesh is None:
            devices = jax.devices()[: workers or len(jax.devices())]
            mesh = TrainingMesh(data=len(devices), devices=devices)
        self.mesh = mesh
        self.prefetch = prefetch
        self._sharded_step = None

    def _build(self):
        if self.model._train_step is None:
            raise ValueError("model must be init()ed first")
        # The model's own step function (weighted variant for exact ragged-
        # batch masking), jitted over sharded operands: params replicated,
        # batch split over 'data'. jit infers the SPMD partition from operand
        # shardings (set by device_put in fit); the gradient all-reduce is
        # emitted by the partitioner, not written here.
        self._sharded_step = jax.jit(
            self.model.make_step_fn(weighted=True), donate_argnums=(0, 1, 2)
        )
        # replicate current model state across the mesh (TP-sharded leaves
        # placed on this mesh keep their sharding)
        self.model.params = self.mesh.replicate(self.model.params)
        self.model.states = self.mesh.replicate(self.model.states)
        self.model.opt_states = self.mesh.replicate(self.model.opt_states)

    def fit(self, iterator, epochs: int = 1):
        if self._sharded_step is None:
            self._build()
        model = self.model
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                x, y, w = self._shard(ds.features, ds.labels)
                model._rng_key, sub = jax.random.split(model._rng_key)
                model.params, model.states, model.opt_states, loss = (
                    self._sharded_step(
                        model.params, model.states, model.opt_states,
                        jnp.asarray(model.iteration), x, y, sub, w,
                    )
                )
                model.score_value = loss
                model.iteration += 1
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration, model.epoch)
            model.epoch += 1
            for lst in model.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(model)
        return model

    def _shard(self, x, y):
        return self.mesh.pad_shard_batch(x, y)

    def average_model(self):
        """No-op for API parity: params are kept consistent every step by the
        compiled all-reduce (averaging mode with frequency=1, exact)."""
        return self.model


class ParallelInference:
    """Throughput serving over the mesh (ParallelInference parity).

    The reference round-robins requests over model replicas and coalesces
    batches on a queue; here a replicated-params, batch-sharded jitted forward
    serves the full mesh in one call. ``output`` accepts any batch size and
    pads to mesh divisibility.
    """

    def __init__(self, model, mesh: Optional[TrainingMesh] = None,
                 batch_limit: int = 1024):
        self.model = model
        self.mesh = mesh or TrainingMesh(data=len(jax.devices()))
        self.batch_limit = batch_limit
        self._params = self.mesh.replicate(model.params)
        self._states = self.mesh.replicate(model.states)
        self._fwd = jax.jit(model.make_forward_fn())

    def output(self, x):
        x = np.asarray(x)
        n = len(x)
        if n > self.batch_limit:
            # chunk to bound per-call device memory (the reference's queue
            # coalescing bounds batches the same way)
            chunks = [
                self.output(x[i : i + self.batch_limit])
                for i in range(0, n, self.batch_limit)
            ]
            return np.concatenate(chunks, axis=0)
        d = self.mesh.data
        pad = (d - n % d) % d
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        xs = self.mesh.shard_batch(x)
        out = self._fwd(self._params, self._states, xs)
        return np.asarray(out)[:n]
