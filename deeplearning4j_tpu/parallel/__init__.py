"""Scale-out: device meshes, data/tensor/sequence parallelism, serving.

Reference parity: deeplearning4j-scaleout (ParallelWrapper, ParallelInference,
Spark training masters) + nd4j-parameter-server — SURVEY.md §2.3/§2.4. The
entire NCCL/Aeron/accumulator machinery collapses into sharding annotations on
one SPMD program: XLA emits the collectives over ICI/DCN.
"""

from deeplearning4j_tpu.parallel import distributed  # noqa: F401
from deeplearning4j_tpu.parallel import gspmd  # noqa: F401
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    ElasticTrainer,
    FileMembership,
    MembershipError,
    MembershipView,
    bootstrap_elastic,
)
from deeplearning4j_tpu.parallel.accumulator import (  # noqa: F401
    AdaptiveThresholdAlgorithm,
    EncodedGradientsAccumulator,
    FixedThresholdAlgorithm,
    ResidualClippingPostProcessor,
    TargetSparsityThresholdAlgorithm,
)
from deeplearning4j_tpu.parallel.compression import GradCompressor  # noqa: F401
from deeplearning4j_tpu.parallel.masters import (  # noqa: F401
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
    SparkComputationGraph,
    SparkDl4jMultiLayer,
)
from deeplearning4j_tpu.parallel.mesh import TrainingMesh  # noqa: F401
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: F401
    bubble_fraction,
    gpipe_scan,
    pipeline_forward,
    stack_stage_params,
)
from deeplearning4j_tpu.parallel.pipelined import (  # noqa: F401
    PipelinedTrainer,
    stage_partition,
)
from deeplearning4j_tpu.parallel.ring import ring_attention, shard_sequence  # noqa: F401
from deeplearning4j_tpu.parallel.wrapper import ParallelInference, ParallelWrapper  # noqa: F401
