"""EncodedGradientsAccumulator + threshold algorithms — gradient sharing.

Reference parity: org/deeplearning4j/optimize/solvers/accumulation/
{EncodedGradientsAccumulator,IndexedTail}.java, encoding/ResidualPostProcessor
(ResidualClippingPostProcessor), threshold algos
(AdaptiveThresholdAlgorithm, TargetSparsityThresholdAlgorithm,
FixedThresholdAlgorithm) — SURVEY.md §2.2 J16 — path-cite, mount empty this
round.

TPU-native framing: the reference's accumulator is an async queue fabric
between trainer threads + Aeron. Here sharing is synchronous inside the SPMD
step (see parallel.masters.SharedTrainingMaster): each device threshold-
encodes (gradient + residual), the quantized tensors all-reduce over ICI/DCN,
and the residual stays in device-local state. This class carries the
threshold adaptation + residual policy, as pure functions usable inside jit.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.compression import threshold_encode


@dataclasses.dataclass(frozen=True)
class FixedThresholdAlgorithm:
    """FixedThresholdAlgorithm.java parity."""

    threshold: float = 1e-3

    def init_state(self):
        return jnp.asarray(self.threshold, jnp.float32)

    def update(self, t, sparsity_ratio):
        return t


@dataclasses.dataclass(frozen=True)
class AdaptiveThresholdAlgorithm:
    """AdaptiveThresholdAlgorithm.java parity: drift the threshold so the
    fraction of transmitted elements stays near ``target_ratio``."""

    initial: float = 1e-3
    target_ratio: float = 1e-3   # desired fraction of entries above threshold
    decay: float = 1.2
    min_threshold: float = 1e-6
    max_threshold: float = 1.0

    def init_state(self):
        return jnp.asarray(self.initial, jnp.float32)

    def update(self, t, sparsity_ratio):
        too_dense = sparsity_ratio > self.target_ratio * 3.0
        too_sparse = sparsity_ratio < self.target_ratio / 3.0
        t = jnp.where(too_dense, t * self.decay,
                      jnp.where(too_sparse, t / self.decay, t))
        return jnp.clip(t, self.min_threshold, self.max_threshold)


@dataclasses.dataclass(frozen=True)
class TargetSparsityThresholdAlgorithm:
    """TargetSparsityThresholdAlgorithm.java parity: proportional control —
    every step the threshold moves by a factor derived from how far the
    observed transmitted fraction sits from ``target_ratio`` (the adaptive
    algorithm above only reacts outside a 3x dead band; this one always
    corrects, which converges tighter at the cost of more threshold
    churn). The DP-hot-path wrapper default stays Adaptive (the
    reference's default); plug this one into SharedTrainingMaster via
    ``EncodedGradientsAccumulator(threshold_algorithm=...)``."""

    initial: float = 1e-3
    target_ratio: float = 1e-3
    gain: float = 1.05
    min_threshold: float = 1e-8
    max_threshold: float = 1.0

    def init_state(self):
        return jnp.asarray(self.initial, jnp.float32)

    def update(self, t, sparsity_ratio):
        up = sparsity_ratio > self.target_ratio
        t = jnp.where(up, t * self.gain, t / self.gain)
        return jnp.clip(t, self.min_threshold, self.max_threshold)


@dataclasses.dataclass(frozen=True)
class ResidualClippingPostProcessor:
    """ResidualClippingPostProcessor.java parity: every ``frequency`` steps,
    clip the residual to ±``max_multiplier``·threshold so stale error can't
    blow up."""

    max_multiplier: float = 5.0
    frequency: int = 5

    def apply(self, residual, threshold, iteration):
        lim = threshold * self.max_multiplier
        clipped = jax.tree_util.tree_map(
            lambda r: jnp.clip(r, -lim, lim), residual)
        do = (iteration % self.frequency) == 0
        return jax.tree_util.tree_map(
            lambda c, r: jnp.where(do, c, r), clipped, residual)


@dataclasses.dataclass(frozen=True)
class EncodedGradientsAccumulator:
    """Pure-function core of the reference accumulator: encode (with error
    feedback) one flat gradient pytree.

    ``encode(grads, residual, threshold, iteration)`` →
    (quantized_tree, new_residual_tree, sparsity_ratio). All jittable; the
    caller reduces ``quantized`` across workers (psum) and applies it.
    """

    threshold_algorithm: object = AdaptiveThresholdAlgorithm()
    residual_post_processor: object = ResidualClippingPostProcessor()

    def init_residual(self, grads_template):
        return jax.tree_util.tree_map(jnp.zeros_like, grads_template)

    def encode(self, grads, residual, threshold, iteration):
        carried = jax.tree_util.tree_map(lambda g, r: g + r, grads, residual)
        enc = jax.tree_util.tree_map(
            lambda x: threshold_encode(x, threshold), carried)
        quantized = jax.tree_util.tree_map(
            lambda x: x[0], enc, is_leaf=lambda x: isinstance(x, tuple))
        new_residual = jax.tree_util.tree_map(
            lambda x: x[1], enc, is_leaf=lambda x: isinstance(x, tuple))
        if self.residual_post_processor is not None:
            new_residual = self.residual_post_processor.apply(
                new_residual, threshold, iteration)
        leaves = jax.tree_util.tree_leaves(quantized)
        nz = sum(jnp.sum(q != 0).astype(jnp.float32) for q in leaves)
        total = sum(q.size for q in leaves)
        ratio = nz / total
        new_threshold = self.threshold_algorithm.update(threshold, ratio)
        return quantized, new_residual, new_threshold, ratio
