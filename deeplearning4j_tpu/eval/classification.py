"""Classification evaluation (Evaluation.java + ROC.java parity)."""

from __future__ import annotations

import numpy as np


class Evaluation:
    """Accuracy / precision / recall / F1 / confusion matrix.

    Reference: org/nd4j/evaluation/classification/Evaluation.java. Labels and
    predictions are one-hot/probability arrays [batch, classes] (or index
    vectors)."""

    def __init__(self, num_classes: int | None = None, labels: list[str] | None = None):
        self.num_classes = num_classes
        self.label_names = labels
        self.confusion: np.ndarray | None = None

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes), dtype=np.int64)
        elif n > self.num_classes:
            grown = np.zeros((n, n), dtype=np.int64)
            grown[: self.num_classes, : self.num_classes] = self.confusion
            self.confusion = grown
            self.num_classes = n

    def eval(self, labels, predictions):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim > 1:
            true_idx = labels.argmax(axis=-1)
            n = labels.shape[-1]
        else:
            true_idx = labels.astype(np.int64)
            n = int(true_idx.max()) + 1 if self.num_classes is None else self.num_classes
        pred_idx = predictions.argmax(axis=-1) if predictions.ndim > 1 else predictions.astype(np.int64)
        needed = int(
            max(
                predictions.shape[-1] if predictions.ndim > 1 else n,
                int(pred_idx.max()) + 1,
                int(true_idx.max()) + 1,
            )
        )
        self._ensure(needed)
        np.add.at(self.confusion, (true_idx.reshape(-1), pred_idx.reshape(-1)), 1)

    # ---- metrics (ND4J naming) -------------------------------------------
    def accuracy(self) -> float:
        c = self.confusion
        return float(np.trace(c) / max(c.sum(), 1))

    def precision(self, cls: int | None = None) -> float:
        c = self.confusion
        col = c.sum(axis=0)
        tp = np.diag(c)
        with np.errstate(invalid="ignore", divide="ignore"):
            per = np.where(col > 0, tp / col, np.nan)
        if cls is not None:
            return float(per[cls])
        return float(np.nanmean(per))

    def recall(self, cls: int | None = None) -> float:
        c = self.confusion
        row = c.sum(axis=1)
        tp = np.diag(c)
        with np.errstate(invalid="ignore", divide="ignore"):
            per = np.where(row > 0, tp / row, np.nan)
        if cls is not None:
            return float(per[cls])
        return float(np.nanmean(per))

    def f1(self, cls: int | None = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 0.0 if p + r == 0 else 2 * p * r / (p + r)

    def false_positive_rate(self, cls: int) -> float:
        c = self.confusion
        fp = c[:, cls].sum() - c[cls, cls]
        tn = c.sum() - c[cls, :].sum() - c[:, cls].sum() + c[cls, cls]
        return float(fp / max(fp + tn, 1))

    def confusion_matrix(self) -> np.ndarray:
        return self.confusion.copy()

    def stats(self) -> str:
        """Human-readable summary (Evaluation.stats() parity)."""
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
            str(self.confusion),
            "==================================================================",
        ]
        return "\n".join(lines)


class ROC:
    """Binary ROC/AUC via thresholded counts (ROC.java parity; exact mode)."""

    def __init__(self):
        self.scores: list[np.ndarray] = []
        self.labels: list[np.ndarray] = []

    def eval(self, labels, scores):
        labels = np.asarray(labels)
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = labels.argmax(axis=-1)  # one-hot -> class index
        labels = labels.reshape(-1)
        scores = np.asarray(scores)
        if scores.ndim > 1 and scores.shape[-1] == 2:
            scores = scores[..., 1]
        self.labels.append(labels)
        self.scores.append(scores.reshape(-1))

    def calculate_auc(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y, s = y[order], s[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        # collapse tied scores into one threshold point (ties form a single
        # ROC segment, giving AUC 0.5 for constant scores)
        last_of_group = np.r_[s[1:] != s[:-1], True]
        tps, fps = tps[last_of_group], fps[last_of_group]
        P, N = max(tps[-1], 1), max(fps[-1], 1)
        tpr = np.concatenate([[0.0], tps / P])
        fpr = np.concatenate([[0.0], fps / N])
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tps = np.cumsum(y)
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / max(tps[-1], 1)
        return float(np.trapezoid(precision, recall))


class ROCMultiClass:
    """org/nd4j/evaluation/classification/ROCMultiClass.java parity:
    one-vs-all ROC per class over probability outputs."""

    def __init__(self, num_classes: int | None = None):
        self.num_classes = num_classes
        self._rocs: list[ROC] | None = None

    def eval(self, labels, predictions):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n = labels.shape[-1]
        if self.num_classes is not None and self.num_classes != n:
            raise ValueError(
                f"num_classes={self.num_classes} but labels have {n} columns")
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(n)]
        for c, roc in enumerate(self._rocs):
            roc.eval(labels[:, c], predictions[:, c])
        return self

    def calculate_auc(self, cls: int) -> float:
        if self._rocs is None:
            raise ValueError("no data: call eval() first")
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        if self._rocs is None:
            raise ValueError("no data: call eval() first")
        return float(np.mean([r.calculate_auc() for r in self._rocs]))


class EvaluationCalibration:
    """org/nd4j/evaluation/classification/EvaluationCalibration.java parity:
    reliability diagram (confidence bins vs empirical accuracy), expected
    calibration error, and probability histograms."""

    def __init__(self, n_bins: int = 10):
        self.n_bins = n_bins
        self._bin_counts = np.zeros(n_bins, np.int64)
        self._bin_correct = np.zeros(n_bins, np.int64)
        self._bin_conf_sum = np.zeros(n_bins, np.float64)
        self._prob_hist = np.zeros(n_bins, np.int64)  # all predicted probs

    def eval(self, labels, predictions):
        labels = np.asarray(labels)
        p = np.asarray(predictions, np.float64)
        conf = p.max(axis=-1)
        pred_cls = p.argmax(axis=-1)
        true_cls = labels.argmax(axis=-1)
        bins = np.clip((conf * self.n_bins).astype(int), 0, self.n_bins - 1)
        np.add.at(self._bin_counts, bins, 1)
        np.add.at(self._bin_correct, bins, pred_cls == true_cls)
        np.add.at(self._bin_conf_sum, bins, conf)
        all_bins = np.clip((p.ravel() * self.n_bins).astype(int), 0,
                           self.n_bins - 1)
        np.add.at(self._prob_hist, all_bins, 1)
        return self

    def reliability_diagram(self):
        """→ (bin_centers, empirical_accuracy, mean_confidence, counts)."""
        centers = (np.arange(self.n_bins) + 0.5) / self.n_bins
        with np.errstate(invalid="ignore"):
            acc = np.where(self._bin_counts > 0,
                           self._bin_correct / np.maximum(self._bin_counts, 1),
                           np.nan)
            conf = np.where(self._bin_counts > 0,
                            self._bin_conf_sum / np.maximum(self._bin_counts, 1),
                            np.nan)
        return centers, acc, conf, self._bin_counts.copy()

    def expected_calibration_error(self) -> float:
        total = self._bin_counts.sum()
        if total == 0:
            return float("nan")
        _, acc, conf, counts = self.reliability_diagram()
        valid = counts > 0
        return float(np.sum(counts[valid] / total
                            * np.abs(acc[valid] - conf[valid])))

    def probability_histogram(self):
        return self._prob_hist.copy()


class EvaluationBinary:
    """Per-output binary metrics on multi-label sigmoid outputs
    (org/nd4j/evaluation/classification/EvaluationBinary.java, path-cite).

    Labels/predictions are [batch, n_outputs] with independent {0,1} labels
    per column; an optional (batch, n_outputs) mask excludes entries."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def _ensure(self, n: int):
        if self.tp is None:
            self.tp = np.zeros(n)
            self.fp = np.zeros(n)
            self.tn = np.zeros(n)
            self.fn = np.zeros(n)
        elif len(self.tp) != n:
            raise ValueError(
                f"EvaluationBinary was accumulated with {len(self.tp)} "
                f"outputs; this batch has {n}")

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            if preds.shape not in ((labels.shape[0],), labels.shape):
                raise ValueError(
                    f"predictions shape {preds.shape} != labels shape "
                    f"({labels.shape[0]},)")
            preds = preds.reshape(labels.shape)
        elif preds.shape != labels.shape:
            raise ValueError(
                f"predictions shape {preds.shape} != labels shape "
                f"{labels.shape}")
        self._ensure(labels.shape[1])
        pos = preds >= self.threshold
        lab = labels >= 0.5
        w = np.ones_like(labels, dtype=np.float64) if mask is None \
            else np.asarray(mask, dtype=np.float64).reshape(labels.shape)
        self.tp += np.sum(w * (pos & lab), axis=0)
        self.fp += np.sum(w * (pos & ~lab), axis=0)
        self.tn += np.sum(w * (~pos & ~lab), axis=0)
        self.fn += np.sum(w * (~pos & lab), axis=0)
        return self

    def num_outputs(self) -> int:
        if self.tp is None:
            raise ValueError("no data: call eval() first")
        return len(self.tp)

    def accuracy(self, i: int) -> float:
        self.num_outputs()  # no-data guard
        t = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / t) if t else 0.0

    def precision(self, i: int) -> float:
        self.num_outputs()  # no-data guard
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        self.num_outputs()  # no-data guard
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def average_accuracy(self) -> float:
        return float(np.mean([self.accuracy(i)
                              for i in range(self.num_outputs())]))

    def average_f1(self) -> float:
        return float(np.mean([self.f1(i) for i in range(self.num_outputs())]))

    def stats(self) -> str:
        rows = [f"  out {i}: acc={self.accuracy(i):.4f} "
                f"precision={self.precision(i):.4f} "
                f"recall={self.recall(i):.4f} f1={self.f1(i):.4f}"
                for i in range(self.num_outputs())]
        return "EvaluationBinary ({} outputs)\n{}".format(
            self.num_outputs(), "\n".join(rows))


class ROCBinary:
    """Per-output binary ROC/AUC for multi-label sigmoid outputs
    (org/nd4j/evaluation/classification/ROCBinary.java, path-cite, mount
    empty) — the ROC companion to EvaluationBinary. Labels/scores are
    [batch, n_outputs]; an optional same-shape mask excludes entries."""

    def __init__(self):
        self._rocs: "list[ROC]" = []

    def _ensure(self, n: int):
        if not self._rocs:
            self._rocs = [ROC() for _ in range(n)]
        elif len(self._rocs) != n:
            raise ValueError(
                f"ROCBinary was accumulated with {len(self._rocs)} outputs; "
                f"this batch has {n}")

    def eval(self, labels, scores, mask=None):
        labels = np.asarray(labels)
        scores = np.asarray(scores)
        if mask is not None:
            mask = np.asarray(mask)
        if labels.ndim == 1:
            labels = labels[:, None]
            scores = scores[:, None]
        if mask is not None and mask.ndim == 1:
            # per-example mask: applies to every output column
            mask = np.broadcast_to(mask[:, None], labels.shape)
        self._ensure(labels.shape[-1])
        for i, roc in enumerate(self._rocs):
            li, si = labels[:, i], scores[:, i]
            if mask is not None:
                keep = mask[:, i] > 0
                li, si = li[keep], si[keep]
            if li.size:
                roc.eval(li, si)

    def num_outputs(self) -> int:
        return len(self._rocs)

    def calculate_auc(self, output: int) -> float:
        return self._rocs[output].calculate_auc()

    def calculate_auprc(self, output: int) -> float:
        return self._rocs[output].calculate_auprc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))

    def stats(self) -> str:
        rows = [f"ROCBinary ({len(self._rocs)} outputs)"]
        for i, r in enumerate(self._rocs):
            rows.append(f"  output {i}: AUC {r.calculate_auc():.4f}  "
                        f"AUPRC {r.calculate_auprc():.4f}")
        return "\n".join(rows)
