"""Regression evaluation (org/nd4j/evaluation/regression/RegressionEvaluation.java
parity): per-column MSE/MAE/RMSE/RSE/R²/Pearson correlation."""

from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self):
        self._preds: list[np.ndarray] = []
        self._labels: list[np.ndarray] = []

    def eval(self, labels, predictions):
        labels = np.atleast_2d(np.asarray(labels, dtype=np.float64))
        predictions = np.atleast_2d(np.asarray(predictions, dtype=np.float64))
        self._labels.append(labels)
        self._preds.append(predictions)

    def _stacked(self):
        return np.concatenate(self._labels), np.concatenate(self._preds)

    def mean_squared_error(self, col: int | None = None):
        y, p = self._stacked()
        mse = np.mean((y - p) ** 2, axis=0)
        return float(mse[col]) if col is not None else float(mse.mean())

    def mean_absolute_error(self, col: int | None = None):
        y, p = self._stacked()
        mae = np.mean(np.abs(y - p), axis=0)
        return float(mae[col]) if col is not None else float(mae.mean())

    def root_mean_squared_error(self, col: int | None = None):
        return self.mean_squared_error(col) ** 0.5

    def r_squared(self, col: int | None = None):
        y, p = self._stacked()
        ss_res = np.sum((y - p) ** 2, axis=0)
        ss_tot = np.maximum(np.sum((y - y.mean(axis=0)) ** 2, axis=0), 1e-12)
        r2 = 1.0 - ss_res / ss_tot
        return float(r2[col]) if col is not None else float(r2.mean())

    def pearson_correlation(self, col: int = 0):
        y, p = self._stacked()
        return float(np.corrcoef(y[:, col], p[:, col])[0, 1])

    def stats(self) -> str:
        return (
            f"RegressionEvaluation: MSE={self.mean_squared_error():.6f} "
            f"MAE={self.mean_absolute_error():.6f} "
            f"RMSE={self.root_mean_squared_error():.6f} "
            f"R2={self.r_squared():.6f}"
        )
