"""Evaluation — classification/regression metrics + ROC.

Reference parity: org/nd4j/evaluation/classification/{Evaluation,ROC,
EvaluationBinary,EvaluationCalibration}.java and regression/
RegressionEvaluation.java — path-cite, mount empty this round. Accumulation
happens on the host in numpy (cheap; the expensive part — the forward pass —
stays on device).
"""

from deeplearning4j_tpu.eval.classification import (  # noqa: F401
    EvaluationBinary,
    ROCBinary,
    Evaluation,
    EvaluationCalibration,
    ROC,
    ROCMultiClass,
)
from deeplearning4j_tpu.eval.regression import RegressionEvaluation  # noqa: F401
