"""Dtype system.

Parity with ND4J's ``DataType`` enum (reference:
nd4j/nd4j-backends/nd4j-api-parent/nd4j-api/ org/nd4j/linalg/api/buffer/DataType.java,
path-cite — mount empty this round). The TPU-native twist: ``bfloat16`` is the
default compute dtype for MXU-bound work, while ``float32`` remains the default
parameter/accumulation dtype.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical names → jnp dtypes (DataType enum parity).
DOUBLE = jnp.float64
FLOAT = jnp.float32
HALF = jnp.float16
BFLOAT16 = jnp.bfloat16
INT64 = jnp.int64
INT32 = jnp.int32
INT16 = jnp.int16
INT8 = jnp.int8
UINT64 = jnp.uint64
UINT32 = jnp.uint32
UINT16 = jnp.uint16
UINT8 = jnp.uint8
BOOL = jnp.bool_

_BY_NAME = {
    "double": DOUBLE, "float64": DOUBLE,
    "float": FLOAT, "float32": FLOAT,
    "half": HALF, "float16": HALF,
    "bfloat16": BFLOAT16, "bf16": BFLOAT16,
    "long": INT64, "int64": INT64,
    "int": INT32, "int32": INT32,
    "short": INT16, "int16": INT16,
    "byte": INT8, "int8": INT8,
    "ulong": UINT64, "uint64": UINT64,
    "uint": UINT32, "uint32": UINT32,
    "ushort": UINT16, "uint16": UINT16,
    "ubyte": UINT8, "uint8": UINT8,
    "bool": BOOL,
}

FLOATING_DTYPES = (DOUBLE, FLOAT, HALF, BFLOAT16)
INTEGER_DTYPES = (INT64, INT32, INT16, INT8, UINT64, UINT32, UINT16, UINT8)

# Global defaults (Nd4j.setDefaultDataTypes parity).
_default_floating = FLOAT
_compute_dtype = BFLOAT16  # MXU-preferred dtype for matmul/conv compute.


def by_name(name: str):
    """Resolve a DataType by its ND4J-style name (case-insensitive)."""
    key = name.lower()
    if key not in _BY_NAME:
        raise ValueError(f"Unknown dtype name: {name!r}")
    return _BY_NAME[key]


def default_floating_dtype():
    return _default_floating


def set_default_floating_dtype(dtype) -> None:
    global _default_floating
    _default_floating = jnp.dtype(dtype)


def compute_dtype():
    """Dtype used for MXU-bound compute (matmul/conv) when mixed precision is on."""
    return _compute_dtype


def set_compute_dtype(dtype) -> None:
    global _compute_dtype
    _compute_dtype = jnp.dtype(dtype)


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_bool(dtype) -> bool:
    return jnp.dtype(dtype) == np.bool_
