"""Zoo model definitions (org/deeplearning4j/zoo/model/*.java parity).

Every model is TPU-first: NHWC layout, fused conv+bn+relu left to XLA,
ResNet/SqueezeNet/UNet expressed on ComputationGraph so the whole DAG traces
into one XLA program. ``compute_dtype='bfloat16'`` puts the convs on the MXU
in bf16 with fp32 params (recommended for benchmarks).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deeplearning4j_tpu.nn import (
    ComputationGraph,
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    Deconvolution2D,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    OutputLayer,
    SeparableConvolution2D,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs
from deeplearning4j_tpu.nn.vertices import ElementWiseVertex, MergeVertex, ScaleVertex


@dataclasses.dataclass
class ZooModel:
    """Base (org/deeplearning4j/zoo/ZooModel.java parity)."""

    num_classes: int = 1000
    seed: int = 12345
    input_shape: Tuple[int, int, int] = (224, 224, 3)  # HWC (NHWC batch layout)
    compute_dtype: str = "float32"
    updater: object = None

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the network (ZooModel.init parity)."""
        conf = self.conf()
        if hasattr(conf, "nodes"):
            return ComputationGraph(conf).init()
        return MultiLayerNetwork(conf).init()

    def pretrained(self, *a, **kw):
        raise NotImplementedError(
            "pretrained weights need network egress (reference downloads from "
            "dl4j blob storage); save/restore locally via ModelSerializer"
        )

    def _builder(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.updater or Adam(1e-3))
            .compute_dtype(self.compute_dtype)
        )


# ---------------------------------------------------------------------------
# Linear stacks (MultiLayerNetwork)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LeNet(ZooModel):
    """zoo/model/LeNet.java — BASELINE config #1."""

    num_classes: int = 10
    input_shape: Tuple[int, int, int] = (28, 28, 1)

    def conf(self):
        h, w, c = self.input_shape
        return (
            self._builder()
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), padding="VALID", activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), padding="VALID", activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_in=500, n_out=self.num_classes))
            .set_input_type(InputType.convolutional(h, w, c))
            .build()
        )


@dataclasses.dataclass
class SimpleCNN(ZooModel):
    """zoo/model/SimpleCNN.java."""

    num_classes: int = 10
    input_shape: Tuple[int, int, int] = (48, 48, 3)

    def conf(self):
        h, w, c = self.input_shape
        return (
            self._builder()
            .list()
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3), activation="relu"))
            .layer(BatchNormalization())
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3), activation="relu"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3), activation="relu"))
            .layer(BatchNormalization())
            .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3), activation="relu"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DropoutLayer(rate=0.5))
            .layer(GlobalPoolingLayer())
            .layer(OutputLayer(n_in=32, n_out=self.num_classes))
            .set_input_type(InputType.convolutional(h, w, c))
            .build()
        )


@dataclasses.dataclass
class AlexNet(ZooModel):
    """zoo/model/AlexNet.java (one-tower variant)."""

    def conf(self):
        h, w, c = self.input_shape
        return (
            self._builder()
            .list()
            .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4), padding="VALID", activation="relu"))
            .layer(LocalResponseNormalization())
            .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5), activation="relu"))
            .layer(LocalResponseNormalization())
            .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), activation="relu"))
            .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), activation="relu"))
            .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(DenseLayer(n_in=4096, n_out=4096, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_in=4096, n_out=self.num_classes))
            .set_input_type(InputType.convolutional(h, w, c))
            .build()
        )


def _vgg_blocks(lb, spec):
    for n_convs, channels in spec:
        for _ in range(n_convs):
            lb.layer(ConvolutionLayer(n_out=channels, kernel_size=(3, 3), activation="relu"))
        lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    return lb


@dataclasses.dataclass
class VGG16(ZooModel):
    """zoo/model/VGG16.java."""

    spec = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))

    def conf(self):
        h, w, c = self.input_shape
        lb = self._builder().list()
        _vgg_blocks(lb, self.spec)
        return (
            lb.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(DenseLayer(n_in=4096, n_out=4096, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_in=4096, n_out=self.num_classes))
            .set_input_type(InputType.convolutional(h, w, c))
            .build()
        )


@dataclasses.dataclass
class VGG19(VGG16):
    """zoo/model/VGG19.java."""

    spec = ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512))


@dataclasses.dataclass
class Darknet19(ZooModel):
    """zoo/model/Darknet19.java."""

    def conf(self):
        h, w, c = self.input_shape

        def conv_bn(lb, n_out, k):
            lb.layer(ConvolutionLayer(n_out=n_out, kernel_size=(k, k), has_bias=False))
            lb.layer(BatchNormalization())
            lb.layer(ActivationLayer(activation="leakyrelu"))

        lb = self._builder().list()
        conv_bn(lb, 32, 3)
        lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(lb, 64, 3)
        lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for a, b_, k in ((128, 64, 3), (256, 128, 3)):
            conv_bn(lb, a, k)
            conv_bn(lb, b_, 1)
            conv_bn(lb, a, k)
            lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for a, b_ in ((512, 256), (1024, 512)):
            conv_bn(lb, a, 3)
            conv_bn(lb, b_, 1)
            conv_bn(lb, a, 3)
            conv_bn(lb, b_, 1)
            conv_bn(lb, a, 3)
            if a == 512:
                lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        lb.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1)))
        lb.layer(GlobalPoolingLayer())
        return (
            lb.layer(OutputLayer(n_in=self.num_classes, n_out=self.num_classes))
            .set_input_type(InputType.convolutional(h, w, c))
            .build()
        )


# ---------------------------------------------------------------------------
# DAG models (ComputationGraph)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResNet50(ZooModel):
    """zoo/model/ResNet50.java — BASELINE config #2 and the flagship bench
    model. ResNet-v1 bottleneck layout (stride on the first 1x1, as in the
    reference/Keras); NHWC; every block is conv→bn→relu chains XLA fuses.

    ``remat_policy``/``stage_barriers`` engage the fusion-boundary subsystem
    (util/xla_tuning.py): residual-stage boundaries (stem, res2–res5) are
    always recorded in the config; a named policy selectively rematerializes
    each stage in the backward pass (save conv outputs, recompute the cheap
    BN/elementwise epilogue), barriers fence XLA fusion at the boundaries.
    The default stays ``None`` per the measured record — see BASELINE.md's
    fusion-sweep table before changing it."""

    updater: object = None
    remat_policy: Optional[str] = None
    stage_barriers: bool = False

    def conf(self):
        h, w, c = self.input_shape
        b = self._builder()
        if self.remat_policy is not None:
            b.remat_policy(self.remat_policy)
        if self.stage_barriers:
            b.stage_barriers(True)
        gb = b.graph_builder().add_inputs("input")

        def conv_bn(name, inp, n_out, k, stride=(1, 1), relu=True, pad="SAME"):
            gb.add_layer(
                f"{name}_conv",
                ConvolutionLayer(n_out=n_out, kernel_size=(k, k), stride=stride,
                                 padding=pad, has_bias=False),
                inp,
            )
            gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            if relu:
                gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_bn")
                return f"{name}_relu"
            return f"{name}_bn"

        def bottleneck(name, inp, filters, stride, project):
            f1, f2, f3 = filters
            x = conv_bn(f"{name}_a", inp, f1, 1, stride=stride)
            x = conv_bn(f"{name}_b", x, f2, 3)
            x = conv_bn(f"{name}_c", x, f3, 1, relu=False)
            if project:
                sc = conv_bn(f"{name}_sc", inp, f3, 1, stride=stride, relu=False)
            else:
                sc = inp
            gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
            gb.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
            return f"{name}_out"

        x = conv_bn("stem", "input", 64, 7, stride=(2, 2))
        gb.add_layer("stem_pool", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2), padding="SAME"), x)
        x = "stem_pool"
        gb.stage_boundary("stem_pool")
        stages = [
            ("res2", 3, (64, 64, 256), (1, 1)),
            ("res3", 4, (128, 128, 512), (2, 2)),
            ("res4", 6, (256, 256, 1024), (2, 2)),
            ("res5", 3, (512, 512, 2048), (2, 2)),
        ]
        for sname, blocks, filters, stride in stages:
            x = bottleneck(f"{sname}a", x, filters, stride, project=True)
            for i in range(1, blocks):
                x = bottleneck(f"{sname}{chr(ord('a') + i)}", x, filters, (1, 1), project=False)
            gb.stage_boundary(x)  # stage end (res2c_out … res5c_out)
        gb.add_layer("avgpool", GlobalPoolingLayer(), x)
        gb.add_layer("output", OutputLayer(n_in=2048, n_out=self.num_classes), "avgpool")
        gb.set_outputs("output")
        gb.set_input_types(InputType.convolutional(h, w, c))
        return gb.build()


@dataclasses.dataclass
class SqueezeNet(ZooModel):
    """zoo/model/SqueezeNet.java — fire modules on ComputationGraph."""

    def conf(self):
        h, w, c = self.input_shape
        gb = self._builder().graph_builder().add_inputs("input")

        def fire(name, inp, squeeze, expand):
            gb.add_layer(f"{name}_sq", ConvolutionLayer(n_out=squeeze, kernel_size=(1, 1), activation="relu"), inp)
            gb.add_layer(f"{name}_e1", ConvolutionLayer(n_out=expand, kernel_size=(1, 1), activation="relu"), f"{name}_sq")
            gb.add_layer(f"{name}_e3", ConvolutionLayer(n_out=expand, kernel_size=(3, 3), activation="relu"), f"{name}_sq")
            gb.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
            return f"{name}_cat"

        gb.add_layer("conv1", ConvolutionLayer(n_out=64, kernel_size=(3, 3), stride=(2, 2), padding="VALID", activation="relu"), "input")
        gb.add_layer("pool1", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), "conv1")
        x = fire("fire2", "pool1", 16, 64)
        x = fire("fire3", x, 16, 64)
        gb.add_layer("pool3", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), x)
        x = fire("fire4", "pool3", 32, 128)
        x = fire("fire5", x, 32, 128)
        gb.add_layer("pool5", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), x)
        x = fire("fire6", "pool5", 48, 192)
        x = fire("fire7", x, 48, 192)
        x = fire("fire8", x, 64, 256)
        x = fire("fire9", x, 64, 256)
        gb.add_layer("drop9", DropoutLayer(rate=0.5), x)
        gb.add_layer("conv10", ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1), activation="relu"), "drop9")
        gb.add_layer("gap", GlobalPoolingLayer(), "conv10")
        gb.add_layer("output", OutputLayer(n_in=self.num_classes, n_out=self.num_classes), "gap")
        gb.set_outputs("output")
        gb.set_input_types(InputType.convolutional(h, w, c))
        return gb.build()


@dataclasses.dataclass
class UNet(ZooModel):
    """zoo/model/UNet.java — encoder/decoder with skip merges. Output is a
    per-pixel sigmoid map on CnnLossLayer with XENT, as in the reference."""

    num_classes: int = 1
    input_shape: Tuple[int, int, int] = (128, 128, 3)
    base_filters: int = 16  # reference uses 64; configurable for memory

    def conf(self):
        h, w, c = self.input_shape
        f = self.base_filters
        gb = self._builder().graph_builder().add_inputs("input")

        def double_conv(name, inp, n_out):
            gb.add_layer(f"{name}_c1", ConvolutionLayer(n_out=n_out, kernel_size=(3, 3), activation="relu"), inp)
            gb.add_layer(f"{name}_c2", ConvolutionLayer(n_out=n_out, kernel_size=(3, 3), activation="relu"), f"{name}_c1")
            return f"{name}_c2"

        # encoder
        skips = []
        x = "input"
        for i, mult in enumerate((1, 2, 4, 8)):
            x = double_conv(f"enc{i}", x, f * mult)
            skips.append(x)
            gb.add_layer(f"down{i}", SubsamplingLayer(kernel_size=(2, 2)), x)
            x = f"down{i}"
        x = double_conv("mid", x, f * 16)
        # decoder
        for i, mult in zip(range(3, -1, -1), (8, 4, 2, 1)):
            gb.add_layer(f"up{i}", Deconvolution2D(n_out=f * mult, kernel_size=(2, 2), stride=(2, 2), activation="relu"), x)
            gb.add_vertex(f"skip{i}", MergeVertex(), f"up{i}", skips[i])
            x = double_conv(f"dec{i}", f"skip{i}", f * mult)
        from deeplearning4j_tpu.nn.layers_special import CnnLossLayer

        gb.add_layer("logits", ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1)), x)
        gb.add_layer("output", CnnLossLayer(loss="xent", activation="sigmoid"), "logits")
        gb.set_outputs("output")
        gb.set_input_types(InputType.convolutional(h, w, c))
        return gb.build()


@dataclasses.dataclass
class Xception(ZooModel):
    """zoo/model/Xception.java — separable convs with residual connections
    (entry/middle/exit flow; middle-flow repeats configurable)."""

    middle_repeats: int = 8

    def conf(self):
        h, w, c = self.input_shape
        gb = self._builder().graph_builder().add_inputs("input")

        def conv_bn(name, inp, n_out, k, stride=(1, 1), relu=True):
            gb.add_layer(f"{name}_conv", ConvolutionLayer(n_out=n_out, kernel_size=(k, k), stride=stride, has_bias=False), inp)
            gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            if relu:
                gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_bn")
                return f"{name}_relu"
            return f"{name}_bn"

        def sep_bn(name, inp, n_out, relu_before=True):
            src = inp
            if relu_before:
                gb.add_layer(f"{name}_prerelu", ActivationLayer(activation="relu"), inp)
                src = f"{name}_prerelu"
            gb.add_layer(f"{name}_sep", SeparableConvolution2D(n_out=n_out, kernel_size=(3, 3), has_bias=False), src)
            gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_sep")
            return f"{name}_bn"

        x = conv_bn("stem1", "input", 32, 3, stride=(2, 2))
        x = conv_bn("stem2", x, 64, 3)
        # entry-flow residual blocks
        for i, n_out in enumerate((128, 256, 728)):
            sc = conv_bn(f"entry{i}_sc", x, n_out, 1, stride=(2, 2), relu=False)
            b = sep_bn(f"entry{i}_s1", x, n_out, relu_before=i > 0)
            b = sep_bn(f"entry{i}_s2", b, n_out)
            gb.add_layer(f"entry{i}_pool", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2), padding="SAME"), b)
            gb.add_vertex(f"entry{i}_add", ElementWiseVertex(op="add"), f"entry{i}_pool", sc)
            x = f"entry{i}_add"
        # middle flow
        for r in range(self.middle_repeats):
            b = x
            for j in range(3):
                b = sep_bn(f"mid{r}_s{j}", b, 728)
            gb.add_vertex(f"mid{r}_add", ElementWiseVertex(op="add"), b, x)
            x = f"mid{r}_add"
        # exit flow
        sc = conv_bn("exit_sc", x, 1024, 1, stride=(2, 2), relu=False)
        b = sep_bn("exit_s1", x, 728)
        b = sep_bn("exit_s2", b, 1024)
        gb.add_layer("exit_pool", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2), padding="SAME"), b)
        gb.add_vertex("exit_add", ElementWiseVertex(op="add"), "exit_pool", sc)
        b = sep_bn("exit_s3", "exit_add", 1536, relu_before=False)
        gb.add_layer("exit_relu3", ActivationLayer(activation="relu"), b)
        b = sep_bn("exit_s4", "exit_relu3", 2048, relu_before=False)
        gb.add_layer("exit_relu4", ActivationLayer(activation="relu"), b)
        gb.add_layer("gap", GlobalPoolingLayer(), "exit_relu4")
        gb.add_layer("output", OutputLayer(n_in=2048, n_out=self.num_classes), "gap")
        gb.set_outputs("output")
        gb.set_input_types(InputType.convolutional(h, w, c))
        return gb.build()


@dataclasses.dataclass
class TextGenerationLSTM(ZooModel):
    """zoo/model/TextGenerationLSTM.java — char-level generation: stacked
    LSTMs + per-timestep softmax (the GravesLSTM char-RNN, BASELINE #3's
    model family). Input (B,T,vocab) one-hot; output per-step distribution."""

    total_unique_characters: int = 47
    units: int = 256
    dropout: float = 0.2
    max_length: int = 40

    def conf(self):
        from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer

        v = self.total_unique_characters
        lb = self._builder().list()
        lb.layer(LSTM(n_in=v, n_out=self.units))
        lb.layer(LSTM(n_in=self.units, n_out=self.units, dropout=self.dropout))
        lb.layer(RnnOutputLayer(n_in=self.units, n_out=v, loss="mcxent",
                                activation="softmax", dropout=self.dropout))
        lb.set_input_type(InputType.recurrent(v, self.max_length))
        return lb.build()


@dataclasses.dataclass
class TinyYOLO(ZooModel):
    """zoo/model/TinyYOLO.java — Darknet-tiny backbone + YOLOv2 head.
    Input HxW divisible by 32; output grid (H/32, W/32)."""

    input_shape: Tuple[int, int, int] = (416, 416, 3)
    num_classes: int = 20
    anchors: tuple = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                      (9.42, 5.11), (16.62, 10.52))

    def conf(self):
        from deeplearning4j_tpu.nn.objdetect import Yolo2OutputLayer

        h, w, c = self.input_shape
        a = len(self.anchors)
        lb = self._builder().list()

        def conv_bn(n_out, k=3):
            lb.layer(ConvolutionLayer(n_out=n_out, kernel_size=(k, k), has_bias=False))
            lb.layer(BatchNormalization())
            lb.layer(ActivationLayer(activation="leakyrelu"))

        for i, n in enumerate((16, 32, 64, 128, 256)):
            conv_bn(n)
            lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(512)
        lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(1, 1), padding="SAME"))
        conv_bn(1024)
        conv_bn(1024)
        lb.layer(ConvolutionLayer(n_out=a * (5 + self.num_classes),
                                  kernel_size=(1, 1)))
        lb.layer(Yolo2OutputLayer(anchors=self.anchors))
        lb.set_input_type(InputType.convolutional(h, w, c))
        return lb.build()


@dataclasses.dataclass
class YOLO2(TinyYOLO):
    """zoo/model/YOLO2.java — Darknet-19 backbone + YOLOv2 detection head
    (without the passthrough/reorg skip of the full paper model, like the
    reference's simplified zoo config)."""

    anchors: tuple = ((0.57273, 0.677385), (1.87446, 2.06253),
                      (3.33843, 5.47434), (7.88282, 3.52778),
                      (9.77052, 9.16828))

    def conf(self):
        from deeplearning4j_tpu.nn.objdetect import Yolo2OutputLayer

        h, w, c = self.input_shape
        a = len(self.anchors)
        lb = self._builder().list()

        def conv_bn(n_out, k):
            lb.layer(ConvolutionLayer(n_out=n_out, kernel_size=(k, k), has_bias=False))
            lb.layer(BatchNormalization())
            lb.layer(ActivationLayer(activation="leakyrelu"))

        conv_bn(32, 3)
        lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(64, 3)
        lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for big, small in ((128, 64), (256, 128)):
            conv_bn(big, 3)
            conv_bn(small, 1)
            conv_bn(big, 3)
            lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for big, small in ((512, 256), (1024, 512)):
            conv_bn(big, 3)
            conv_bn(small, 1)
            conv_bn(big, 3)
            conv_bn(small, 1)
            conv_bn(big, 3)
            if big == 512:
                lb.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conv_bn(1024, 3)
        conv_bn(1024, 3)
        lb.layer(ConvolutionLayer(n_out=a * (5 + self.num_classes),
                                  kernel_size=(1, 1)))
        lb.layer(Yolo2OutputLayer(anchors=self.anchors))
        lb.set_input_type(InputType.convolutional(h, w, c))
        return lb.build()


@dataclasses.dataclass
class InceptionResNetV1(ZooModel):
    """zoo/model/InceptionResNetV1.java — the FaceNet embedding network:
    stem + 5x block35 + reduction-A + 10x block17 + reduction-B + 5x block8,
    global pool, 128-d L2-normalized embedding + softmax head."""

    input_shape: Tuple[int, int, int] = (160, 160, 3)
    embedding_size: int = 128

    def conf(self):
        from deeplearning4j_tpu.nn.vertices import L2NormalizeVertex

        h, w, c = self.input_shape
        gb = self._builder().graph_builder().add_inputs("input")
        uid = [0]

        def conv_bn(inp, n_out, k, stride=(1, 1), pad="SAME", relu=True):
            uid[0] += 1
            name = f"cb{uid[0]}"
            gb.add_layer(f"{name}_c", ConvolutionLayer(
                n_out=n_out, kernel_size=(k, k) if isinstance(k, int) else k,
                stride=stride, padding=pad, has_bias=False), inp)
            gb.add_layer(f"{name}_b", BatchNormalization(), f"{name}_c")
            if not relu:
                return f"{name}_b"
            gb.add_layer(f"{name}_r", ActivationLayer(activation="relu"), f"{name}_b")
            return f"{name}_r"

        def block35(inp, scale=0.17):  # Inception-ResNet-A
            uid[0] += 1
            name = f"a{uid[0]}"
            b0 = conv_bn(inp, 32, 1)
            b1 = conv_bn(conv_bn(inp, 32, 1), 32, 3)
            b2 = conv_bn(conv_bn(conv_bn(inp, 32, 1), 32, 3), 32, 3)
            gb.add_vertex(f"{name}_cat", MergeVertex(), b0, b1, b2)
            up = conv_bn(f"{name}_cat", 256, 1, relu=False)
            gb.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp, f"{name}_scale")
            gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
            return f"{name}_relu"

        def block17(inp, scale=0.10):  # Inception-ResNet-B
            uid[0] += 1
            name = f"b{uid[0]}"
            b0 = conv_bn(inp, 128, 1)
            b1 = conv_bn(conv_bn(conv_bn(inp, 128, 1), 128, (1, 7)), 128, (7, 1))
            gb.add_vertex(f"{name}_cat", MergeVertex(), b0, b1)
            up = conv_bn(f"{name}_cat", 896, 1, relu=False)
            gb.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp, f"{name}_scale")
            gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
            return f"{name}_relu"

        def block8(inp, scale=0.20):  # Inception-ResNet-C
            uid[0] += 1
            name = f"c{uid[0]}"
            b0 = conv_bn(inp, 192, 1)
            b1 = conv_bn(conv_bn(conv_bn(inp, 192, 1), 192, (1, 3)), 192, (3, 1))
            gb.add_vertex(f"{name}_cat", MergeVertex(), b0, b1)
            up = conv_bn(f"{name}_cat", 1792, 1, relu=False)
            gb.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp, f"{name}_scale")
            gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
            return f"{name}_relu"

        # stem
        x = conv_bn("input", 32, 3, stride=(2, 2))
        x = conv_bn(x, 32, 3, pad="VALID")
        x = conv_bn(x, 64, 3)
        gb.add_layer("stem_pool", SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), x)
        x = conv_bn("stem_pool", 80, 1)
        x = conv_bn(x, 192, 3, pad="VALID")
        x = conv_bn(x, 256, 3, stride=(2, 2))
        for _ in range(5):
            x = block35(x)
        # reduction-A → 896 channels
        ra0 = conv_bn(x, 384, 3, stride=(2, 2), pad="VALID")
        ra1 = conv_bn(conv_bn(conv_bn(x, 192, 1), 192, 3), 256, 3,
                      stride=(2, 2), pad="VALID")
        gb.add_layer("redA_pool", SubsamplingLayer(kernel_size=(3, 3),
                                                   stride=(2, 2)), x)
        gb.add_vertex("redA", MergeVertex(), ra0, ra1, "redA_pool")
        x = "redA"
        for _ in range(10):
            x = block17(x)
        # reduction-B → 1792 channels
        rb0 = conv_bn(conv_bn(x, 256, 1), 384, 3, stride=(2, 2), pad="VALID")
        rb1 = conv_bn(conv_bn(x, 256, 1), 256, 3, stride=(2, 2), pad="VALID")
        rb2 = conv_bn(conv_bn(conv_bn(x, 256, 1), 256, 3), 256, 3,
                      stride=(2, 2), pad="VALID")
        gb.add_layer("redB_pool", SubsamplingLayer(kernel_size=(3, 3),
                                                   stride=(2, 2)), x)
        gb.add_vertex("redB", MergeVertex(), rb0, rb1, rb2, "redB_pool")
        x = "redB"
        for _ in range(5):
            x = block8(x)
        gb.add_layer("gap", GlobalPoolingLayer(), x)
        gb.add_layer("embedding", DenseLayer(n_in=1792, n_out=self.embedding_size), "gap")
        gb.add_vertex("embed_norm", L2NormalizeVertex(), "embedding")
        gb.add_layer("output", OutputLayer(n_in=self.embedding_size,
                                           n_out=self.num_classes), "embed_norm")
        gb.set_outputs("output")
        gb.set_input_types(InputType.convolutional(h, w, c))
        return gb.build()


@dataclasses.dataclass
class FaceNetNN4Small2(ZooModel):
    """zoo/model/FaceNetNN4Small2.java — the OpenFace nn4.small2 inception
    face-embedding net (path-cite, mount empty): 7×7/2 stem, inception-2
    3a/3b/3c/4a/4e/5a/5b mixed modules (1×1 + reduced 3×3 + reduced 5×5 +
    pool-projection branches), avg pool, 128-d L2-normalized embedding,
    softmax head for classifier training."""

    input_shape: Tuple[int, int, int] = (96, 96, 3)
    embedding_size: int = 128

    def conf(self):
        from deeplearning4j_tpu.nn.vertices import L2NormalizeVertex

        h, w, c = self.input_shape
        gb = self._builder().graph_builder().add_inputs("input")
        uid = [0]

        def conv_bn(inp, n_out, k, stride=(1, 1), pad="SAME"):
            uid[0] += 1
            name = f"f{uid[0]}"
            gb.add_layer(f"{name}_c", ConvolutionLayer(
                n_out=n_out, kernel_size=(k, k) if isinstance(k, int) else k,
                stride=stride, padding=pad, has_bias=False), inp)
            gb.add_layer(f"{name}_b", BatchNormalization(), f"{name}_c")
            gb.add_layer(f"{name}_r", ActivationLayer(activation="relu"),
                         f"{name}_b")
            return f"{name}_r"

        def inception(inp, c1, r3, c3, r5, c5, pool_proj, stride=(1, 1)):
            """nn4.small2 mixed module; any branch with 0 channels is
            omitted (the reference's 3c/4e reduction modules)."""
            uid[0] += 1
            name = f"inc{uid[0]}"
            branches = []
            if c1:
                branches.append(conv_bn(inp, c1, 1))
            if c3:
                branches.append(conv_bn(conv_bn(inp, r3, 1), c3, 3,
                                        stride=stride))
            if c5:
                branches.append(conv_bn(conv_bn(inp, r5, 1), c5, 5,
                                        stride=stride))
            pname = f"{name}_pool"
            gb.add_layer(pname, SubsamplingLayer(
                kernel_size=(3, 3), stride=stride, padding="SAME"), inp)
            branches.append(conv_bn(pname, pool_proj, 1)
                            if pool_proj else pname)
            gb.add_vertex(name, MergeVertex(), *branches)
            return name

        x = conv_bn("input", 64, 7, stride=(2, 2))
        gb.add_layer("p1", SubsamplingLayer(kernel_size=(3, 3),
                                            stride=(2, 2), padding="SAME"), x)
        x = conv_bn("p1", 64, 1)
        x = conv_bn(x, 192, 3)
        gb.add_layer("p2", SubsamplingLayer(kernel_size=(3, 3),
                                            stride=(2, 2), padding="SAME"), x)
        # nn4.small2 channel table
        x = inception("p2", 64, 96, 128, 16, 32, 32)       # 3a
        x = inception(x, 64, 96, 128, 32, 64, 64)          # 3b
        x = inception(x, 0, 128, 256, 32, 64, 0,
                      stride=(2, 2))                       # 3c (reduction)
        x = inception(x, 256, 96, 192, 32, 64, 128)        # 4a
        x = inception(x, 0, 160, 256, 64, 128, 0,
                      stride=(2, 2))                       # 4e (reduction)
        x = inception(x, 256, 96, 384, 0, 0, 96)           # 5a
        x = inception(x, 256, 96, 384, 0, 0, 96)           # 5b
        gb.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        gb.add_layer("embedding", DenseLayer(
            n_in=736, n_out=self.embedding_size), "gap")
        gb.add_vertex("embed_norm", L2NormalizeVertex(), "embedding")
        gb.add_layer("output", OutputLayer(n_in=self.embedding_size,
                                           n_out=self.num_classes),
                     "embed_norm")
        gb.set_outputs("output")
        gb.set_input_types(InputType.convolutional(h, w, c))
        return gb.build()
