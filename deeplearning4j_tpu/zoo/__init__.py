"""Model zoo — standard architectures as ready-to-init configs.

Reference parity: deeplearning4j-zoo (SURVEY.md §2.2 J14:
org/deeplearning4j/zoo/model/{LeNet,AlexNet,VGG16,VGG19,ResNet50,SqueezeNet,
Darknet19,UNet,Xception,SimpleCNN,TextGenerationLSTM}.java, each a ZooModel
with conf() + init()) — path-cite, mount empty this round.

Pretrained-weight download is stubbed: this machine has no egress; use
ModelSerializer restore for locally saved weights instead.
"""

from deeplearning4j_tpu.zoo.bert import Bert  # noqa: F401
from deeplearning4j_tpu.zoo.unet import DiffusionUNet  # noqa: F401
from deeplearning4j_tpu.zoo.models import (  # noqa: F401
    AlexNet,
    Darknet19,
    FaceNetNN4Small2,
    LeNet,
    ResNet50,
    SimpleCNN,
    InceptionResNetV1,
    SqueezeNet,
    TextGenerationLSTM,
    TinyYOLO,
    YOLO2,
    UNet,
    VGG16,
    VGG19,
    Xception,
    ZooModel,
)
