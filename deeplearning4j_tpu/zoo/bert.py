"""BERT models: native transformer-encoder configs (BASELINE config #4).

Reference parity: the reference runs BERT as a TF-imported frozen SameDiff
graph (BASELINE.json config #4 "BERT-base fine-tune (SameDiff TF import)";
SURVEY.md §3.3) — it has no native BERT model class. Here BERT is a
first-class zoo model over the transformer layer family, so fine-tune and
masked-LM pretraining run through the ordinary MultiLayerNetwork.fit() path
as ONE jitted train step; the TF-import route remains available through
deeplearning4j_tpu.samediff for graph-parity work.

Input convention (matches nlp.BertIterator): features (B,T,2) stacked
[token_ids, segment_ids], features_mask (B,T).
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.recurrent import RnnOutputLayer
from deeplearning4j_tpu.nn.transformer import (
    BertEmbeddingLayer,
    TimeStepLayer,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork
from deeplearning4j_tpu.zoo.models import ZooModel


@dataclasses.dataclass
class Bert(ZooModel):
    """Configurable BERT encoder. ``base()``/``tiny()`` give standard sizes;
    ``task`` selects the head: "classification" ([CLS] → pooler → softmax over
    num_classes) or "mlm" (per-token softmax over the vocab)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_size: int = 0  # 0 → 4*hidden
    max_length: int = 128
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    task: str = "classification"
    num_classes: int = 2
    flash: object = "auto"  # True | False | "auto" (measured-crossover dispatch)
    causal: bool = False  # decoder-only (GPT-style) blocks — with
    # task="mlm" this is an autoregressive LM whose per-token softmax head
    # drives the KV-cache serving path (serving/generate.py)

    @classmethod
    def base(cls, **kw):
        kw.setdefault("hidden_size", 768)
        kw.setdefault("n_layers", 12)
        kw.setdefault("n_heads", 12)
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("n_layers", 24)
        kw.setdefault("n_heads", 16)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        """BERT-tiny (2L/128H) — test/CI size."""
        kw.setdefault("hidden_size", 128)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 2)
        return cls(**kw)

    @classmethod
    def draft(cls, **kw):
        """Draft-model size (1L/64H, causal, no dropout) for speculative
        decoding (serving/generate.py): a few-percent-of-target net that
        proposes tokens the target verifies in one batched window. Share
        the target's ``vocab_size``/``max_length`` when constructing."""
        kw.setdefault("hidden_size", 64)
        kw.setdefault("n_layers", 1)
        kw.setdefault("n_heads", 1)
        kw.setdefault("hidden_dropout", 0.0)
        kw.setdefault("causal", True)
        kw.setdefault("task", "mlm")
        return cls(**kw)

    def conf(self):
        lb = self._builder().list()
        lb.layer(BertEmbeddingLayer(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            max_position=self.max_length, type_vocab_size=self.type_vocab_size,
            dropout=self.hidden_dropout))
        for _ in range(self.n_layers):
            lb.layer(TransformerEncoderBlock(
                hidden_size=self.hidden_size, n_heads=self.n_heads,
                ffn_size=self.ffn_size, hidden_dropout=self.hidden_dropout,
                flash=self.flash, causal=self.causal))
        if self.task == "classification":
            lb.layer(TimeStepLayer(index=0))  # [CLS]
            lb.layer(DenseLayer(n_in=self.hidden_size, n_out=self.hidden_size,
                                activation="tanh"))  # pooler
            lb.layer(OutputLayer(n_in=self.hidden_size, n_out=self.num_classes,
                                 loss="mcxent", activation="softmax"))
        elif self.task == "mlm":
            lb.layer(RnnOutputLayer(n_in=self.hidden_size, n_out=self.vocab_size,
                                    loss="mcxent", activation="softmax"))
        else:
            raise ValueError(f"unknown task {self.task!r}")
        lb.set_input_type(InputType.recurrent(2, self.max_length))
        return lb.build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
