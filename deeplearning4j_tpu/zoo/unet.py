"""Diffusion U-Net zoo workload (ROADMAP item 5).

A DDPM-style noise-prediction U-Net on the ComputationGraph DSL: conv-heavy
encoder/decoder with skip connections (MergeVertex concat, the U-Net paper's
copy-and-crop collapsed to same-size concat at SAME padding), stride-2 conv
downsampling, Upsampling2D decoder, and a sinusoidal-free timestep
conditioning path — a 2-layer MLP embedding of the scalar diffusion step,
broadcast-added onto the bottleneck feature map (ReshapeVertex to (1,1,E) +
ElementWiseVertex add). The head is a 1x1 conv predicting the per-pixel
noise, trained with plain MSE (the DDPM simple loss).

Why it exists here: the zoo's conv workloads were all classification heads —
this one stresses (a) the per-layer conv cost model on a DAG whose FLOPs are
split across resolutions (util/cost_model.py rows must still reconcile), and
(b) the compressed-DP path end-to-end on a conv topology with
multi-megabyte gradients (tests/test_zoo_unet.py fits it through
``ParallelWrapper(grad_compression="threshold")``).

Reference framing: the reference zoo ships UNet.java (segmentation); the
diffusion variant differs only in the conditioning path and the regression
head — the encoder/decoder skeleton is UNet.java's.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.nn import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    LossLayer,
    Upsampling2D,
)
from deeplearning4j_tpu.nn.vertices import (
    ElementWiseVertex,
    MergeVertex,
    ReshapeVertex,
)
from deeplearning4j_tpu.zoo.models import ZooModel


@dataclasses.dataclass
class DiffusionUNet(ZooModel):
    """Noise-prediction U-Net: ``fit([image, timestep], [noise])``.

    ``image``: (H, W, C) NHWC, ``timestep``: (1,) scalar diffusion step
    (normalize to [0, 1] on the host), label: (H, W, C) noise target.
    ``depth`` downsamplings halve the resolution each level (H, W must be
    divisible by 2**depth); channels grow ``base_channels * 2**level``.
    """

    input_shape: Tuple[int, int, int] = (32, 32, 3)
    base_channels: int = 16
    depth: int = 2
    time_embed: int = 0  # 0 = base_channels * 2**depth (bottleneck width)

    def conf(self):
        h, w, c = self.input_shape
        if h % (2 ** self.depth) or w % (2 ** self.depth):
            raise ValueError(
                f"input {h}x{w} not divisible by 2**depth={2 ** self.depth}")
        gb = (self._builder().graph_builder()
              .add_inputs("image", "timestep"))

        def conv_block(name, inp, n_out, stride=(1, 1)):
            gb.add_layer(f"{name}_conv",
                         ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                          stride=stride, padding="SAME",
                                          has_bias=False), inp)
            gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                         f"{name}_bn")
            return f"{name}_relu"

        # ---------------------------------------------------------- encoder
        x = conv_block("stem", "image", self.base_channels)
        skips = []
        ch = self.base_channels
        for lvl in range(self.depth):
            x = conv_block(f"enc{lvl}_a", x, ch)
            skips.append((x, ch))
            ch *= 2
            # stride-2 conv downsample (the reference UNet's pool, as conv)
            x = conv_block(f"enc{lvl}_down", x, ch, stride=(2, 2))

        # ------------------------------------------- bottleneck + time MLP
        x = conv_block("mid_a", x, ch)
        emb = self.time_embed or ch
        gb.add_layer("t_embed1", DenseLayer(n_in=1, n_out=emb,
                                            activation="relu"), "timestep")
        gb.add_layer("t_embed2", DenseLayer(n_in=emb, n_out=ch,
                                            activation="identity"),
                     "t_embed1")
        gb.add_vertex("t_map", ReshapeVertex(new_shape=(1, 1, ch)),
                      "t_embed2")
        # broadcast-add the (B,1,1,ch) embedding onto the (B,h,w,ch) map;
        # ElementWiseVertex's output shape follows its FIRST input
        gb.add_vertex("mid_cond", ElementWiseVertex(op="add"), x, "t_map")
        x = conv_block("mid_b", "mid_cond", ch)

        # ---------------------------------------------------------- decoder
        for lvl in reversed(range(self.depth)):
            skip, skip_ch = skips[lvl]
            gb.add_layer(f"dec{lvl}_up", Upsampling2D(size=2), x)
            gb.add_vertex(f"dec{lvl}_cat", MergeVertex(), f"dec{lvl}_up",
                          skip)
            ch //= 2
            x = conv_block(f"dec{lvl}_a", f"dec{lvl}_cat", ch)
            x = conv_block(f"dec{lvl}_b", x, ch)

        # 1x1 conv noise head + DDPM simple (MSE) loss
        gb.add_layer("noise", ConvolutionLayer(n_out=c, kernel_size=(1, 1),
                                               padding="SAME",
                                               activation="identity"), x)
        gb.add_layer("loss", LossLayer(loss="mse"), "noise")
        gb.set_outputs("loss")
        gb.set_input_types(InputType.convolutional(h, w, c),
                           InputType.feed_forward(1))
        return gb.build()
