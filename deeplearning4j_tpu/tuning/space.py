"""Search-space registry: existing seams declare their tunable knobs as
typed candidate sets.

Five eras of perf work each ended with "CPU proves equivalence but cannot
rank" (docs/KERNELS.md, FUSION_TUNING.md, DISTRIBUTED.md): the repo has
accumulated deferred perf decisions with no machinery to close them. This
module is the declaration side of that machinery (TVM's schedule space,
arXiv:1802.04799 §4): each :class:`SearchSpace` names one seam, emits its
typed candidates for a concrete workload context, guards each candidate
with the seam's own validity checks (tile divides shape, VMEM fit), and
builds the measurable case (reference outputs + candidate outputs + a
timed runner) the driver in ``tuning/measure.py`` sweeps.

Registered spaces:

- ``conv2d_tiles`` / ``lstm_tiles`` — MEASURABLE. The Pallas kernel tile
  shapes (``row_tile`` / ``b_tile``, ops/kernels/) *plus* the exact path
  as candidate ``exact``: the winner record's ``impl`` field IS the
  per-(op, shape, dtype) ``kernel_impl`` decision the cuDNN paper frames
  as algorithm selection (arXiv:1410.0759 §3), subsumed by tile search.
- ``remat_policy`` — MEASURABLE (conf scope). Rides
  ``util/xla_tuning.register_policy``: every registered policy name is a
  candidate, measured on a small conv net's jitted train step; the winner
  lands under the reserved ``conf-default`` signature consulted by the
  conf builders.
- ``prefill_chunk`` — MEASURABLE (conf scope). Chunked-prefill window
  width for the paged decode engine (serving/generate.py); equivalence
  gate is generated-token identity (tolerance 0) so a chunk width that
  perturbs decode output can never win; the latency trade it ranks is
  decode-lane HOL blocking vs whole-prompt dispatch amortization.
- ``xla_flags`` — DECLARED. Candidates from
  ``xla_tuning.XLA_FLAG_CANDIDATES``; flags are process-global and abort
  XLA when unknown, so measurement belongs to the subprocess harness
  (``benchmarks/fusion_sweep.py``), not the in-process driver.
- ``bucket_sets`` — DECLARED. Candidate bucket specs for ragged
  workloads; ranking needs the workload's real length distribution
  (``benchmarks/autotune.py --space bucket_sets`` on a recorded stream).
- ``compression_hosts`` — DECLARED. Hierarchical-compression host counts
  (parallel/compression.py); unrankable without real DCN, the standing
  first-TPU-session harvest (docs/DISTRIBUTED.md honesty note).

Declared spaces still enumerate and key — the database schema covers
them, the first real-chip session measures them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.tuning.database import TuningKey


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in a search space: a dispatch choice (``impl``) plus its
    typed parameters. ``label`` is the stable human/database name."""

    label: str
    impl: str = "exact"            # "exact" | "pallas" | knob-specific
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    is_default: bool = False

    def as_dict(self) -> dict:
        return {"label": self.label, "impl": self.impl,
                "params": dict(self.params),
                "is_default": self.is_default}


class MeasureCase:
    """One concrete workload built by a space: the reference outputs, a
    per-candidate output function (for the equivalence gate), and a
    per-candidate timed runner (one call = one measured execution,
    blocked to completion)."""

    def __init__(self, *, reference: Callable[[], Any],
                 outputs: Callable[[Candidate], Any],
                 timer: Callable[[Candidate], Callable[[], None]],
                 tolerance: float):
        self.reference = reference
        self.outputs = outputs
        self.timer = timer
        self.tolerance = tolerance


class SearchSpace:
    """Base declaration. Subclasses override the class attributes and the
    four methods; ``measurable=False`` spaces only declare (enumerate +
    key) and state what measuring them ``requires``."""

    name: str = ""
    op: str = ""                   # database key op
    scope: str = "op"              # "op" (shape-keyed) | "conf"
    measurable: bool = True
    requires: str = ""             # why a declared space cannot measure here
    tolerance: float = 1e-5        # per-seam equivalence bound (abs, fp32)

    def signature(self, ctx: dict) -> str:
        raise NotImplementedError

    def dtype(self, ctx: dict) -> str:
        return str(ctx.get("dtype", "float32"))

    def key(self, ctx: dict) -> TuningKey:
        return TuningKey.for_op(self.op, self.signature(ctx),
                                self.dtype(ctx))

    def enumerate(self, ctx: dict) -> List[Candidate]:
        raise NotImplementedError

    def validate(self, cand: Candidate, ctx: dict) -> Tuple[bool, str]:
        """Validated-shape guard: (ok, reason). Invalid candidates are
        recorded as skipped, never measured."""
        return True, ""

    def neighbors(self, cand: Candidate, ctx: dict) -> List[Candidate]:
        """Adjacent candidates for greedy refinement (random search mode);
        default none."""
        return []

    def build(self, ctx: dict) -> MeasureCase:
        raise NotImplementedError(
            f"space {self.name!r} is declared, not measurable here"
            + (f" (requires {self.requires})" if self.requires else ""))

    def default_contexts(self) -> List[dict]:
        """The workload contexts ``benchmarks/autotune.py`` sweeps when
        the user names no explicit shapes — the repo's hot-path
        geometries, kept tiny on CPU (the machinery proof) and meaningful
        on the chip."""
        return []


# ---------------------------------------------------------------- registry
_REGISTRY: Dict[str, SearchSpace] = {}


def register_space(space: SearchSpace) -> SearchSpace:
    """Declare a knob space (idempotent by name; re-registering replaces,
    the ``xla_tuning.register_policy`` convention)."""
    if not space.name:
        raise ValueError("search space needs a name")
    _REGISTRY[space.name] = space
    return space


def get_space(name: str) -> SearchSpace:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown search space {name!r}; known: {space_names()}"
        ) from None


def space_names() -> List[str]:
    return sorted(_REGISTRY)


def measurable_spaces() -> List[str]:
    return sorted(n for n, s in _REGISTRY.items() if s.measurable)


# ----------------------------------------------------- conv2d tile space
class ConvTileSpace(SearchSpace):
    """Pallas conv2d forward row tiles + the exact path, per conv
    geometry — the first registrable space (ISSUE 11; parameterized in
    ops/kernels/conv.py). Context: ``{"x_shape", "w_shape", "strides",
    "padding", "dilation", "groups", "dtype"}``."""

    name = "conv2d_tiles"
    op = "conv2d"
    tolerance = 2e-4   # docs/KERNELS.md conv fwd/grad bound (fp32, CPU)

    def _geom(self, ctx):
        from deeplearning4j_tpu.ops.kernels import conv as kconv

        x_shape = tuple(ctx["x_shape"])
        w_shape = tuple(ctx["w_shape"])
        strides = tuple(ctx.get("strides", (1, 1)))
        dilation = tuple(ctx.get("dilation", (1, 1)))
        groups = int(ctx.get("groups", 1))
        pads = kconv.resolve_padding(
            ctx.get("padding", "SAME"), x_shape[1:3], w_shape[:2], strides,
            dilation)
        # kconv._out_size is the ONE output-size formula (shared with
        # fits_vmem and the kernels) — no second inline copy to drift
        oh = kconv._out_size(x_shape[1], pads[0], w_shape[0], strides[0],
                             dilation[0])
        return x_shape, w_shape, strides, dilation, groups, pads, oh

    def signature(self, ctx: dict) -> str:
        from deeplearning4j_tpu.ops.kernels import conv as kconv

        x_shape, w_shape, strides, dilation, groups, _, _ = self._geom(ctx)
        # ONE signature builder shared with the dispatch site (ops/nn.py)
        return kconv.shape_signature(x_shape, w_shape, strides,
                                     ctx.get("padding", "SAME"), dilation,
                                     groups)

    def enumerate(self, ctx: dict) -> List[Candidate]:
        from deeplearning4j_tpu.ops.kernels import conv as kconv

        _, _, _, _, _, _, oh = self._geom(ctx)
        out = [Candidate("exact", impl="exact", is_default=True)]
        for rt in kconv.valid_row_tiles(oh):
            label = "pallas:rt=whole" if rt is None else f"pallas:rt={rt}"
            out.append(Candidate(label, impl="pallas",
                                 params={"row_tile": rt}))
        return out

    def validate(self, cand: Candidate, ctx: dict) -> Tuple[bool, str]:
        from deeplearning4j_tpu.ops.kernels import conv as kconv
        import jax.numpy as jnp

        if cand.impl == "exact":
            return True, ""
        x_shape, w_shape, strides, dilation, groups, pads, oh = \
            self._geom(ctx)
        rt = cand.params.get("row_tile")
        if not kconv.valid_row_tile(oh, rt):
            return False, f"row_tile {rt} does not divide OH={oh}"
        itemsize = jnp.dtype(self.dtype(ctx)).itemsize
        if not kconv.fits_vmem(x_shape, w_shape, pads, groups, itemsize,
                               row_tile=rt, strides=strides,
                               dilation=dilation):
            return False, "VMEM budget exceeded"
        return True, ""

    def neighbors(self, cand: Candidate, ctx: dict) -> List[Candidate]:
        if cand.impl != "pallas":
            return []
        all_c = [c for c in self.enumerate(ctx) if c.impl == "pallas"]
        tiles = [c.params.get("row_tile") for c in all_c]
        try:
            i = tiles.index(cand.params.get("row_tile"))
        except ValueError:
            return []
        return [all_c[j] for j in (i - 1, i + 1) if 0 <= j < len(all_c)]

    def build(self, ctx: dict) -> MeasureCase:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from deeplearning4j_tpu.ops.kernels import conv as kconv

        x_shape, w_shape, strides, dilation, groups, pads, _ = \
            self._geom(ctx)
        dtype = jnp.dtype(self.dtype(ctx))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=x_shape), dtype)
        w = jnp.asarray(rng.normal(size=w_shape) * 0.1, dtype)
        interpret = jax.default_backend() != "tpu"

        def loss_of(conv_fn):
            def loss(x, w):
                return jnp.sum(jnp.sin(conv_fn(x, w)))
            return jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))

        def exact_conv(x, w):
            from jax import lax

            dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NHWC", "HWIO", "NHWC"))
            return lax.conv_general_dilated(
                x, w, window_strides=strides,
                padding=[tuple(p) for p in pads], rhs_dilation=dilation,
                dimension_numbers=dn,
                feature_group_count=groups).astype(x.dtype)

        def fn_for(cand: Candidate):
            if cand.impl == "exact":
                return loss_of(exact_conv)
            rt = cand.params.get("row_tile")
            return loss_of(lambda x, w: kconv.conv2d_pallas(
                x, w, strides, pads, dilation, groups, interpret, rt))

        def outputs(cand: Candidate):
            v, (gx, gw) = fn_for(cand)(x, w)
            return (v, gx, gw)

        def timer(cand: Candidate):
            f = fn_for(cand)

            def run_once():
                v, (gx, gw) = f(x, w)
                jax.block_until_ready((v, gx, gw))

            return run_once

        return MeasureCase(
            reference=lambda: outputs(Candidate("exact", impl="exact")),
            outputs=outputs, timer=timer, tolerance=self.tolerance)

    def default_contexts(self) -> List[dict]:
        import jax

        tiny = jax.default_backend() != "tpu"
        if tiny:  # machinery proof: small enough for the CPU interpreter
            return [
                {"x_shape": (2, 16, 16, 8), "w_shape": (3, 3, 8, 16),
                 "strides": (1, 1), "padding": "SAME",
                 "dilation": (1, 1), "groups": 1, "dtype": "float32"},
                {"x_shape": (2, 16, 16, 8), "w_shape": (3, 3, 8, 16),
                 "strides": (2, 2), "padding": "SAME",
                 "dilation": (1, 1), "groups": 1, "dtype": "float32"},
            ]
        # the flagship hot shapes (zoo ResNet-50 stem + res3) — the first
        # real-chip harvest measures what training actually runs
        return [
            {"x_shape": (32, 56, 56, 64), "w_shape": (3, 3, 64, 64),
             "strides": (1, 1), "padding": "SAME", "dilation": (1, 1),
             "groups": 1, "dtype": "bfloat16"},
            {"x_shape": (32, 28, 28, 128), "w_shape": (3, 3, 128, 128),
             "strides": (1, 1), "padding": "SAME", "dilation": (1, 1),
             "groups": 1, "dtype": "bfloat16"},
        ]


# ------------------------------------------------------ lstm tile space
class LstmTileSpace(SearchSpace):
    """Fused LSTM cell batch tiles + the exact scan, per (B, H, T)
    geometry (ops/kernels/lstm.py). Context: ``{"batch", "hidden",
    "timesteps", "dtype"}``."""

    name = "lstm_tiles"
    op = "lstm_cell"
    tolerance = 1e-4   # docs/KERNELS.md LSTM trajectory bound (fp32)

    def signature(self, ctx: dict) -> str:
        from deeplearning4j_tpu.ops.kernels import lstm as klstm

        # (B, H) only: the per-step kernel is T-independent, so a winner
        # measured at one sequence length serves every scan (ONE builder
        # shared with the dispatch sites in nn/recurrent.py + ops/rnn.py)
        return klstm.shape_signature(int(ctx["batch"]), int(ctx["hidden"]))

    def enumerate(self, ctx: dict) -> List[Candidate]:
        from deeplearning4j_tpu.ops.kernels import lstm as klstm

        out = [Candidate("exact", impl="exact", is_default=True)]
        for bt in klstm.valid_b_tiles(int(ctx["batch"])):
            label = "pallas:bt=whole" if bt is None else f"pallas:bt={bt}"
            out.append(Candidate(label, impl="pallas",
                                 params={"b_tile": bt}))
        return out

    def validate(self, cand: Candidate, ctx: dict) -> Tuple[bool, str]:
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops.kernels import lstm as klstm

        if cand.impl == "exact":
            return True, ""
        b, h = int(ctx["batch"]), int(ctx["hidden"])
        bt = cand.params.get("b_tile")
        if not klstm.valid_b_tile(b, bt):
            return False, f"b_tile {bt} does not divide B={b}"
        dtype = jnp.dtype(self.dtype(ctx))
        xp = jnp.zeros((b, 4 * h), dtype)
        u = jnp.zeros((h, 4 * h), dtype)
        # the same tile-aware call the dispatch sites make — validate and
        # trace-time admission can never disagree on a candidate
        if not klstm.fits_vmem(xp, u, bt):
            return False, "VMEM budget exceeded"
        return True, ""

    def neighbors(self, cand: Candidate, ctx: dict) -> List[Candidate]:
        if cand.impl != "pallas":
            return []
        all_c = [c for c in self.enumerate(ctx) if c.impl == "pallas"]
        tiles = [c.params.get("b_tile") for c in all_c]
        try:
            i = tiles.index(cand.params.get("b_tile"))
        except ValueError:
            return []
        return [all_c[j] for j in (i - 1, i + 1) if 0 <= j < len(all_c)]

    def build(self, ctx: dict) -> MeasureCase:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from deeplearning4j_tpu.ops.kernels import lstm as klstm

        b, h = int(ctx["batch"]), int(ctx["hidden"])
        t = int(ctx.get("timesteps", 8))
        dtype = jnp.dtype(self.dtype(ctx))
        rng = np.random.default_rng(0)
        xp = jnp.asarray(rng.normal(size=(t, b, 4 * h)) * 0.3, dtype)
        h0 = jnp.zeros((b, h), dtype)
        c0 = jnp.zeros((b, h), dtype)
        u = jnp.asarray(rng.normal(size=(h, 4 * h)) * 0.1, dtype)
        mode = "pallas" if jax.default_backend() == "tpu" else "interpret"

        def seq_for(cand: Candidate):
            if cand.impl == "exact":
                def exact_seq(xp, u):
                    from jax import lax

                    def body(carry, xt):
                        hp, cp = carry
                        hn, cn, _ = klstm._cell_exact(
                            xt, hp, cp, u, klstm.ORDER_IFOG)
                        hn = hn.astype(xp.dtype)
                        cn = cn.astype(xp.dtype)
                        return (hn, cn), hn

                    (hf, cf), ys = lax.scan(body, (h0, c0), xp)
                    return ys
                seq = exact_seq
            else:
                bt = cand.params.get("b_tile")

                def seq(xp, u, bt=bt):
                    ys, _ = klstm.lstm_sequence_fused(
                        xp, h0, c0, u, klstm.ORDER_IFOG, mode, bt)
                    return ys

            def loss(xp, u):
                return jnp.sum(jnp.cos(seq(xp, u)))

            return jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))

        def outputs(cand: Candidate):
            v, (gx, gu) = seq_for(cand)(xp, u)
            return (v, gx, gu)

        def timer(cand: Candidate):
            f = seq_for(cand)

            def run_once():
                out = f(xp, u)
                jax.block_until_ready(out)

            return run_once

        return MeasureCase(
            reference=lambda: outputs(Candidate("exact", impl="exact")),
            outputs=outputs, timer=timer, tolerance=self.tolerance)

    def default_contexts(self) -> List[dict]:
        import jax

        if jax.default_backend() != "tpu":
            return [{"batch": 8, "hidden": 16, "timesteps": 6,
                     "dtype": "float32"}]
        return [{"batch": 128, "hidden": 512, "timesteps": 64,
                 "dtype": "float32"}]


# --------------------------------------------------- remat policy space
class RematPolicySpace(SearchSpace):
    """Selective-remat policy for the jitted train step (conf scope,
    riding ``util/xla_tuning.register_policy`` — every registered name is
    a candidate, including user-registered ones). Measured on a small
    conv net's whole ``_fit_batch``; equivalence = k-step loss trajectory
    within the fp32 reassociation bound (remat recomputes, it must not
    change math). Winner lands under the reserved ``conf-default``
    signature consulted by the conf builders at build() time."""

    name = "remat_policy"
    op = "remat_policy"
    scope = "conf"
    tolerance = 5e-4   # fp32 trajectory wobble over k steps (FMA folds)

    def signature(self, ctx: dict) -> str:
        return "conf-default"

    def dtype(self, ctx: dict) -> str:
        return "any"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        from deeplearning4j_tpu.util import xla_tuning

        out = []
        for name in xla_tuning.policy_names():
            out.append(Candidate(
                f"policy:{name}", impl="conf",
                params={"remat_policy": None if name == "none" else name},
                is_default=(name == "none")))
        return out

    def _make_net(self, seed: int = 7):
        from deeplearning4j_tpu.nn import layers as L
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Sgd

        def build(policy):
            conf = (NeuralNetConfiguration.builder()
                    .seed(seed).updater(Sgd(0.05))
                    .list()
                    .layer(L.ConvolutionLayer(n_out=8, kernel_size=(3, 3)))
                    .stage_boundary()
                    .layer(L.ConvolutionLayer(n_out=8, kernel_size=(3, 3)))
                    .stage_boundary()
                    .layer(L.DenseLayer(n_out=16))
                    .layer(L.OutputLayer(n_out=4, loss="mcxent",
                                         activation="softmax"))
                    .set_input_type((12, 12, 3))
                    .build())
            conf.remat_policy = policy
            net = MultiLayerNetwork(conf)
            net.init()
            return net

        return build

    def build(self, ctx: dict) -> MeasureCase:
        import jax
        import numpy as np

        steps = int(ctx.get("steps", 3))
        rng = np.random.default_rng(3)
        x = np.asarray(rng.normal(size=(8, 12, 12, 3)), np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=8)]
        build = self._make_net()

        def trajectory(cand: Candidate):
            net = build(cand.params.get("remat_policy"))
            for _ in range(steps):
                net._fit_batch(x, y)
            return float(net.score_value)

        nets = {}

        def net_for(cand: Candidate):
            if cand.label not in nets:
                net = build(cand.params.get("remat_policy"))
                for _ in range(2):          # warm past the trace
                    net._fit_batch(x, y)
                float(net.score_value)
                nets[cand.label] = net
            return nets[cand.label]

        def timer(cand: Candidate):
            net = net_for(cand)

            def run_once():
                net._fit_batch(x, y)
                float(net.score_value)

            return run_once

        def outputs(cand: Candidate):
            return (trajectory(cand),)

        return MeasureCase(
            reference=lambda: outputs(
                Candidate("policy:none", impl="conf",
                          params={"remat_policy": None})),
            outputs=outputs, timer=timer, tolerance=self.tolerance)

    def default_contexts(self) -> List[dict]:
        return [{"steps": 3}]


# ------------------------------------------------- declared-only spaces
class XlaFlagsSpace(SearchSpace):
    """XLA flag candidates (util/xla_tuning.XLA_FLAG_CANDIDATES). Flags
    are process-global and unknown flags ABORT XLA at client init, so the
    in-process driver must not measure them — ``benchmarks/
    fusion_sweep.py`` is the subprocess harness; commit its winner by
    hand as a ``TuningDatabase.commit`` entry under this space's key
    (op=xla_flags, sig=conf-default — see docs/AUTOTUNE.md), the schema
    a future importer flag would also write."""

    name = "xla_flags"
    op = "xla_flags"
    scope = "conf"
    measurable = False
    requires = "subprocess isolation (benchmarks/fusion_sweep.py)"

    def signature(self, ctx: dict) -> str:
        return "conf-default"

    def dtype(self, ctx: dict) -> str:
        return "any"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        from deeplearning4j_tpu.util import xla_tuning

        out = [Candidate("flags:none", impl="conf",
                         params={"xla_flags": ""}, is_default=True)]
        for name, flag in xla_tuning.XLA_FLAG_CANDIDATES:
            out.append(Candidate(name, impl="conf",
                                 params={"xla_flags": flag}))
        return out


class BucketSetSpace(SearchSpace):
    """Shape-bucket candidate sets (data/bucketing.py). Ranking needs the
    workload's real length distribution — pad-waste vs recompile-count is
    a property of the data, not the op — so this space declares the
    candidates and the key shape; ``benchmarks/autotune.py`` measures it
    against a recorded stream when one is provided."""

    name = "bucket_sets"
    op = "bucket_sets"
    scope = "conf"
    measurable = False
    requires = "a recorded ragged-length distribution (autotune.py --help)"

    def signature(self, ctx: dict) -> str:
        dist = ctx.get("length_histogram")
        if dist:
            return "hist=" + ",".join(f"{k}:{v}"
                                      for k, v in sorted(dist.items()))
        return "conf-default"

    def dtype(self, ctx: dict) -> str:
        return "any"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        cands = [Candidate("buckets:pow2", impl="conf",
                           params={"batch_buckets": "pow2"},
                           is_default=True),
                 Candidate("buckets:8-16-32", impl="conf",
                           params={"batch_buckets": [8, 16, 32]}),
                 Candidate("buckets:16-64", impl="conf",
                           params={"batch_buckets": [16, 64]})]
        return cands


class CompressionHostsSpace(SearchSpace):
    """Hierarchical gradient-compression host counts
    (parallel/compression.py ``compression_hosts``): full-precision
    intra-host combines, encoded cross-host axis. Wire math is
    deterministic but wall-clock ranking needs real DCN — the standing
    first-TPU-session harvest (docs/DISTRIBUTED.md)."""

    name = "compression_hosts"
    op = "compression_hosts"
    scope = "conf"
    measurable = False
    requires = "real multi-host DCN (CPU cannot rank wire vs encode cost)"

    def signature(self, ctx: dict) -> str:
        return "conf-default"

    def dtype(self, ctx: dict) -> str:
        return "any"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        out = [Candidate("hosts:auto", impl="conf",
                         params={"compression_hosts": "auto"},
                         is_default=True)]
        for n in (1, 2, 4):
            out.append(Candidate(f"hosts:{n}", impl="conf",
                                 params={"compression_hosts": n}))
        return out


class PipeScheduleSpace(SearchSpace):
    """Pipeline-schedule candidates for the 3D-parallel trainer
    (parallel/pipelined.py, docs/DISTRIBUTED.md#pipeline-parallelism):
    microbatch counts (the bubble-vs-activation-memory dial — bubble
    fraction (S-1)/(n_micro+S-1) shrinks as n_micro grows while live
    activations grow) × the schedule family (the implemented GPipe
    fill-drain scan vs a 1F1B interleave candidate). On this CPU the
    bubble is arithmetic, not wall-clock — CPU proves the schedules
    EQUIVALENT (trajectory tests) and computes their bubble fractions,
    but cannot rank bubble cost against per-microbatch dispatch overhead
    or remat recompute; and 1F1B's payoff is live-activation memory that
    only a real HBM budget prices. The first chip session measures steps
    of the real pipelined fit per candidate (1f1b additionally needs the
    interleaved variant implemented behind the same gpipe_scan seam)."""

    name = "pipe_schedule"
    op = "pipe_schedule"
    scope = "conf"
    measurable = False
    requires = ("real TPU wall-clock + HBM budget (CPU proves schedule "
                "equivalence and computes bubble fractions, cannot rank "
                "bubble vs dispatch/remat cost; 1f1b candidates also need "
                "the interleaved scan variant on chip)")

    def signature(self, ctx: dict) -> str:
        s = int(ctx.get("pipe_stages", 2))
        return f"stages={s}"

    def dtype(self, ctx: dict) -> str:
        return "any"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        from deeplearning4j_tpu.parallel.pipeline import bubble_fraction

        s = int(ctx.get("pipe_stages", 2))
        out = []
        for sched in ("gpipe", "1f1b"):
            for mult in (1, 2, 4, 8):
                n_micro = s * mult
                out.append(Candidate(
                    f"{sched}:m{n_micro}", impl="conf",
                    params={"pipe_schedule": sched, "n_micro": n_micro,
                            "bubble_fraction": bubble_fraction(s, n_micro)},
                    is_default=(sched == "gpipe" and mult == 1)))
        return out


# --------------------------------------------------- prefill chunk space
class PrefillChunkSpace(SearchSpace):
    """Chunked-prefill window width for the paged decode engine
    (serving/generate.py ``prefill_chunk``, docs/SERVING.md#prefix-cache
    --chunked-prefill): how long prompts are sliced into fixed windows
    interleaved with decode batches. Small chunks bound decode-lane HOL
    blocking (Sarathi-style stall control); the whole-prompt prefill
    amortizes dispatch best. The equivalence gate is the serving
    contract itself — **generated-token identity** (tolerance 0: the
    chunked path must reproduce the whole-prompt path bit-for-bit), so a
    chunk width that perturbs decode can never win. Context:
    ``{"max_length", "prompt_len", "batch", "max_new"}``."""

    name = "prefill_chunk"
    op = "prefill_chunk"
    scope = "conf"
    tolerance = 0.0    # token IDs are integers: identity or rejection

    def signature(self, ctx: dict) -> str:
        return (f"maxlen={int(ctx.get('max_length', 64))}"
                f",prompt={int(ctx.get('prompt_len', 24))}")

    def dtype(self, ctx: dict) -> str:
        return "int32"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        max_length = int(ctx.get("max_length", 64))
        out = [Candidate("chunk:whole", impl="conf",
                         params={"prefill_chunk": None}, is_default=True)]
        w = 4
        while w < max_length:
            out.append(Candidate(f"chunk:{w}", impl="conf",
                                 params={"prefill_chunk": w}))
            w *= 2
        return out

    def validate(self, cand: Candidate, ctx: dict) -> Tuple[bool, str]:
        w = cand.params.get("prefill_chunk")
        if w is None:
            return True, ""
        max_length = int(ctx.get("max_length", 64))
        if not 1 <= int(w) <= max_length:
            return False, f"chunk {w} outside [1, max_length={max_length}]"
        return True, ""

    def neighbors(self, cand: Candidate, ctx: dict) -> List[Candidate]:
        if cand.params.get("prefill_chunk") is None:
            return []
        all_c = [c for c in self.enumerate(ctx)
                 if c.params.get("prefill_chunk") is not None]
        widths = [c.params.get("prefill_chunk") for c in all_c]
        try:
            i = widths.index(cand.params.get("prefill_chunk"))
        except ValueError:
            return []
        return [all_c[j] for j in (i - 1, i + 1) if 0 <= j < len(all_c)]

    def build(self, ctx: dict) -> MeasureCase:
        import jax.numpy as jnp
        import numpy as np

        from deeplearning4j_tpu.serving.generate import Generator
        from deeplearning4j_tpu.zoo.bert import Bert

        max_length = int(ctx.get("max_length", 64))
        prompt_len = int(ctx.get("prompt_len", 24))
        batch = int(ctx.get("batch", 2))
        max_new = int(ctx.get("max_new", 8))
        vocab = 61
        net = Bert.tiny(causal=True, task="mlm", vocab_size=vocab,
                        max_length=max_length, hidden_dropout=0.0).init()
        rng = np.random.default_rng(11)
        prompts = [[int(t) for t in rng.integers(1, vocab, prompt_len)]
                   for _ in range(batch)]

        gens: Dict[str, Generator] = {}

        def gen_for(cand: Candidate) -> Generator:
            if cand.label not in gens:
                g = Generator(net, paged=True, block_size=4,
                              batch_buckets=(batch,),
                              prefill_buckets=(max_length,),
                              prefill_chunk=cand.params.get("prefill_chunk"))
                g.generate(prompts, max_new_tokens=max_new)  # warm the trace
                gens[cand.label] = g
            return gens[cand.label]

        def outputs(cand: Candidate):
            toks = gen_for(cand).generate(prompts, max_new_tokens=max_new)
            # pad ragged eos-exits to a fixed shape for the pytree diff
            arr = np.full((batch, max_new), -1, np.int32)
            for i, row in enumerate(toks):
                arr[i, :len(row)] = row
            return (jnp.asarray(arr),)

        def timer(cand: Candidate):
            g = gen_for(cand)

            def run_once():
                g.generate(prompts, max_new_tokens=max_new)

            return run_once

        return MeasureCase(
            reference=lambda: outputs(
                Candidate("chunk:whole", impl="conf",
                          params={"prefill_chunk": None})),
            outputs=outputs, timer=timer, tolerance=self.tolerance)

    def default_contexts(self) -> List[dict]:
        import jax

        if jax.default_backend() != "tpu":
            return [{"max_length": 32, "prompt_len": 20, "batch": 2,
                     "max_new": 4}]
        return [{"max_length": 2048, "prompt_len": 1536, "batch": 8,
                 "max_new": 32}]


# ------------------------------------------------- affinity head space
class AffinityHeadSpace(SearchSpace):
    """Prompt-head length the fleet router hashes for prefix-affinity
    routing (serving/fleet.py ``affinity_head``, env
    ``DL4J_TPU_AFFINITY_HEAD`` — docs/SERVING.md#fleet). The TVM framing
    (arXiv:1802.04799): a routing policy's free parameter is a search
    dimension, not a constant. The trade-off is real on both ends —
    head:0 disables affinity (pure least-loaded: best load spread, every
    worker cold-starts every prefix), a short head collapses distinct
    system prompts onto one worker (hot-spot risk), a long head splits
    requests that DO share a radix-cache prefix across workers (hit-rate
    loss). Ranking candidates needs a live multi-worker fleet under a
    representative shared-prefix traffic trace: the objective (fleet QPS
    at a latency bound, or aggregate ``prefix_cache_hit_rate`` ×
    load-stddev penalty) only exists at fleet scope, so the space is
    declared, not measurable in this process."""

    name = "affinity_head"
    op = "affinity_head"
    scope = "conf"
    measurable = False
    requires = ("a live multi-process fleet + representative shared-"
                "prefix traffic trace (the objective — fleet QPS / "
                "aggregate prefix hit rate vs load skew — only exists "
                "at fleet scope)")

    def signature(self, ctx: dict) -> str:
        n = int(ctx.get("n_workers", 2))
        return f"workers={n}"

    def dtype(self, ctx: dict) -> str:
        return "any"

    def enumerate(self, ctx: dict) -> List[Candidate]:
        from deeplearning4j_tpu.serving.fleet import DEFAULT_AFFINITY_HEAD

        out = [Candidate("head:0", impl="conf",
                         params={"affinity_head": 0})]  # no affinity
        for head in (4, 8, 16, 32, 64):
            out.append(Candidate(
                f"head:{head}", impl="conf",
                params={"affinity_head": head},
                is_default=head == DEFAULT_AFFINITY_HEAD))
        return out


# ------------------------------------------------------- default wiring
register_space(ConvTileSpace())
register_space(LstmTileSpace())
register_space(RematPolicySpace())
register_space(XlaFlagsSpace())
register_space(BucketSetSpace())
register_space(CompressionHostsSpace())
register_space(PipeScheduleSpace())
register_space(PrefillChunkSpace())
register_space(AffinityHeadSpace())
