"""Autotuning subsystem: searchable knob spaces, an equivalence-gated
measurement driver, and a persistent tuning database consulted by
``auto`` dispatch (ROADMAP item 2, docs/AUTOTUNE.md).

Five eras of perf work each ended with "CPU proves equivalence but
cannot rank" — r6 remat policies, r14 ``kernel_impl=auto`` + Pallas tile
shapes, r8 bucket sets, r15 ``compression_hosts``, the XLA flag
candidates. This package is the TVM-style piece (arXiv:1802.04799) that
closes the loop:

- ``tuning/space.py`` — the **search-space registry**: seams declare
  their tunable knobs as typed candidate sets with per-candidate
  validity guards (tile-divides-shape, VMEM fit).
- ``tuning/measure.py`` — the **measurement driver**: grid/random search
  + greedy refinement, deterministic seeding, two-point-fit median-of-3
  timing, and an equivalence gate that refuses to admit any candidate
  whose value/grad diverges from the exact path (the r6 honesty
  convention made executable).
- ``tuning/database.py`` — the **persistent TuningDatabase**: winners
  keyed by (op, shape-signature, dtype, backend, topology) with atomic
  checkpoint-style commits and corrupt-entry skip-with-warning; armed by
  ``DL4J_TPU_TUNING_DB`` and consulted at trace time by ``ops/kernels``
  ``auto`` resolution and conf-time knob defaulting — the way the r8 AOT
  store is consulted at compile time.

One command — ``benchmarks/autotune.py`` — sweeps the registered spaces:
on CPU it proves the machinery end-to-end; on the first real-TPU session
it harvests the standing hardware debt (ROADMAP).
"""

from deeplearning4j_tpu.tuning.database import (  # noqa: F401
    TuningDatabase, TuningKey, conf_default, current_status, database_dir,
    get_database, resolve, set_database)
from deeplearning4j_tpu.tuning.measure import MeasurementDriver  # noqa: F401
from deeplearning4j_tpu.tuning.space import (  # noqa: F401
    Candidate, MeasureCase, SearchSpace, get_space, measurable_spaces,
    register_space, space_names)
