"""Persistent tuning database: measured winners keyed by
(op, shape-signature, dtype, backend, topology), consulted at trace time.

The repo's r8 AOT store answers "have we COMPILED this program before?"
across processes; this database answers "have we MEASURED this choice
before?" — the TVM-style artifact (arXiv:1802.04799 §5: the log of
schedule measurements that makes search results durable). Every entry is
the committed outcome of one equivalence-gated sweep by
``tuning/measure.py``: the winning candidate (impl + params), its
measured per-call milliseconds, the full per-candidate measurement table,
and a digest of the candidate set so a warm consumer can prove the search
space hasn't drifted since the entry was written.

Storage model (mirrors util/checkpoint.py's crash discipline):

- One JSON file per key under the database directory, named
  ``<op>--<sha16>.json`` so a human can grep the evidence.
- Commits are atomic: write ``.tmp`` then ``os.replace`` — a SIGKILL
  mid-commit can never leave a half-written entry under the real name.
- Corrupt/truncated entries are skipped with a loud warning and a
  ``tuning.corrupt_skipped_total`` counter (the ``restore_latest_good``
  convention), never a crash: a damaged database degrades to "unmeasured",
  exactly like an absent one.
- Keys embed backend ("cpu"/"tpu") and topology ("cpu:8"), so a database
  harvested on the real chip coexists with CPU harness entries and a
  topology change invalidates cleanly by missing.

Consultation (``resolve``) is what ``ops/kernels`` ``auto`` dispatch and
conf-time knob defaulting call at trace time: one in-memory-cached lookup
(positive AND negative results cached — a trace-loop miss costs a dict
probe, not a disk stat), with ``tuning.lookups_total`` /
``tuning.hits_total`` counters feeding /metrics and the /healthz tuning
section. The ``DL4J_TPU_TUNING_DB`` env knob arms the process-global
database (config.py); ``set_database`` re-points it at runtime.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1


def _tm():
    from deeplearning4j_tpu.util import telemetry

    return telemetry


def current_backend() -> str:
    """The JAX backend the measurements ran on ("cpu" | "tpu" | ...)."""
    import jax

    return jax.default_backend()


def current_topology() -> str:
    """Device-topology component of the key: ``<platform>:<n_devices>``
    (plus the device kind on real chips — a v5e entry must not answer for
    a v4 pod). Virtual CPU meshes key as ``cpu:8`` so the CI harness and
    a single-device run don't share entries."""
    import jax

    devs = jax.devices()
    plat = devs[0].platform
    kind = getattr(devs[0], "device_kind", "") or ""
    base = f"{plat}:{len(devs)}"
    if plat != "cpu" and kind:
        base += f":{kind.replace(' ', '_')}"
    return base


@dataclasses.dataclass(frozen=True)
class TuningKey:
    """One measurement context. ``sig`` is the space's canonical shape
    signature (e.g. ``x=8x32x32x4;w=3x3x4x8;s=1x1;...``); conf-scope
    knobs use the reserved ``conf-default`` signature."""

    op: str
    sig: str
    dtype: str
    backend: str
    topology: str

    @staticmethod
    def for_op(op: str, sig: str, dtype: str) -> "TuningKey":
        return TuningKey(op=op, sig=sig, dtype=str(dtype),
                         backend=current_backend(),
                         topology=current_topology())

    def digest(self) -> str:
        payload = "|".join((self.op, self.sig, self.dtype, self.backend,
                            self.topology))
        return hashlib.sha256(payload.encode()).hexdigest()

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def candidates_digest(candidates: List[dict]) -> str:
    """Stable digest of a candidate set (labels + params), so a warm
    lookup can prove the registered search space hasn't changed since the
    entry was measured — a drifted space re-measures instead of trusting
    a stale winner."""
    payload = json.dumps(
        sorted((c.get("label", ""), json.dumps(c.get("params") or {},
                                               sort_keys=True))
               for c in candidates))
    return hashlib.sha256(payload.encode()).hexdigest()


class TuningDatabase:
    """Directory of per-key JSON entries with atomic commits and an
    in-memory read cache (thread-safe; shared by trace-time dispatch)."""

    def __init__(self, directory: str):
        # no makedirs here: consultation (get_database/resolve) must be
        # read-only — a typo'd DL4J_TPU_TUNING_DB or a read-only mount
        # degrades to "unmeasured", never a crash mid-trace. The write
        # path (commit) creates the directory.
        self.dir = os.path.abspath(directory)
        self._cache: Dict[str, Optional[dict]] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- paths
    def _path(self, key: TuningKey) -> str:
        safe_op = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in key.op)
        return os.path.join(self.dir, f"{safe_op}--{key.digest()[:16]}.json")

    # ----------------------------------------------------------- lookups
    def lookup(self, key: TuningKey) -> Optional[dict]:
        """The committed entry for ``key`` (or None). Counts
        ``tuning.lookups_total`` / ``tuning.hits_total``; both outcomes
        are cached in memory, so trace-time consultation costs one dict
        probe after the first call."""
        _tm().counter("tuning.lookups_total")
        kd = key.digest()
        with self._lock:
            if kd in self._cache:
                entry = self._cache[kd]
                if entry is not None:
                    _tm().counter("tuning.hits_total")
                return entry
        entry = self._read(key)
        with self._lock:
            self._cache[kd] = entry
        if entry is not None:
            _tm().counter("tuning.hits_total")
        return entry

    def _read(self, key: TuningKey) -> Optional[dict]:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                entry = json.load(f)
            if not isinstance(entry, dict) or "winner" not in entry \
                    or entry.get("schema") != SCHEMA_VERSION \
                    or not isinstance(entry.get("key"), dict):
                raise ValueError("not a tuning entry")
        except Exception as e:
            # the restore_latest_good convention: a truncated/corrupt
            # entry (incl. a hand-written one missing "key") is a loud
            # warning and a skip, never a crash — and never silently
            # believed
            logger.warning(
                "tuning database: skipping corrupt entry %s (%s: %s)",
                path, type(e).__name__, e)
            _tm().counter("tuning.corrupt_skipped_total")
            _tm().instant("tuning.corrupt_skipped", path=path)
            return None
        if entry["key"].get("op") != key.op:
            # 16-hex-digit prefix collision across ops is practically
            # impossible, but verify rather than assume
            logger.warning("tuning database: key mismatch in %s", path)
            return None
        return entry

    # ------------------------------------------------------------ writes
    def commit(self, key: TuningKey, entry: dict) -> str:
        """Atomically persist ``entry`` for ``key`` (checkpoint-style
        tmp+rename) and refresh the in-memory cache."""
        entry = dict(entry)
        entry.setdefault("schema", SCHEMA_VERSION)
        entry["key"] = key.as_dict()
        entry.setdefault("created_unix", time.time())
        os.makedirs(self.dir, exist_ok=True)
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(entry, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        with self._lock:
            self._cache[key.digest()] = entry
        _resolve_cache.clear()   # a fresh winner must reach live dispatch
        _tm().counter("tuning.commits_total")
        return path

    def invalidate_cache(self):
        """Drop the in-memory cache (tests; a sweep writing through a
        SECOND database object pointed at the same directory)."""
        with self._lock:
            self._cache.clear()

    # ------------------------------------------------------------- stats
    def entry_paths(self) -> List[str]:
        try:
            return sorted(
                os.path.join(self.dir, f) for f in os.listdir(self.dir)
                if f.endswith(".json"))
        except OSError:
            return []

    def entries(self) -> int:
        return len(self.entry_paths())

    def all_records(self) -> List[dict]:
        """Every loadable entry (corrupt ones skipped with the warning
        counter) — the sweep report and the stats surface."""
        out = []
        for path in self.entry_paths():
            try:
                with open(path) as f:
                    entry = json.load(f)
                if not isinstance(entry, dict) or "winner" not in entry:
                    raise ValueError("not a tuning entry")
            except Exception as e:
                logger.warning(
                    "tuning database: skipping corrupt entry %s (%s: %s)",
                    path, type(e).__name__, e)
                _tm().counter("tuning.corrupt_skipped_total")
                continue
            out.append(entry)
        return out

    def stats(self) -> dict:
        """Per-op entry counts from the ``<op>--<sha16>.json`` filenames
        alone — /healthz probes this every few seconds, so it must not
        open and parse every entry (``all_records`` is for the sweep
        report, which wants the payloads anyway)."""
        by_op: Dict[str, int] = {}
        paths = self.entry_paths()
        for path in paths:
            stem = os.path.basename(path)[:-len(".json")]
            op = stem.rsplit("--", 1)[0] if "--" in stem else "?"
            by_op[op] = by_op.get(op, 0) + 1
        return {"dir": self.dir, "entries": len(paths),
                "entries_by_op": by_op}


# ------------------------------------------------------- process singleton
_UNSET = object()   # "no explicit set_database call": defer to the env knob
_db: Optional[TuningDatabase] = None
_db_dir: Any = _UNSET
_db_lock = threading.Lock()
# trace-time resolve() memo: (db identity, op, sig, dtype) -> winner|None.
# Building a TuningKey costs a sha256 + a jax.devices() walk — fine per
# sweep, too much per eager-dispatch call (bench.py
# autotune_dispatch_overhead gates the ≤1.05x budget). Backend/topology
# cannot change under a live process, so the memo is sound; commits and
# set_database() clear it.
_resolve_cache: Dict[tuple, Optional[dict]] = {}


def database_dir() -> Optional[str]:
    """The armed database directory (explicit set_database wins over the
    DL4J_TPU_TUNING_DB env knob — including ``set_database(None)``, which
    is explicit OFF, not "defer to env"), or None when tuning is off."""
    if _db_dir is not _UNSET:
        return _db_dir
    return os.environ.get("DL4J_TPU_TUNING_DB") or None


def set_database(directory: Optional[str]) -> Optional[TuningDatabase]:
    """Arm (or, with None, disarm) the process-global tuning database.
    ``None`` disarms even when DL4J_TPU_TUNING_DB is exported — test
    fixtures and benches rely on teardown actually turning tuning off."""
    global _db, _db_dir
    with _db_lock:
        _db_dir = directory
        _db = TuningDatabase(directory) if directory else None
        _resolve_cache.clear()
        return _db


def get_database() -> Optional[TuningDatabase]:
    """The process-global database per :func:`database_dir`, or None."""
    global _db
    d = database_dir()
    if not d:
        return None
    with _db_lock:
        if _db is None or _db.dir != os.path.abspath(d):
            _db = TuningDatabase(d)
            # the memo keys include id(db): clear on re-point so a
            # recycled object address can never alias stale winners
            _resolve_cache.clear()
        return _db


def resolve(op: str, sig: str, dtype) -> Optional[dict]:
    """Trace-time consultation: the winner record
    (``{"label", "impl", "params", "ms", ...}``) for the current
    backend/topology, or None when no database is armed / no entry
    exists. This is the one call ``ops/kernels`` ``auto`` resolution and
    conf-time defaulting make (docs/AUTOTUNE.md). Memoized per
    (op, sig, dtype) after the first call — the lookup counters track
    DATABASE lookups, not memo probes."""
    db = get_database()
    if db is None:
        return None
    ck = (id(db), op, sig, str(dtype))
    try:
        return _resolve_cache[ck]
    except KeyError:
        pass
    entry = db.lookup(TuningKey.for_op(op, sig, str(dtype)))
    winner = entry.get("winner") if entry is not None else None
    _resolve_cache[ck] = winner
    return winner


def conf_default(knob: str, dtype: str = "any") -> Optional[Any]:
    """Tuned default for a conf-scope knob (``remat_policy``,
    ``batch_buckets``, ``compression_hosts``): the winner's param value
    under the reserved ``conf-default`` signature, or None. Callers apply
    it only when the user/env left the knob unset — tuned evidence fills
    the deferred default, it never overrides an explicit choice."""
    winner = resolve(knob, "conf-default", dtype)
    if winner is None:
        return None
    params = winner.get("params") or {}
    return params.get(knob)


def current_status() -> dict:
    """The /healthz tuning section (sys.modules-guarded in ui_server.py,
    like elastic/serving): database dir, entry count, lookup/hit/
    measurement counters — empty dict when no database is armed."""
    db = get_database()
    if db is None:
        return {}
    snap = _tm().get_telemetry().snapshot()
    body = dict(db.stats())
    body["counters"] = {n: v for n, v in snap["counters"].items()
                        if n.startswith("tuning.")}
    return body


def collect_tuning_gauges() -> list:
    """Scrape-time collector for /metrics (registered by
    util/telemetry.install_default_collectors via a sys.modules guard)."""
    db = get_database()
    if db is None:
        return [("tuning.db_enabled", {}, 0)]
    return [("tuning.db_enabled", {}, 1),
            ("tuning.db_entries", {}, db.entries())]
