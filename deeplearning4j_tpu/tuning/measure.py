"""Equivalence-gated measurement driver: the search half of the
autotuner (docs/AUTOTUNE.md).

The loop TVM runs per schedule (arXiv:1802.04799 §5) with the r6 honesty
convention made executable: a candidate is **admitted** only after its
value AND gradients match the exact path within the space's documented
per-seam tolerance; only admitted candidates are timed; the winner is the
fastest admitted candidate, committed to the tuning database with the
full measurement table as evidence. A candidate that computes the wrong
thing can win nothing here — the gate runs before the stopwatch.

Timing discipline is the repo's bench standard (BASELINE.md since r5):
**two-point fit** — time ``n`` calls and ``2n`` calls, per-call cost =
(t2 − t1)/n, which cancels fixed dispatch/sync overhead — wrapped in
**median-of-3** with the explicit ±spread/2 noise field. Call counts are
sized so one measurement window exceeds ``min_window_s`` (scheduler noise
amortized), deterministic given the seed.

Search: ``grid`` measures every valid candidate (the default — spaces
are small by construction); ``random`` samples ``samples`` candidates
with a seeded RNG (always including the registered default, so the
winner's speedup is always relative to today's behaviour) and then
**greedy refinement** walks ``space.neighbors`` of the incumbent until no
neighbor improves — the classic coordinate-descent tail for larger
spaces.

Self-test hooks (used by ``benchmarks/autotune_smoke.py``, the CI gate
self-test, and tests/test_autotune.py): ``handicap`` adds a per-call
sleep to a labelled candidate (a planted-slow config must demonstrably
LOSE), ``corrupt`` perturbs a labelled candidate's outputs (a planted
wrong-output config must be REJECTED by the equivalence gate). Both act
on the real measurement path — the machinery proves itself end-to-end,
nothing is mocked.
"""

from __future__ import annotations

import math
import random
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.tuning import database as tdb
from deeplearning4j_tpu.tuning.space import Candidate, SearchSpace


def _tm():
    from deeplearning4j_tpu.util import telemetry

    return telemetry


def _max_abs_diff(a, b) -> float:
    """Worst elementwise |a-b| over a pytree pair, normalized per leaf by
    max(1, |ref|_inf) — the per-seam tolerance is absolute for O(1)
    magnitudes and relative for large ones."""
    import jax

    worst = 0.0
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return float("inf")
    for xa, xb in zip(la, lb):
        xa = np.asarray(xa, np.float64)
        xb = np.asarray(xb, np.float64)
        if xa.shape != xb.shape:
            return float("inf")
        if not (np.all(np.isfinite(xa)) and np.all(np.isfinite(xb))):
            return float("inf")
        scale = max(1.0, float(np.max(np.abs(xa))) if xa.size else 0.0)
        d = float(np.max(np.abs(xa - xb))) / scale if xa.size else 0.0
        worst = max(worst, d)
    return worst


class MeasurementDriver:
    """Sweeps one :class:`SearchSpace` context and commits the winner.

    Parameters: ``db`` (a :class:`tuning.database.TuningDatabase`),
    ``search`` ("grid" | "random"), ``samples`` (random-mode candidate
    budget), ``seed`` (deterministic candidate sampling), ``runs``
    (median-of-N), ``min_window_s`` (minimum timed window — the smoke
    keeps it small, real sweeps use the default)."""

    def __init__(self, db: tdb.TuningDatabase, *, search: str = "grid",
                 samples: int = 6, seed: int = 0, runs: int = 3,
                 min_window_s: float = 0.05):
        if search not in ("grid", "random"):
            raise ValueError(
                f"search must be grid|random, got {search!r}")
        self.db = db
        self.search = search
        self.samples = int(samples)
        self.seed = int(seed)
        self.runs = int(runs)
        self.min_window_s = float(min_window_s)

    # ------------------------------------------------------------ timing
    def _time_candidate(self, run_once: Callable[[], None],
                        handicap_s: float = 0.0):
        """(per_call_ms, noise_str): two-point-fit median-of-N."""
        def call():
            run_once()
            if handicap_s:
                time.sleep(handicap_s)

        call()  # warm: compile/trace outside the timed window
        t0 = time.perf_counter()
        call()
        once = max(time.perf_counter() - t0, 1e-7)
        n1 = max(1, int(math.ceil(self.min_window_s / once)))

        def window(n):
            t0 = time.perf_counter()
            for _ in range(n):
                call()
            return time.perf_counter() - t0

        slopes = []
        for _ in range(self.runs):
            t1 = window(n1)
            t2 = window(2 * n1)
            slopes.append(max((t2 - t1) / n1, 1e-9))
        slopes.sort()
        med = slopes[len(slopes) // 2]
        noise = (slopes[-1] - slopes[0]) / 2.0 / med if med else 0.0
        return med * 1e3, f"±{round(100 * noise, 1)}% ({self.runs}-sample spread/2)"

    # ------------------------------------------------------------ search
    def _select(self, space: SearchSpace, candidates: List[Candidate]):
        if self.search == "grid" or len(candidates) <= self.samples:
            return list(candidates)
        rng = random.Random(self.seed)
        defaults = [c for c in candidates if c.is_default]
        pool = [c for c in candidates if not c.is_default]
        picked = rng.sample(pool, max(0, self.samples - len(defaults)))
        return defaults + picked

    # ------------------------------------------------------------- sweep
    def sweep(self, space: SearchSpace, ctx: dict, *,
              force: bool = False,
              handicap: Optional[Dict[str, float]] = None,
              corrupt: Optional[Dict[str, Callable]] = None) -> dict:
        """Measure one (space, context): returns the committed entry plus
        a ``status`` field — ``"warm"`` (database already holds a winner
        for this key and an UNCHANGED candidate set: nothing measured,
        nothing re-proven — the cross-process contract) or
        ``"measured"``. Raises RuntimeError when no candidate survives
        the equivalence gate (a space whose every candidate is wrong is a
        bug, not a tuning result)."""
        if not space.measurable:
            raise RuntimeError(
                f"space {space.name!r} is declared, not measurable here "
                f"(requires {space.requires})")
        key = space.key(ctx)
        candidates = space.enumerate(ctx)
        digest = tdb.candidates_digest([c.as_dict() for c in candidates])
        if not force:
            entry = self.db.lookup(key)
            if entry is not None \
                    and entry.get("candidates_digest") == digest:
                out = dict(entry)
                out["status"] = "warm"
                return out

        handicap = handicap or {}
        corrupt = corrupt or {}
        case = space.build(ctx)
        reference = case.reference()
        selected = self._select(space, candidates)
        measured: List[dict] = []
        admitted: List[dict] = []
        seen_labels = set()

        def consider(cand: Candidate):
            if cand.label in seen_labels:
                return None
            seen_labels.add(cand.label)
            row = cand.as_dict()
            ok, reason = space.validate(cand, ctx)
            if not ok:
                row.update(admitted=False, reason=f"invalid: {reason}")
                measured.append(row)
                return None
            # the equivalence gate runs BEFORE the stopwatch: a candidate
            # that computes the wrong thing is never even timed
            outputs = case.outputs(cand)
            if cand.label in corrupt:
                outputs = corrupt[cand.label](outputs)
            err = _max_abs_diff(reference, outputs)
            if err > case.tolerance:
                row.update(admitted=False,
                           reason=(f"equivalence: max diff {err:.3e} > "
                                   f"tol {case.tolerance:.0e}"))
                measured.append(row)
                _tm().counter("tuning.equivalence_rejects_total")
                return None
            ms, noise = self._time_candidate(
                case.timer(cand), handicap.get(cand.label, 0.0))
            _tm().counter("tuning.measurements_total")
            row.update(admitted=True, ms=round(ms, 6), noise=noise,
                       max_diff=err)
            measured.append(row)
            admitted.append(row)
            return row

        for cand in selected:
            consider(cand)

        # greedy refinement (random mode): walk neighbors of the
        # incumbent until no neighbor improves — deterministic because
        # the incumbent choice and the neighbor order both are
        if self.search == "random" and admitted:
            improved = True
            while improved:
                improved = False
                best = min(admitted, key=lambda r: r["ms"])
                best_cand = next(c for c in candidates
                                 if c.label == best["label"])
                for nb in space.neighbors(best_cand, ctx):
                    row = consider(nb)
                    if row is not None and row["ms"] < best["ms"]:
                        improved = True

        if not admitted:
            raise RuntimeError(
                f"tuning sweep for {space.name} {key.sig}: no candidate "
                "passed the equivalence gate — refusing to commit a "
                f"winner ({len(measured)} candidates rejected)")

        winner_row = min(admitted, key=lambda r: r["ms"])
        default_rows = [r for r in admitted
                        if r.get("is_default")] or admitted
        default_ms = default_rows[0]["ms"]
        winner = {"label": winner_row["label"],
                  "impl": winner_row["impl"],
                  "params": winner_row["params"],
                  "ms": winner_row["ms"], "noise": winner_row["noise"]}
        entry = {
            "schema": tdb.SCHEMA_VERSION,
            "winner": winner,
            "default_ms": default_ms,
            "speedup_vs_default": round(default_ms / winner_row["ms"], 4)
            if winner_row["ms"] else None,
            "tolerance": case.tolerance,
            "candidates_digest": digest,
            "search": {"mode": self.search, "seed": self.seed,
                       "runs": self.runs,
                       "selected": len(seen_labels),
                       "enumerated": len(candidates)},
            "measured": measured,
        }
        self.db.commit(key, entry)
        out = dict(entry)
        out["status"] = "measured"
        out["key"] = key.as_dict()
        return out
