"""Schema + TransformProcess — org/datavec/api/transform/** parity.

Reference (path-cite, mount empty this round):
- ``schema/Schema.java`` — ordered, typed column metadata with a builder
  (addColumnString/Integer/Double/Categorical/Time...).
- ``TransformProcess.java`` — an immutable pipeline of column transforms built
  fluently (removeColumns, filter, categoricalToInteger, categoricalToOneHot,
  integerMathOp, doubleMathOp, renameColumn, reorderColumns, stringToTimeTransform,
  conditionalReplaceValueTransform...), executed locally or on Spark
  (LocalTransformExecutor / SparkTransformExecutor).

TPU-native stance: transforms are pure host-side functions record→record; the
"executor" is a list comprehension (local) — Spark-scale execution maps to the
distributed input pipeline instead, not re-implemented here. Each step also
transforms the schema, so final_schema() gives the post-pipeline column map —
the invariant the reference tests (TransformProcessTest) assert.
"""

from __future__ import annotations

import math
import time as _time
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Sequence


class ColumnType(Enum):
    String = "String"
    Integer = "Integer"
    Long = "Long"
    Double = "Double"
    Float = "Float"
    Categorical = "Categorical"
    Time = "Time"
    Bytes = "Bytes"
    NDArray = "NDArray"


class Schema:
    """Ordered typed columns (Schema.java parity)."""

    def __init__(self, columns: Optional[List[tuple]] = None):
        # columns: list of (name, ColumnType, meta) — meta holds categorical
        # state lists etc.
        self.columns: List[tuple] = list(columns or [])

    # -- builder ------------------------------------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[tuple] = []

        def add_column_string(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.String, None))
            return self

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Integer, None))
            return self

        def add_column_long(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Long, None))
            return self

        def add_column_double(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Double, None))
            return self

        def add_column_float(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Float, None))
            return self

        def add_column_categorical(self, name, *states):
            self._cols.append((name, ColumnType.Categorical, list(states)))
            return self

        def add_column_time(self, name, timezone="UTC"):
            self._cols.append((name, ColumnType.Time, timezone))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    # -- accessors ----------------------------------------------------------
    def column_names(self) -> List[str]:
        return [c[0] for c in self.columns]

    def column_index(self, name: str) -> int:
        return self.column_names().index(name)

    def column_type(self, name: str) -> ColumnType:
        return self.columns[self.column_index(name)][1]

    def meta(self, name: str):
        return self.columns[self.column_index(name)][2]

    def num_columns(self) -> int:
        return len(self.columns)

    def __repr__(self):
        cols = ", ".join(f"{n}:{t.value}" for n, t, _ in self.columns)
        return f"Schema[{cols}]"


class _Step:
    """One transform: fn(record, schema) -> record|None, plus schema_fn."""

    def __init__(self, name, record_fn, schema_fn):
        self.name = name
        self.record_fn = record_fn
        self.schema_fn = schema_fn


class TransformProcess:
    """Immutable transform pipeline (TransformProcess.java parity)."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps
        self._schemas = [initial_schema]
        for s in steps:
            self._schemas.append(s.schema_fn(self._schemas[-1]))

    def final_schema(self) -> Schema:
        return self._schemas[-1]

    # -- execution ----------------------------------------------------------
    def execute_record(self, record: Sequence[Any]):
        rec = list(record)
        for s, schema in zip(self.steps, self._schemas):
            rec = s.record_fn(rec, schema)
            if rec is None:
                return None
        return rec

    def execute(self, records: Sequence[Sequence[Any]]) -> List[List[Any]]:
        """LocalTransformExecutor.execute parity."""
        out = []
        for r in records:
            t = self.execute_record(r)
            if t is not None:
                out.append(t)
        return out

    # -- builder ------------------------------------------------------------
    class Builder:
        def __init__(self, initial_schema: Schema):
            self.schema = initial_schema
            self.steps: List[_Step] = []

        def _add(self, name, record_fn, schema_fn):
            self.steps.append(_Step(name, record_fn, schema_fn))
            return self

        def remove_columns(self, *names):
            def rec(r, schema):
                keep = [i for i, n in enumerate(schema.column_names()) if n not in names]
                return [r[i] for i in keep]

            def sch(schema):
                return Schema([c for c in schema.columns if c[0] not in names])

            return self._add(f"remove{names}", rec, sch)

        def remove_all_columns_except_for(self, *names):
            def rec(r, schema):
                return [r[i] for i, n in enumerate(schema.column_names()) if n in names]

            def sch(schema):
                return Schema([c for c in schema.columns if c[0] in names])

            return self._add(f"keep{names}", rec, sch)

        def rename_column(self, old, new):
            def sch(schema):
                return Schema([
                    (new if n == old else n, t, m) for n, t, m in schema.columns
                ])

            return self._add(f"rename {old}->{new}", lambda r, s: r, sch)

        def reorder_columns(self, *names):
            def rec(r, schema):
                idx = [schema.column_index(n) for n in names]
                rest = [i for i in range(len(r)) if i not in idx]
                return [r[i] for i in idx + rest]

            def sch(schema):
                named = [schema.columns[schema.column_index(n)] for n in names]
                rest = [c for c in schema.columns if c[0] not in names]
                return Schema(named + rest)

            return self._add("reorder", rec, sch)

        def filter(self, predicate: Callable[[list, Schema], bool]):
            """Drop records where predicate is True (FilterOp parity)."""

            def rec(r, schema):
                return None if predicate(r, schema) else r

            return self._add("filter", rec, lambda s: s)

        def categorical_to_integer(self, *names):
            def rec(r, schema):
                r = list(r)
                for n in names:
                    i = schema.column_index(n)
                    states = schema.meta(n)
                    r[i] = states.index(r[i])
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Integer if n in names else t,
                     None if n in names else m)
                    for n, t, m in schema.columns
                ])

            return self._add("cat2int", rec, sch)

        def categorical_to_one_hot(self, *names):
            def rec(r, schema):
                out = []
                for i, (n, t, m) in enumerate(schema.columns):
                    if n in names:
                        states = m
                        onehot = [0] * len(states)
                        onehot[states.index(r[i])] = 1
                        out.extend(onehot)
                    else:
                        out.append(r[i])
                return out

            def sch(schema):
                cols = []
                for n, t, m in schema.columns:
                    if n in names:
                        cols.extend(
                            (f"{n}[{s}]", ColumnType.Integer, None) for s in m
                        )
                    else:
                        cols.append((n, t, m))
                return Schema(cols)

            return self._add("cat2onehot", rec, sch)

        def string_to_categorical(self, name, states):
            def sch(schema):
                return Schema([
                    (n, ColumnType.Categorical if n == name else t,
                     list(states) if n == name else m)
                    for n, t, m in schema.columns
                ])

            return self._add("str2cat", lambda r, s: r, sch)

        def convert_to_double(self, *names):
            def rec(r, schema):
                r = list(r)
                for n in names:
                    i = schema.column_index(n)
                    r[i] = float(r[i])
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Double if n in names else t, m)
                    for n, t, m in schema.columns
                ])

            return self._add("toDouble", rec, sch)

        def convert_to_integer(self, *names):
            def rec(r, schema):
                r = list(r)
                for n in names:
                    i = schema.column_index(n)
                    r[i] = int(float(r[i]))
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Integer if n in names else t, m)
                    for n, t, m in schema.columns
                ])

            return self._add("toInt", rec, sch)

        def double_math_op(self, name, op: str, value: float):
            """op ∈ add/subtract/multiply/divide/modulus/power (MathOp parity)."""
            fns = {
                "add": lambda v: v + value,
                "subtract": lambda v: v - value,
                "multiply": lambda v: v * value,
                "divide": lambda v: v / value,
                "modulus": lambda v: math.fmod(v, value),
                "power": lambda v: v ** value,
            }

            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                r[i] = fns[op](float(r[i]))
                return r

            return self._add(f"math {op}", rec, lambda s: s)

        def double_column_transform(self, name, fn: Callable[[float], float]):
            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                r[i] = fn(float(r[i]))
                return r

            return self._add("doubleTransform", rec, lambda s: s)

        def conditional_replace_value_transform(self, name, new_value,
                                                condition: Callable[[Any], bool]):
            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                if condition(r[i]):
                    r[i] = new_value
                return r

            return self._add("condReplace", rec, lambda s: s)

        def string_to_time(self, name, fmt: str = "%Y-%m-%d %H:%M:%S"):
            """Parse to UTC epoch millis (StringToTimeTransform parity —
            timegm, not mktime: results must not depend on host timezone)."""
            import calendar

            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                t = _time.strptime(r[i], fmt)
                r[i] = int(calendar.timegm(t) * 1000)
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Time if n == name else t, m)
                    for n, t, m in schema.columns
                ])

            return self._add("str2time", rec, sch)

        def append_string_column_transform(self, name, to_append: str):
            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                r[i] = str(r[i]) + to_append
                return r

            return self._add("appendStr", rec, lambda s: s)

        def replace_missing_value_with(self, name, value):
            """ReplaceInvalidWithIntegerTransform/fillna parity: None or
            empty-string cells become ``value``."""

            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                v = r[i]
                missing = v is None or v == ""
                if not missing and isinstance(v, float):
                    missing = math.isnan(v)  # same rule filter_invalid uses
                if missing:
                    r[i] = value
                return r

            return self._add(f"fillna {name}", rec, lambda s: s)

        def filter_invalid_values(self, *names):
            """Drop records whose named numeric cells are None/''/NaN
            (FilterInvalidValues parity)."""

            def bad(v):
                if v is None or v == "":
                    return True
                try:
                    return math.isnan(float(v))
                except (TypeError, ValueError):
                    return True

            def rec(r, schema):
                return None if any(
                    bad(r[schema.column_index(n)]) for n in names) else r

            return self._add(f"filter_invalid{names}", rec, lambda s: s)

        def add_constant_column(self, name, col_type: "ColumnType", value):
            def rec(r, schema):
                return list(r) + [value]

            def sch(schema):
                return Schema(schema.columns + [(name, col_type, None)])

            return self._add(f"const {name}", rec, sch)

        def duplicate_column(self, name, new_name):
            def rec(r, schema):
                return list(r) + [r[schema.column_index(name)]]

            def sch(schema):
                n, t, m = schema.columns[schema.column_index(name)]
                return Schema(schema.columns + [(new_name, t, m)])

            return self._add(f"dup {name}", rec, sch)

        def integer_to_categorical(self, name, states):
            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                v = int(r[i])
                if not 0 <= v < len(states):
                    raise ValueError(
                        f"integer_to_categorical: value {v} out of range "
                        f"for {len(states)} states in column {name!r}")
                r[i] = states[v]
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Categorical if n == name else t,
                     list(states) if n == name else m)
                    for n, t, m in schema.columns
                ])

            return self._add(f"int2cat {name}", rec, sch)

        def integer_math_op(self, name, op: str, value: int):
            """IntegerMathOpTransform parity: Add/Subtract/Multiply/Divide/
            Modulus/ScalarMin/ScalarMax. Divide/Modulus follow the
            reference's JAVA semantics — truncation toward zero, remainder
            keeping the dividend's sign — not Python floor division."""
            def trunc_div(v):
                # exact integer truncation toward zero (no float64 detour —
                # Long-range values stay exact)
                q = abs(v) // abs(value)
                return q if (v < 0) == (value < 0) else -q

            fns = {"Add": lambda v: v + value,
                   "Subtract": lambda v: v - value,
                   "Multiply": lambda v: v * value,
                   "Divide": trunc_div,
                   "Modulus": lambda v: v - trunc_div(v) * value,
                   "ScalarMin": lambda v: min(v, value),
                   "ScalarMax": lambda v: max(v, value)}
            fn = fns[op]

            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                r[i] = fn(int(r[i]))
                return r

            return self._add(f"imath {op} {name}", rec, lambda s: s)

        def change_case_string_transform(self, name, upper=False):
            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                r[i] = str(r[i]).upper() if upper else str(r[i]).lower()
                return r

            return self._add(f"case {name}", rec, lambda s: s)

        def replace_string_transform(self, name, pattern, replacement,
                                     regex=False):
            """ReplaceStringTransform / RegexReplace parity."""
            import re as _re

            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                r[i] = (_re.sub(pattern, replacement, str(r[i])) if regex
                        else str(r[i]).replace(pattern, replacement))
                return r

            return self._add(f"replace {name}", rec, lambda s: s)

        def map_string(self, name, fn: Callable[[str], str]):
            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                r[i] = fn(str(r[i]))
                return r

            return self._add(f"map_string {name}", rec, lambda s: s)

        def normalize(self, name, min_value: float, max_value: float):
            """Min-max scale to [0, 1] using the given statistics (DataVec's
            Normalize.MinMax over a DataAnalysis)."""
            span = max(max_value - min_value, 1e-12)

            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                r[i] = (float(r[i]) - min_value) / span
                return r

            return self._add(f"minmax {name}", rec, lambda s: s)

        def standardize(self, name, mean: float, stdev: float):
            """Z-score using given statistics (Normalize.Standardize)."""
            sd = max(stdev, 1e-12)

            def rec(r, schema):
                i = schema.column_index(name)
                r = list(r)
                r[i] = (float(r[i]) - mean) / sd
                return r

            return self._add(f"standardize {name}", rec, lambda s: s)

        def derive_column_from_time(self, name, field: str,
                                    new_name: "Optional[str]" = None):
            """DeriveColumnsFromTimeTransform parity: extract hour_of_day /
            day_of_week / day_of_month / month / year from a Time column
            (epoch milliseconds, UTC)."""
            import datetime as _dt

            getters = {
                "hour_of_day": lambda d: d.hour,
                # isoweekday: Monday=1..Sunday=7 (Joda/DataVec convention)
                "day_of_week": lambda d: d.isoweekday(),
                "day_of_month": lambda d: d.day,
                "month": lambda d: d.month,
                "year": lambda d: d.year,
            }
            get = getters[field]
            out = new_name or f"{name}_{field}"

            def rec(r, schema):
                i = schema.column_index(name)
                d = _dt.datetime.fromtimestamp(int(r[i]) / 1000.0,
                                               _dt.timezone.utc)
                return list(r) + [get(d)]

            def sch(schema):
                return Schema(schema.columns + [(out, ColumnType.Integer,
                                                 None)])

            return self._add(f"time {field} {name}", rec, sch)

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema, self.steps)

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)


class Join:
    """Record-collection join (org/datavec/api/transform/join/Join.java
    parity: Inner / LeftOuter / RightOuter / FullOuter on key columns; the
    reference executes these on Spark — here locally over record lists).

        join = (Join.Builder("inner")
                .set_join_columns("id")
                .set_schemas(left_schema, right_schema).build())
        rows = join.execute(left_records, right_records)
    """

    TYPES = ("inner", "leftouter", "rightouter", "fullouter")

    def __init__(self, join_type: str, keys: List[str],
                 left_schema: Schema, right_schema: Schema):
        jt = join_type.lower().replace("_", "")
        if jt not in self.TYPES:
            raise ValueError(f"join_type must be one of {self.TYPES}")
        self.join_type = jt
        self.keys = list(keys)
        self.left_schema = left_schema
        self.right_schema = right_schema
        self._l_idx = [left_schema.column_names().index(k) for k in self.keys]
        self._r_idx = [right_schema.column_names().index(k) for k in self.keys]
        # output: all left columns + right columns minus the keys
        self._r_keep = [i for i, n in enumerate(right_schema.column_names())
                        if n not in self.keys]

    class Builder:
        def __init__(self, join_type: str = "inner"):
            self._type = join_type
            self._keys: List[str] = []
            self._left = self._right = None

        def set_join_columns(self, *names: str):
            self._keys = list(names)
            return self

        def set_schemas(self, left: Schema, right: Schema):
            self._left, self._right = left, right
            return self

        def build(self) -> "Join":
            return Join(self._type, self._keys, self._left, self._right)

    def output_schema(self) -> Schema:
        cols = list(self.left_schema.columns)
        cols += [self.right_schema.columns[i] for i in self._r_keep]
        return Schema(cols)

    def _null_row(self, schema, keep=None):
        n = len(schema.columns) if keep is None else len(keep)
        return [None] * n

    def execute(self, left_records, right_records) -> List[list]:
        right_by_key: dict = {}
        for r in right_records:
            right_by_key.setdefault(
                tuple(r[i] for i in self._r_idx), []).append(r)
        out = []
        matched_right = set()
        for l in left_records:
            k = tuple(l[i] for i in self._l_idx)
            matches = right_by_key.get(k, [])
            if matches:
                matched_right.add(k)
                for r in matches:
                    out.append(list(l) + [r[i] for i in self._r_keep])
            elif self.join_type in ("leftouter", "fullouter"):
                out.append(list(l) + self._null_row(self.right_schema,
                                                    self._r_keep))
        if self.join_type in ("rightouter", "fullouter"):
            ln = len(self.left_schema.columns)
            for k, rs in right_by_key.items():
                if k in matched_right:
                    continue
                for r in rs:
                    row = [None] * ln
                    for ki, li in zip(k, self._l_idx):
                        row[li] = ki  # key values survive on the left side
                    out.append(row + [r[i] for i in self._r_keep])
        return out


class Reducer:
    """Group-by aggregation (org/datavec/api/transform/reduce/Reducer.java,
    path-cite): records sharing the key column values collapse to one row
    per group, non-key columns reduced by the configured op.

    Ops: sum, mean, min, max, count, stdev, first, last, takefirst (alias
    of first, as upstream).
    """

    _OPS = {
        "sum": lambda vs: sum(float(v) for v in vs),
        "mean": lambda vs: sum(float(v) for v in vs) / len(vs),
        "min": lambda vs: min(float(v) for v in vs),
        "max": lambda vs: max(float(v) for v in vs),
        "count": lambda vs: len(vs),
        "stdev": lambda vs: _stdev([float(v) for v in vs]),
        "first": lambda vs: vs[0],
        "takefirst": lambda vs: vs[0],
        "last": lambda vs: vs[-1],
    }
    _NUMERIC = {"sum", "mean", "min", "max", "stdev"}

    def __init__(self, schema: Schema, keys: List[str],
                 default_op: str = "takefirst",
                 column_ops: "Optional[dict]" = None):
        self.schema = schema
        self.keys = list(keys)
        self.default_op = default_op.lower()
        self.column_ops = {k: v.lower() for k, v in (column_ops or {}).items()}
        for o in [self.default_op, *self.column_ops.values()]:
            if o not in self._OPS:
                raise ValueError(f"unknown reduce op {o!r}")

    class Builder:
        def __init__(self, schema: Schema, *keys: str):
            self._schema = schema
            self._keys = list(keys)
            self._default = "takefirst"
            self._ops: dict = {}

        def default_op(self, op: str):
            self._default = op
            return self

        def op(self, op: str, *names: str):
            for n in names:
                self._ops[n] = op
            return self

        # upstream spelling helpers
        def sum_columns(self, *names):
            return self.op("sum", *names)

        def mean_columns(self, *names):
            return self.op("mean", *names)

        def min_columns(self, *names):
            return self.op("min", *names)

        def max_columns(self, *names):
            return self.op("max", *names)

        def count_columns(self, *names):
            return self.op("count", *names)

        def stdev_columns(self, *names):
            return self.op("stdev", *names)

        def build(self) -> "Reducer":
            return Reducer(self._schema, self._keys, self._default,
                           self._ops)

    def output_schema(self) -> Schema:
        cols = []
        for n, t, m in self.schema.columns:
            if n in self.keys:
                cols.append((n, t, m))
                continue
            o = self.column_ops.get(n, self.default_op)
            if o == "count":
                cols.append((f"count({n})", ColumnType.Long, None))
            elif o in self._NUMERIC:
                cols.append((f"{o}({n})", ColumnType.Double, None))
            else:
                cols.append((n, t, m))
        return Schema(cols)

    def execute(self, records: Sequence[Sequence[Any]]) -> List[list]:
        names = self.schema.column_names()
        kidx = [self.schema.column_index(k) for k in self.keys]
        groups: dict = {}
        order = []
        for r in records:
            key = tuple(r[i] for i in kidx)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(r)
        out = []
        for key in order:
            rows = groups[key]
            row = []
            for i, n in enumerate(names):
                if n in self.keys:
                    row.append(rows[0][i])
                    continue
                o = self.column_ops.get(n, self.default_op)
                row.append(self._OPS[o]([r[i] for r in rows]))
            out.append(row)
        return out


def _stdev(vals):
    if len(vals) < 2:
        return 0.0
    m = sum(vals) / len(vals)
    return (sum((v - m) ** 2 for v in vals) / (len(vals) - 1)) ** 0.5
