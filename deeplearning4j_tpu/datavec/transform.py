"""Schema + TransformProcess — org/datavec/api/transform/** parity.

Reference (path-cite, mount empty this round):
- ``schema/Schema.java`` — ordered, typed column metadata with a builder
  (addColumnString/Integer/Double/Categorical/Time...).
- ``TransformProcess.java`` — an immutable pipeline of column transforms built
  fluently (removeColumns, filter, categoricalToInteger, categoricalToOneHot,
  integerMathOp, doubleMathOp, renameColumn, reorderColumns, stringToTimeTransform,
  conditionalReplaceValueTransform...), executed locally or on Spark
  (LocalTransformExecutor / SparkTransformExecutor).

TPU-native stance: transforms are pure host-side functions record→record; the
"executor" is a list comprehension (local) — Spark-scale execution maps to the
distributed input pipeline instead, not re-implemented here. Each step also
transforms the schema, so final_schema() gives the post-pipeline column map —
the invariant the reference tests (TransformProcessTest) assert.
"""

from __future__ import annotations

import math
import time as _time
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Sequence


class ColumnType(Enum):
    String = "String"
    Integer = "Integer"
    Long = "Long"
    Double = "Double"
    Float = "Float"
    Categorical = "Categorical"
    Time = "Time"
    Bytes = "Bytes"
    NDArray = "NDArray"


class Schema:
    """Ordered typed columns (Schema.java parity)."""

    def __init__(self, columns: Optional[List[tuple]] = None):
        # columns: list of (name, ColumnType, meta) — meta holds categorical
        # state lists etc.
        self.columns: List[tuple] = list(columns or [])

    # -- builder ------------------------------------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[tuple] = []

        def add_column_string(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.String, None))
            return self

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Integer, None))
            return self

        def add_column_long(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Long, None))
            return self

        def add_column_double(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Double, None))
            return self

        def add_column_float(self, *names):
            for n in names:
                self._cols.append((n, ColumnType.Float, None))
            return self

        def add_column_categorical(self, name, *states):
            self._cols.append((name, ColumnType.Categorical, list(states)))
            return self

        def add_column_time(self, name, timezone="UTC"):
            self._cols.append((name, ColumnType.Time, timezone))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    # -- accessors ----------------------------------------------------------
    def column_names(self) -> List[str]:
        return [c[0] for c in self.columns]

    def column_index(self, name: str) -> int:
        return self.column_names().index(name)

    def column_type(self, name: str) -> ColumnType:
        return self.columns[self.column_index(name)][1]

    def meta(self, name: str):
        return self.columns[self.column_index(name)][2]

    def num_columns(self) -> int:
        return len(self.columns)

    def __repr__(self):
        cols = ", ".join(f"{n}:{t.value}" for n, t, _ in self.columns)
        return f"Schema[{cols}]"


class _Step:
    """One transform: fn(record, schema) -> record|None, plus schema_fn."""

    def __init__(self, name, record_fn, schema_fn):
        self.name = name
        self.record_fn = record_fn
        self.schema_fn = schema_fn


class TransformProcess:
    """Immutable transform pipeline (TransformProcess.java parity)."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps
        self._schemas = [initial_schema]
        for s in steps:
            self._schemas.append(s.schema_fn(self._schemas[-1]))

    def final_schema(self) -> Schema:
        return self._schemas[-1]

    # -- execution ----------------------------------------------------------
    def execute_record(self, record: Sequence[Any]):
        rec = list(record)
        for s, schema in zip(self.steps, self._schemas):
            rec = s.record_fn(rec, schema)
            if rec is None:
                return None
        return rec

    def execute(self, records: Sequence[Sequence[Any]]) -> List[List[Any]]:
        """LocalTransformExecutor.execute parity."""
        out = []
        for r in records:
            t = self.execute_record(r)
            if t is not None:
                out.append(t)
        return out

    # -- builder ------------------------------------------------------------
    class Builder:
        def __init__(self, initial_schema: Schema):
            self.schema = initial_schema
            self.steps: List[_Step] = []

        def _add(self, name, record_fn, schema_fn):
            self.steps.append(_Step(name, record_fn, schema_fn))
            return self

        def remove_columns(self, *names):
            def rec(r, schema):
                keep = [i for i, n in enumerate(schema.column_names()) if n not in names]
                return [r[i] for i in keep]

            def sch(schema):
                return Schema([c for c in schema.columns if c[0] not in names])

            return self._add(f"remove{names}", rec, sch)

        def remove_all_columns_except_for(self, *names):
            def rec(r, schema):
                return [r[i] for i, n in enumerate(schema.column_names()) if n in names]

            def sch(schema):
                return Schema([c for c in schema.columns if c[0] in names])

            return self._add(f"keep{names}", rec, sch)

        def rename_column(self, old, new):
            def sch(schema):
                return Schema([
                    (new if n == old else n, t, m) for n, t, m in schema.columns
                ])

            return self._add(f"rename {old}->{new}", lambda r, s: r, sch)

        def reorder_columns(self, *names):
            def rec(r, schema):
                idx = [schema.column_index(n) for n in names]
                rest = [i for i in range(len(r)) if i not in idx]
                return [r[i] for i in idx + rest]

            def sch(schema):
                named = [schema.columns[schema.column_index(n)] for n in names]
                rest = [c for c in schema.columns if c[0] not in names]
                return Schema(named + rest)

            return self._add("reorder", rec, sch)

        def filter(self, predicate: Callable[[list, Schema], bool]):
            """Drop records where predicate is True (FilterOp parity)."""

            def rec(r, schema):
                return None if predicate(r, schema) else r

            return self._add("filter", rec, lambda s: s)

        def categorical_to_integer(self, *names):
            def rec(r, schema):
                r = list(r)
                for n in names:
                    i = schema.column_index(n)
                    states = schema.meta(n)
                    r[i] = states.index(r[i])
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Integer if n in names else t,
                     None if n in names else m)
                    for n, t, m in schema.columns
                ])

            return self._add("cat2int", rec, sch)

        def categorical_to_one_hot(self, *names):
            def rec(r, schema):
                out = []
                for i, (n, t, m) in enumerate(schema.columns):
                    if n in names:
                        states = m
                        onehot = [0] * len(states)
                        onehot[states.index(r[i])] = 1
                        out.extend(onehot)
                    else:
                        out.append(r[i])
                return out

            def sch(schema):
                cols = []
                for n, t, m in schema.columns:
                    if n in names:
                        cols.extend(
                            (f"{n}[{s}]", ColumnType.Integer, None) for s in m
                        )
                    else:
                        cols.append((n, t, m))
                return Schema(cols)

            return self._add("cat2onehot", rec, sch)

        def string_to_categorical(self, name, states):
            def sch(schema):
                return Schema([
                    (n, ColumnType.Categorical if n == name else t,
                     list(states) if n == name else m)
                    for n, t, m in schema.columns
                ])

            return self._add("str2cat", lambda r, s: r, sch)

        def convert_to_double(self, *names):
            def rec(r, schema):
                r = list(r)
                for n in names:
                    i = schema.column_index(n)
                    r[i] = float(r[i])
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Double if n in names else t, m)
                    for n, t, m in schema.columns
                ])

            return self._add("toDouble", rec, sch)

        def convert_to_integer(self, *names):
            def rec(r, schema):
                r = list(r)
                for n in names:
                    i = schema.column_index(n)
                    r[i] = int(float(r[i]))
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Integer if n in names else t, m)
                    for n, t, m in schema.columns
                ])

            return self._add("toInt", rec, sch)

        def double_math_op(self, name, op: str, value: float):
            """op ∈ add/subtract/multiply/divide/modulus/power (MathOp parity)."""
            fns = {
                "add": lambda v: v + value,
                "subtract": lambda v: v - value,
                "multiply": lambda v: v * value,
                "divide": lambda v: v / value,
                "modulus": lambda v: math.fmod(v, value),
                "power": lambda v: v ** value,
            }

            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                r[i] = fns[op](float(r[i]))
                return r

            return self._add(f"math {op}", rec, lambda s: s)

        def double_column_transform(self, name, fn: Callable[[float], float]):
            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                r[i] = fn(float(r[i]))
                return r

            return self._add("doubleTransform", rec, lambda s: s)

        def conditional_replace_value_transform(self, name, new_value,
                                                condition: Callable[[Any], bool]):
            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                if condition(r[i]):
                    r[i] = new_value
                return r

            return self._add("condReplace", rec, lambda s: s)

        def string_to_time(self, name, fmt: str = "%Y-%m-%d %H:%M:%S"):
            """Parse to UTC epoch millis (StringToTimeTransform parity —
            timegm, not mktime: results must not depend on host timezone)."""
            import calendar

            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                t = _time.strptime(r[i], fmt)
                r[i] = int(calendar.timegm(t) * 1000)
                return r

            def sch(schema):
                return Schema([
                    (n, ColumnType.Time if n == name else t, m)
                    for n, t, m in schema.columns
                ])

            return self._add("str2time", rec, sch)

        def append_string_column_transform(self, name, to_append: str):
            def rec(r, schema):
                r = list(r)
                i = schema.column_index(name)
                r[i] = str(r[i]) + to_append
                return r

            return self._add("appendStr", rec, lambda s: s)

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema, self.steps)

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)


class Join:
    """Record-collection join (org/datavec/api/transform/join/Join.java
    parity: Inner / LeftOuter / RightOuter / FullOuter on key columns; the
    reference executes these on Spark — here locally over record lists).

        join = (Join.Builder("inner")
                .set_join_columns("id")
                .set_schemas(left_schema, right_schema).build())
        rows = join.execute(left_records, right_records)
    """

    TYPES = ("inner", "leftouter", "rightouter", "fullouter")

    def __init__(self, join_type: str, keys: List[str],
                 left_schema: Schema, right_schema: Schema):
        jt = join_type.lower().replace("_", "")
        if jt not in self.TYPES:
            raise ValueError(f"join_type must be one of {self.TYPES}")
        self.join_type = jt
        self.keys = list(keys)
        self.left_schema = left_schema
        self.right_schema = right_schema
        self._l_idx = [left_schema.column_names().index(k) for k in self.keys]
        self._r_idx = [right_schema.column_names().index(k) for k in self.keys]
        # output: all left columns + right columns minus the keys
        self._r_keep = [i for i, n in enumerate(right_schema.column_names())
                        if n not in self.keys]

    class Builder:
        def __init__(self, join_type: str = "inner"):
            self._type = join_type
            self._keys: List[str] = []
            self._left = self._right = None

        def set_join_columns(self, *names: str):
            self._keys = list(names)
            return self

        def set_schemas(self, left: Schema, right: Schema):
            self._left, self._right = left, right
            return self

        def build(self) -> "Join":
            return Join(self._type, self._keys, self._left, self._right)

    def output_schema(self) -> Schema:
        cols = list(self.left_schema.columns)
        cols += [self.right_schema.columns[i] for i in self._r_keep]
        return Schema(cols)

    def _null_row(self, schema, keep=None):
        n = len(schema.columns) if keep is None else len(keep)
        return [None] * n

    def execute(self, left_records, right_records) -> List[list]:
        right_by_key: dict = {}
        for r in right_records:
            right_by_key.setdefault(
                tuple(r[i] for i in self._r_idx), []).append(r)
        out = []
        matched_right = set()
        for l in left_records:
            k = tuple(l[i] for i in self._l_idx)
            matches = right_by_key.get(k, [])
            if matches:
                matched_right.add(k)
                for r in matches:
                    out.append(list(l) + [r[i] for i in self._r_keep])
            elif self.join_type in ("leftouter", "fullouter"):
                out.append(list(l) + self._null_row(self.right_schema,
                                                    self._r_keep))
        if self.join_type in ("rightouter", "fullouter"):
            ln = len(self.left_schema.columns)
            for k, rs in right_by_key.items():
                if k in matched_right:
                    continue
                for r in rs:
                    row = [None] * ln
                    for ki, li in zip(k, self._l_idx):
                        row[li] = ki  # key values survive on the left side
                    out.append(row + [r[i] for i in self._r_keep])
        return out
