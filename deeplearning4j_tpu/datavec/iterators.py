"""RecordReader → DataSet bridge iterators.

Reference: org/deeplearning4j/datasets/datavec/RecordReaderDataSetIterator.java
and SequenceRecordReaderDataSetIterator.java (deeplearning4j-core; SURVEY.md
§2.2 J11) — path-cite, mount empty this round.

Semantics mirrored: ``label_index`` picks the label column; ``num_classes``
one-hots classification labels; regression=True keeps raw label values;
image records ([HWC array, label]) batch into NHWC tensors. Sequence variant:
pads ragged sequences and emits (B,T) masks with the reference's
AlignmentMode semantics (align_start default, align_end opt-in) — feeding the
network mask plumbing.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


class RecordReaderDataSetIterator(DataSetIterator):
    def __init__(self, reader, batch_size: int, label_index: Optional[int] = None,
                 num_classes: Optional[int] = None, regression: bool = False,
                 preprocessor=None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.preprocessor = preprocessor

    def reset(self):
        self.reader.reset()

    def _to_dataset(self, feats, labels) -> DataSet:
        x = np.asarray(feats, dtype=np.float32)
        if self.label_index is None:
            ds = DataSet(x, x)
        elif self.regression:
            ds = DataSet(x, np.asarray(labels, dtype=np.float32))
        else:
            y = np.zeros((len(labels), self.num_classes), dtype=np.float32)
            y[np.arange(len(labels)), np.asarray(labels, dtype=int)] = 1.0
            ds = DataSet(x, y)
        if self.preprocessor is not None:
            self.preprocessor.pre_process(ds)
        return ds

    def __iter__(self):
        self.reader.reset()
        feats, labels = [], []
        for rec in self.reader:
            if self.label_index is None:
                feats.append([float(v) for v in rec])
            elif len(rec) == 2 and hasattr(rec[0], "ndim"):
                # image record: [array, label]
                feats.append(np.asarray(rec[0], dtype=np.float32))
                labels.append(rec[1])
            else:
                li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
                lab = rec[li]
                rest = [v for i, v in enumerate(rec) if i != li]
                feats.append([float(v) for v in rest])
                labels.append(
                    [float(lab)] if self.regression else int(float(lab))
                )
            if len(feats) == self.batch_size:
                yield self._to_dataset(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._to_dataset(feats, labels)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records → (B, T, F) batches with padding + masks.

    ``alignment_mode`` (AlignmentMode parity): "align_start" (default; data at
    t=0..L-1, padding at the end) or "align_end" (right-aligned so the final
    time steps coincide across the batch — last-step readouts line up)."""

    def __init__(self, reader, batch_size: int, label_index: int = -1,
                 num_classes: Optional[int] = None, regression: bool = False,
                 alignment_mode: str = "align_start"):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        if alignment_mode.lower() not in ("align_start", "align_end"):
            raise ValueError(f"unknown alignment_mode {alignment_mode!r}")
        self.alignment_mode = alignment_mode.lower()

    def reset(self):
        self.reader.reset()

    def _emit(self, seqs) -> DataSet:
        T = max(len(s) for s in seqs)
        nf = len(seqs[0][0]) - 1
        B = len(seqs)
        x = np.zeros((B, T, nf), dtype=np.float32)
        mask = np.zeros((B, T), dtype=np.float32)
        if self.regression:
            y = np.zeros((B, T, 1), dtype=np.float32)
        else:
            y = np.zeros((B, T, self.num_classes), dtype=np.float32)
        for b, seq in enumerate(seqs):
            L = len(seq)
            off = (T - L) if self.alignment_mode == "align_end" else 0
            for t, rec in enumerate(seq):
                li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
                lab = rec[li]
                feats = [float(v) for i, v in enumerate(rec) if i != li]
                x[b, off + t] = feats
                if self.regression:
                    y[b, off + t, 0] = float(lab)
                else:
                    y[b, off + t, int(float(lab))] = 1.0
            mask[b, off:off + L] = 1.0
        return DataSet(x, y, features_mask=mask, labels_mask=mask.copy())

    def __iter__(self):
        self.reader.reset()
        seqs = []
        for seq in self.reader:
            seqs.append(seq)
            if len(seqs) == self.batch_size:
                yield self._emit(seqs)
                seqs = []
        if seqs:
            yield self._emit(seqs)
