"""Record readers — org/datavec/api/records/reader/impl/** parity.

A record is a plain list of values (strings/floats/np arrays); a sequence
record is a list of records. Readers are iterators with reset(), mirroring
RecordReader.next()/hasNext()/reset() without the JVM Writable hierarchy.

Reference classes mirrored (path-cite, mount empty this round):
- CSVRecordReader / CSVSequenceRecordReader  (csv/CSVRecordReader.java)
- LineRecordReader                           (misc/LineRecordReader.java)
- CollectionRecordReader                     (collection/CollectionRecordReader.java)
- RegexLineRecordReader                      (regex/RegexLineRecordReader.java)
- SVMLightRecordReader                       (misc/SVMLightRecordReader.java)
- ImageRecordReader                          (datavec-data-image; PIL replaces
                                              the JavaCPP OpenCV NativeImageLoader)
- TransformProcessRecordReader               (transform wrapper)
"""

from __future__ import annotations

import csv
import os
import re
from typing import Any, Iterable, List, Optional, Sequence

import numpy as np


class RecordReader:
    """Iterator protocol + reset (RecordReader.java parity)."""

    def __iter__(self):
        self.reset()
        return self._gen()

    def _gen(self):
        raise NotImplementedError

    def reset(self):
        pass

    def next_record(self):
        if not hasattr(self, "_it") or self._it is None:
            self._it = iter(self)
        try:
            return next(self._it)
        except StopIteration:
            self._it = None
            raise

    def has_next(self) -> bool:
        if not hasattr(self, "_it") or self._it is None:
            self._it = iter(self)
        try:
            self._peek = next(self._it)
        except StopIteration:
            self._it = None
            return False
        # re-chain the peeked element
        import itertools

        self._it = itertools.chain([self._peek], self._it)
        return True


class LineRecordReader(RecordReader):
    """One record per line: [line]."""

    def __init__(self, path: str, skip_lines: int = 0):
        self.path = path
        self.skip_lines = skip_lines

    def _gen(self):
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """One record per CSV row; values kept as strings (schema/transform or the
    iterator layer handles typing), matching CSVRecordReader's Text writables."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ",",
                 quote: str = '"'):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.quote = quote

    def _gen(self):
        with open(self.path, newline="") as f:
            rd = csv.reader(f, delimiter=self.delimiter, quotechar=self.quote)
            for i, row in enumerate(rd):
                if i < self.skip_lines or not row:
                    continue
                yield list(row)


class CSVSequenceRecordReader(RecordReader):
    """One file = one sequence (list of rows). ``paths`` is a list of files or
    a directory (sorted listing), matching CSVSequenceRecordReader semantics."""

    def __init__(self, paths, skip_lines: int = 0, delimiter: str = ","):
        if isinstance(paths, str) and os.path.isdir(paths):
            paths = [
                os.path.join(paths, p) for p in sorted(os.listdir(paths))
            ]
        self.paths = list(paths) if not isinstance(paths, str) else [paths]
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _gen(self):
        for p in self.paths:
            rows = []
            with open(p, newline="") as f:
                rd = csv.reader(f, delimiter=self.delimiter)
                for i, row in enumerate(rd):
                    if i < self.skip_lines or not row:
                        continue
                    rows.append(list(row))
            yield rows


class CollectionRecordReader(RecordReader):
    """Wraps an in-memory collection of records."""

    def __init__(self, records: Iterable[Sequence[Any]]):
        self.records = [list(r) for r in records]

    def _gen(self):
        yield from (list(r) for r in self.records)


class RegexLineRecordReader(RecordReader):
    """Splits each line by a regex with groups → one value per group."""

    def __init__(self, path: str, regex: str, skip_lines: int = 0):
        self.path = path
        self.pattern = re.compile(regex)
        self.skip_lines = skip_lines

    def _gen(self):
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                m = self.pattern.match(line.rstrip("\n"))
                if m is None:
                    raise ValueError(f"line {i} does not match: {line!r}")
                yield list(m.groups())


class SVMLightRecordReader(RecordReader):
    """`label idx:val idx:val ...` sparse format → [dense features…, label]."""

    def __init__(self, path: str, num_features: int, zero_based: bool = False):
        self.path = path
        self.num_features = num_features
        self.zero_based = zero_based

    def _gen(self):
        with open(self.path) as f:
            for line in f:
                line = line.split("#")[0].strip()
                if not line:
                    continue
                parts = line.split()
                label = float(parts[0])
                feats = np.zeros(self.num_features, dtype=np.float32)
                for tok in parts[1:]:
                    idx, val = tok.split(":")
                    j = int(idx) - (0 if self.zero_based else 1)
                    feats[j] = float(val)
                yield [*feats.tolist(), label]


class ImageRecordReader(RecordReader):
    """Images under class-named directories → [HWC float array, label_index].

    Reference: ImageRecordReader + ParentPathLabelGenerator + NativeImageLoader
    (resize to height×width×channels). PIL replaces JavaCPP OpenCV; output is
    NHWC float32 in [0,255] (normalizers scale), TPU-native channel-last.
    """

    def __init__(self, height: int, width: int, channels: int = 3,
                 root: Optional[str] = None, paths_labels=None):
        self.height = height
        self.width = width
        self.channels = channels
        if root is not None:
            self.labels = sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))
            )
            self.items = [
                (os.path.join(root, lab, fn), i)
                for i, lab in enumerate(self.labels)
                for fn in sorted(os.listdir(os.path.join(root, lab)))
            ]
        else:
            self.items = list(paths_labels or [])
            self.labels = sorted({l for _, l in self.items})

    def _load(self, path: str) -> np.ndarray:
        from deeplearning4j_tpu import native

        if native.image_available():  # NativeImageLoader path (C++ decode)
            try:
                return native.decode_image_file(
                    path, self.height, self.width, self.channels)
            except ValueError:
                pass  # non-JPEG/PNG format: PIL fallback below
        from PIL import Image

        img = Image.open(path)
        img = img.convert("L" if self.channels == 1 else "RGB")
        img = img.resize((self.width, self.height))
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def _gen(self):
        for path, label in self.items:
            yield [self._load(path), label]


class TransformProcessRecordReader(RecordReader):
    """Applies a TransformProcess to each record of an underlying reader
    (org/datavec/api/records/reader/impl/transform/TransformProcessRecordReader.java)."""

    def __init__(self, reader: RecordReader, transform_process):
        self.reader = reader
        self.tp = transform_process

    def reset(self):
        self.reader.reset()

    def _gen(self):
        for rec in self.reader:
            out = self.tp.execute_record(rec)
            if out is not None:  # filtered rows are dropped
                yield out


class WavFileRecordReader(RecordReader):
    """Audio reader (datavec-data-audio WavFileRecordReader.java parity):
    one record per file = [waveform (n_frames, channels) float32 in [-1,1],
    sample_rate]. Pure-stdlib WAV parse (the reference wraps FFmpeg via
    JavaCPP; WAV covers the tested surface offline)."""

    def __init__(self, paths: Sequence[str]):
        self.paths = [os.fspath(p) for p in paths]

    def _gen(self):
        import wave

        for path in self.paths:
            with wave.open(path, "rb") as w:
                n = w.getnframes()
                raw = w.readframes(n)
                width = w.getsampwidth()
                ch = w.getnchannels()
                if width == 2:
                    arr = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
                elif width == 1:
                    arr = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
                elif width == 4:
                    arr = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
                else:
                    raise ValueError(f"unsupported WAV sample width {width}")
                yield [arr.reshape(-1, ch), w.getframerate()]


class ArrowRecordReader(RecordReader):
    """Arrow IPC/Feather serde (datavec-arrow ArrowRecordReader.java parity
    via pyarrow): one record per row, columns in schema order."""

    def __init__(self, path: str):
        self.path = os.fspath(path)

    def _gen(self):
        import pyarrow.feather as feather

        table = feather.read_table(self.path)
        cols = [c.to_pylist() for c in table.columns]
        for row in zip(*cols):
            yield list(row)


def write_arrow(path: str, records: Sequence[Sequence], column_names: Sequence[str]):
    """Write records (rows) to an Arrow/Feather file (ArrowRecordWriter
    parity)."""
    import pyarrow as pa
    import pyarrow.feather as feather

    cols = list(zip(*records)) if records else [[] for _ in column_names]
    table = pa.table({n: list(c) for n, c in zip(column_names, cols)})
    feather.write_feather(table, os.fspath(path))
    return path


class TfidfRecordReader(RecordReader):
    """TF-IDF vectors from a labelled text corpus.

    Reference parity: datavec-data-nlp's TfidfRecordReader (path-cite,
    mount empty this round) — documents become dense tf-idf rows with the
    label appended, using the same weighting as
    ``nlp.vectorizer.TfidfVectorizer`` (which it wraps). Input layout is
    the reference's label-aware convention: ``root/<label>/<file>.txt``,
    one document per file; or pass explicit ``(text, label)`` pairs.
    """

    def __init__(self, root: str = None, *, documents=None,
                 min_word_frequency: int = 1, append_label: bool = True):
        import os

        from deeplearning4j_tpu.nlp.vectorizer import TfidfVectorizer

        if (root is None) == (documents is None):
            raise ValueError("pass exactly one of root= or documents=")
        if root is not None:
            # store (path, label) and read lazily — the ImageRecordReader
            # convention; the raw corpus never stays pinned in memory
            self.sources = []
            for label in sorted(os.listdir(root)):
                d = os.path.join(root, label)
                if not os.path.isdir(d):
                    continue
                for fn in sorted(os.listdir(d)):
                    self.sources.append((os.path.join(d, fn), label))
            self._from_files = True
        else:
            self.sources = list(documents)
            self._from_files = False
        self.append_label = append_label
        self.vectorizer = TfidfVectorizer(
            min_word_frequency=min_word_frequency)
        self.vectorizer.fit([self._read(s) for s, _ in self.sources],
                            labels=[l for _, l in self.sources])

    def _read(self, source: str) -> str:
        if not self._from_files:
            return source
        with open(source) as f:
            return f.read()

    def labels(self):
        return list(self.vectorizer.labels)

    def _gen(self):
        for source, label in self.sources:
            row = list(self.vectorizer.transform(self._read(source)))
            if self.append_label:
                row.append(self.vectorizer.labels.index(label))
            yield row
