"""DataVec-parity ETL (SURVEY.md §2.2 J12).

Reference: the `datavec/` module family — RecordReader zoo
(org/datavec/api/records/reader/impl/**), schema-typed TransformProcess
(org/datavec/api/transform/TransformProcess.java), image loading
(datavec-data-image NativeImageLoader via JavaCPP OpenCV) — path-cites, mount
empty this round.

TPU-native stance: ETL is host-side work feeding the device input pipeline;
records are plain Python lists / numpy arrays (no Writable object hierarchy —
that existed for JVM serialization), transforms are pure functions over
columns, and the iterator layer batches straight into numpy for device_put.
"""

from deeplearning4j_tpu.datavec.records import (  # noqa: F401
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReader,
    RegexLineRecordReader,
    SVMLightRecordReader,
    TfidfRecordReader,
    TransformProcessRecordReader,
    WavFileRecordReader,
    ArrowRecordReader,
    write_arrow,
)
from deeplearning4j_tpu.datavec.transform import (  # noqa: F401
    ColumnType,
    Join,
    Reducer,
    Schema,
    TransformProcess,
)
from deeplearning4j_tpu.datavec.iterators import (  # noqa: F401
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datavec.executor import (  # noqa: F401
    LocalTransformExecutor,
    MultiProcessTransformExecutor,
    ParallelTransformRecordReader,
    TransformExecutionError,
)
