"""Transform executors — single-process and multiprocess ETL.

Reference parity: org/datavec/local/transforms/LocalTransformExecutor.java
(single-JVM list execution) and org/datavec/spark/transform/
SparkTransformExecutor.java (partitioned RDD execution) — path-cite, mount
empty this round. VERDICT Missing #3 called the executor "the last
uncollapsed piece of the Spark surface": the reference scales TransformProcess
by partitioning records across Spark executors; here the same partitioning
maps onto host OS processes feeding the device input pipeline.

TPU-native stance: transforms are pure host-side record functions, so the
executor is embarrassingly parallel — partition the record list into
contiguous chunks, run each chunk in a worker process, merge in chunk order.
Contiguous chunks + in-order merge make the output BIT-IDENTICAL to
single-process execution (filters drop records within their chunk without
disturbing global order), the invariant the tests assert.

Process model: workers are ``fork``-started, so the TransformProcess (whose
steps close over Python functions — not picklable by design, same as the
reference's non-serializable custom transforms under local execution) is
inherited by memory image rather than serialized over the wire. Results are
plain record lists (picklable) returned through a queue. A worker exception
is captured with its traceback and re-raised in the parent as
:class:`TransformExecutionError`; a wedged worker trips ``timeout`` instead
of hanging the pipeline.

Fork-after-threads caveat: forking a JAX-loaded parent (XLA/PJRT spin up
threads on first compile) is the classic os.fork-after-threads hazard, and
CPython warns about it. It is a deliberate trade: ``forkserver``/``spawn``
would have to pickle the transform closures the whole design exists to
avoid, and the children only run pure-Python record functions — they never
touch JAX, so the locks those warnings guard are never taken in the child.
If a child nonetheless wedges before reaching its queue put, ``timeout``
converts the stall into :class:`TransformExecutionError` instead of a hang.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
import traceback
from typing import Any, List, Optional, Sequence

from deeplearning4j_tpu.util import faults as fl
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.faults import RetryPolicy

#: per-chunk restart policy: a dead/failed worker's CHUNK is retried on a
#: fresh process this many times before the whole execute fails loudly —
#: Spark's task-retry semantics on OS processes (docs/FAULT_TOLERANCE.md)
DEFAULT_CHUNK_RETRY = RetryPolicy(max_attempts=3, base_delay=0.05,
                                  max_delay=1.0)


class TransformExecutionError(RuntimeError):
    """A transform worker process failed (or timed out) beyond its retry
    budget. Carries the worker's formatted traceback so the failing
    record/step is debuggable from the parent."""


class LocalTransformExecutor:
    """LocalTransformExecutor.java parity: execute a TransformProcess over a
    record collection in-process. Exists as the named single-process
    counterpart the multiprocess executor is A/B'd (and bit-compared)
    against."""

    @staticmethod
    def execute(records: Sequence[Sequence[Any]], transform_process) -> List[list]:
        return transform_process.execute(records)


def _default_workers() -> int:
    from deeplearning4j_tpu.config import get_environment

    n = get_environment().etl_workers
    return n if n > 0 else max(1, min(os.cpu_count() or 1, 8))


def _worker_main(transform_process, chunk, chunk_idx, out_queue):
    """Runs in the forked child: transform one contiguous chunk. Telemetry
    spans recorded here carry the CHILD's PID (the fork hook in
    util/telemetry.py cleared inherited parent events) and ship back over
    the result queue as plain dicts; the parent merges them so the single
    Chrome trace shows every worker process as its own row."""
    try:
        with tm.span("etl.transform_chunk", chunk=chunk_idx,
                     records=len(chunk)):
            out = transform_process.execute(chunk)
        out_queue.put((chunk_idx, "ok", out,
                       tm.get_telemetry().drain_events()))
    except BaseException as e:  # noqa: BLE001 — must cross the process gap
        out_queue.put((chunk_idx, "error",
                       f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
                       None))


class MultiProcessTransformExecutor:
    """SparkTransformExecutor partitioning collapsed onto host processes.

    ``num_workers=None`` reads ``DL4J_TPU_ETL_WORKERS`` (0/unset = one worker
    per host core, capped at 8). ``min_records_per_worker`` keeps tiny inputs
    on the serial path — forking costs more than it saves below that size.

        ex = MultiProcessTransformExecutor(tp, num_workers=4)
        out = ex.execute(records)      # == tp.execute(records), bit-identical
    """

    def __init__(self, transform_process, num_workers: Optional[int] = None,
                 timeout: float = 300.0, min_records_per_worker: int = 64,
                 retry: Optional[RetryPolicy] = DEFAULT_CHUNK_RETRY):
        self.transform_process = transform_process
        self.num_workers = num_workers if num_workers else _default_workers()
        self.timeout = timeout
        self.min_records_per_worker = min_records_per_worker
        # retry=None -> one attempt per chunk (the pre-elastic behavior)
        self.retry = retry if retry is not None else RetryPolicy(max_attempts=1)

    def final_schema(self):
        return self.transform_process.final_schema()

    def _chunks(self, records):
        n = len(records)
        w = max(1, min(self.num_workers, n // self.min_records_per_worker or 1))
        size = -(-n // w)  # ceil
        return [records[i:i + size] for i in range(0, n, size)]

    def execute(self, records: Sequence[Sequence[Any]]) -> List[list]:
        records = list(records)
        if (self.num_workers <= 1
                or len(records) < 2 * self.min_records_per_worker):
            with tm.span("etl.execute_serial", records=len(records)):
                return self.transform_process.execute(records)
        try:
            ctx = mp.get_context("fork")
        except ValueError:  # no fork on this platform: serial fallback
            return self.transform_process.execute(records)
        chunks = self._chunks(records)
        if len(chunks) <= 1:
            with tm.span("etl.execute_serial", records=len(records)):
                return self.transform_process.execute(records)
        with tm.span("etl.execute", records=len(records),
                     workers=len(chunks)):
            out = self._execute_chunks(ctx, chunks)
        tm.counter("etl.chunks_total", len(chunks))
        tm.counter("etl.records_total", len(records))
        return out

    def _execute_chunks(self, ctx, chunks) -> List[list]:
        """Supervised chunk execution: a dead or failing worker no longer
        fails the epoch — its CHUNK is restarted on a fresh process (bounded
        by ``self.retry``), and the in-order merge keeps the output
        bit-identical to serial. Exhausting the retry budget raises the
        same loud :class:`TransformExecutionError` as before, with the last
        child traceback attached."""
        import queue as _q

        out_queue = ctx.Queue()
        procs: dict = {}        # chunk idx -> live/most-recent Process
        attempts: dict = {}     # chunk idx -> processes launched so far
        results: dict = {}
        suspects: set = set()   # dead-without-result, seen by ONE scan

        def launch(idx):
            attempts[idx] = attempts.get(idx, 0) + 1
            p = ctx.Process(
                target=_worker_main,
                args=(self.transform_process, chunks[idx], idx, out_queue),
                daemon=True)
            p.start()
            procs[idx] = p

        def retry_or_fail(idx, why):
            nonlocal deadline
            if attempts[idx] >= self.retry.max_attempts:
                raise TransformExecutionError(
                    f"transform worker for chunk {idx} failed after "
                    f"{attempts[idx]} attempt(s):\n{why}")
            tm.counter("etl.worker_restarts_total")
            tm.instant("etl.worker_restart", chunk=idx,
                       attempt=attempts[idx], why=str(why)[:200])
            # the policy's jittered backoff; a restart is progress, so the
            # no-progress window re-arms (bounded: attempts are capped)
            self.retry.sleep_before_retry(attempts[idx])
            launch(idx)
            deadline = time.monotonic() + budget

        for i in range(len(chunks)):
            launch(i)
        # fault seam (util/faults.py): SIGKILL one REAL worker so the
        # restart path below is exercised by the exact mechanism a host
        # OOM-killer / preemption would use
        fault = fl.get_injector().fire(fl.KILL_ETL_WORKER)
        if fault is not None:
            victim = procs[int(fault.arg or 0) % len(chunks)]
            if victim.pid is not None:
                try:
                    os.kill(victim.pid, 9)
                except ProcessLookupError:
                    pass  # won the race and exited already
        # ``timeout`` bounds the wait WITHOUT PROGRESS (the pre-elastic
        # semantics: each chunk result had its own get(timeout)); every
        # arriving result or launched restart re-arms it, so a long
        # many-chunk job that keeps delivering never trips it, while a
        # wedged pipeline still dies after one quiet timeout window. A
        # caller-supplied RetryPolicy(deadline=...) tightens the window.
        budget = self.timeout
        if self.retry.deadline is not None:
            budget = min(budget, self.retry.deadline)
        deadline = time.monotonic() + budget
        try:
            # drain BEFORE join: a child cannot exit until its queue payload
            # is consumed (the classic mp.Queue/join deadlock)
            while len(results) < len(chunks):
                if time.monotonic() > deadline:
                    pending = sorted(set(range(len(chunks))) - set(results))
                    raise TransformExecutionError(
                        f"transform execute timed out: no progress for "
                        f"{budget}s ({len(results)}/{len(chunks)} chunks "
                        f"done, pending {pending})")
                try:
                    idx, status, payload, spans = out_queue.get(timeout=0.2)
                except _q.Empty:
                    # liveness scan: a SIGKILLed worker posts nothing — its
                    # death is only visible through the process table. A
                    # restart is charged only on the SECOND consecutive
                    # dead sighting: a worker that exited right after
                    # flushing its result gets one more drain pass (0.2s)
                    # for that result to surface, so success is never
                    # misread as death at the retry-budget boundary
                    for idx, p in list(procs.items()):
                        if idx in results or p.is_alive():
                            suspects.discard(idx)
                        elif idx in suspects:
                            suspects.discard(idx)
                            retry_or_fail(
                                idx, f"worker pid={p.pid} died with exit "
                                     f"code {p.exitcode} before returning "
                                     f"its chunk")
                        else:
                            suspects.add(idx)
                    continue
                except (EOFError, OSError) as e:
                    # a decode/read error on the result pipe: count it and
                    # let the liveness scan restart the dead sender. (A
                    # worker SIGKILLed exactly mid-frame on a >PIPE_BUF
                    # payload can in principle stall recv past this —
                    # inherent to mp.Queue and present before the retry
                    # rewrite; the fault tests kill between frames.)
                    tm.counter("etl.result_pipe_errors_total")
                    tm.instant("etl.result_pipe_error", error=repr(e)[:200])
                    continue
                deadline = time.monotonic() + budget  # progress: re-arm
                if idx in results:
                    continue  # stale duplicate from a raced restart
                if status != "ok":
                    retry_or_fail(idx, payload)
                    continue
                if spans:  # worker-PID spans onto the merged trace timeline
                    tm.get_telemetry().merge_events(spans)
                results[idx] = payload
        finally:
            for p in procs.values():
                if p.is_alive():
                    p.terminate()
            for p in procs.values():
                p.join(timeout=5.0)
        out: List[list] = []
        for i in range(len(chunks)):
            out.extend(results[i])
        return out

    def execute_reader(self, reader) -> List[list]:
        """Materialize a RecordReader and transform its records in parallel."""
        return self.execute(list(reader))


class ParallelTransformRecordReader:
    """RecordReader facade over the multiprocess executor: reads the base
    reader's records ONCE, transforms them across worker processes, then
    iterates the merged output — drop-in where TransformProcessRecordReader
    goes, so the existing RecordReaderDataSetIterator bridges the parallel
    ETL back into a DataSetIterator unchanged:

        rr = ParallelTransformRecordReader(CSVRecordReader(path), tp,
                                           num_workers=4)
        it = RecordReaderDataSetIterator(rr, batch_size=32, label_index=-1,
                                         num_classes=3)
    """

    def __init__(self, reader, transform_process,
                 num_workers: Optional[int] = None, timeout: float = 300.0):
        self.reader = reader
        self.executor = MultiProcessTransformExecutor(
            transform_process, num_workers=num_workers, timeout=timeout)
        self._out: Optional[List[list]] = None

    def _materialize(self):
        if self._out is None:
            self.reader.reset()
            self._out = self.executor.execute(list(self.reader))
        return self._out

    def reset(self):
        pass  # transformed records are cached; iteration restarts from them

    def __iter__(self):
        return iter(self._materialize())

    def invalidate(self):
        """Drop the cache (re-read + re-transform on next iteration)."""
        self._out = None
