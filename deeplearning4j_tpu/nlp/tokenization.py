"""Tokenizers: default whitespace/punct tokenizer + BERT WordPiece.

Reference parity: deeplearning4j-nlp text/tokenization/tokenizer/** —
DefaultTokenizer.java, BertWordPieceTokenizer.java (wraps
BertWordPieceTokenizerFactory + the wordpiece vocab), and the
BertWordPieceStreamTokenizer greedy longest-match algorithm — path-cite,
mount empty this round. Pure-Python host-side code (tokenization is not a
device workload); emits numpy int arrays ready for device feed.
"""

from __future__ import annotations

import string
import unicodedata
from typing import Dict, Iterable, List, Optional


class Vocab:
    """token ↔ id table (BertWordPieceTokenizerFactory vocab parity).

    File format: one token per line, id = line number (the BERT vocab.txt
    convention)."""

    PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"

    def __init__(self, tokens: Iterable[str]):
        self.tokens: List[str] = list(tokens)
        self.index: Dict[str, int] = {t: i for i, t in enumerate(self.tokens)}

    @classmethod
    def load(cls, path: str) -> "Vocab":
        with open(path, encoding="utf-8") as f:
            return cls([ln.rstrip("\n") for ln in f if ln.rstrip("\n")])

    @classmethod
    def build(cls, corpus: Iterable[str], max_size: int = 30000) -> "Vocab":
        """Build a word-level+wordpiece-ish vocab from a corpus (test/demo
        helper; real BERT vocabs are loaded with :meth:`load`)."""
        counts: Dict[str, int] = {}
        tok = DefaultTokenizer()
        for line in corpus:
            for w in tok.tokenize(line.lower()):
                counts[w] = counts.get(w, 0) + 1
        special = [cls.PAD, cls.UNK, cls.CLS, cls.SEP, cls.MASK]
        words = sorted(counts, key=lambda w: (-counts[w], w))[: max_size - len(special)]
        return cls(special + words)

    def __len__(self):
        return len(self.tokens)

    def __contains__(self, t):
        return t in self.index

    def id(self, token: str) -> int:
        return self.index.get(token, self.index.get(self.UNK, 0))

    def token(self, i: int) -> str:
        return self.tokens[i]


class DefaultTokenizer:
    """Whitespace + punctuation splitting, optional lowercase/accent-strip
    (DefaultTokenizer.java + BERT BasicTokenizer behavior)."""

    def __init__(self, lower_case: bool = True, strip_accents: bool = True):
        self.lower_case = lower_case
        self.strip_accents = strip_accents

    def tokenize(self, text: str) -> List[str]:
        if self.lower_case:
            text = text.lower()
        if self.strip_accents:
            text = "".join(
                c for c in unicodedata.normalize("NFD", text)
                if unicodedata.category(c) != "Mn"
            )
        out: List[str] = []
        cur = ""
        for ch in text:
            if ch.isspace():
                if cur:
                    out.append(cur)
                    cur = ""
            elif ch in string.punctuation:
                if cur:
                    out.append(cur)
                    cur = ""
                out.append(ch)
            else:
                cur += ch
        if cur:
            out.append(cur)
        return out


class BertWordPieceTokenizer:
    """Greedy longest-match-first wordpiece over a basic-tokenized stream
    (BertWordPieceTokenizer.java / the standard BERT WordpieceTokenizer).

    Unknown words (no wordpiece cover) become [UNK]. Continuation pieces use
    the ``##`` prefix convention."""

    def __init__(self, vocab: Vocab, lower_case: bool = True,
                 max_chars_per_word: int = 100):
        self.vocab = vocab
        self.basic = DefaultTokenizer(lower_case=lower_case)
        self.max_chars_per_word = max_chars_per_word

    def tokenize(self, text: str) -> List[str]:
        pieces: List[str] = []
        for word in self.basic.tokenize(text):
            pieces.extend(self._wordpiece(word))
        return pieces

    def encode(self, text: str) -> List[int]:
        return [self.vocab.id(t) for t in self.tokenize(text)]

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars_per_word:
            return [Vocab.UNK]
        if word in self.vocab:
            return [word]
        out: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece: Optional[str] = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [Vocab.UNK]
            out.append(piece)
            start = end
        return out
