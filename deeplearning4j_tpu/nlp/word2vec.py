"""Word vectors: Word2Vec (SGNS), GloVe, ParagraphVectors.

Reference parity: deeplearning4j-nlp models/word2vec/** (Word2Vec.java with
the Builder: minWordFrequency/layerSize/windowSize/negativeSample...),
models/glove/Glove.java, models/paragraphvectors/ParagraphVectors.java, and
the WordVectors lookup API (getWordVectorMatrix, wordsNearest, similarity) —
path-cite, mount empty this round.

TPU-native design: the reference trains with a custom threaded host loop over
hierarchical-softmax/negative-sampling ops. Here training pairs are generated
host-side (cheap) and the update is ONE jitted device step over a whole batch
of (center, context, negatives) — skip-gram negative sampling as two gathers,
a batched dot, and two scatter-adds that XLA fuses; the embedding matrices
never leave the device during an epoch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizer


class _VocabCache:
    """AbstractCache / VocabCache parity: word ↔ index + frequencies."""

    def __init__(self, words: List[str], counts: np.ndarray):
        self.words = words
        self.counts = counts
        self.index = {w: i for i, w in enumerate(words)}

    def __len__(self):
        return len(self.words)

    @classmethod
    def from_corpus(cls, token_lines: Sequence[List[str]], min_count: int):
        freq: Dict[str, int] = {}
        for toks in token_lines:
            for t in toks:
                freq[t] = freq.get(t, 0) + 1
        words = sorted((w for w, c in freq.items() if c >= min_count),
                       key=lambda w: (-freq[w], w))
        return cls(words, np.array([freq[w] for w in words], np.float64))


class WordVectorsMixin:
    """Lookup API parity (WordVectors interface)."""

    vocab: _VocabCache
    vectors: np.ndarray  # (V, D)

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index.get(word)
        return None if i is None else self.vectors[i]

    def has_word(self, word: str) -> bool:
        return word in self.vocab.index

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom else 0.0

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        v = self.word_vector(word)
        if v is None:
            return []
        m = self.vectors
        sims = (m @ v) / (np.linalg.norm(m, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out = [self.vocab.words[i] for i in order if self.vocab.words[i] != word]
        return out[:n]


class Word2Vec(WordVectorsMixin):
    """Skip-gram with negative sampling OR hierarchical softmax
    (Word2Vec.Builder parity args; useHierarchicSoftmax — the reference's
    other learning impl, models/embeddings/learning/impl/elements/
    HierarchicSoftmax.java). Like word2vec.c, ``negative=0`` implies HS."""

    def __init__(self, min_word_frequency: int = 5, layer_size: int = 100,
                 window_size: int = 5, negative: int = 5, epochs: int = 1,
                 learning_rate: float = 0.025, subsample: float = 1e-3,
                 batch_size: int = 1024, seed: int = 0,
                 use_hierarchic_softmax: bool = False):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.negative = negative
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.subsample = subsample
        self.batch_size = batch_size
        self.seed = seed
        self.use_hierarchic_softmax = use_hierarchic_softmax or negative == 0
        self.vocab: Optional[_VocabCache] = None
        self.vectors: Optional[np.ndarray] = None
        self._tok = DefaultTokenizer()

    # ---------------------------------------------------------------- fit
    def fit(self, sentences: Sequence[str]) -> "Word2Vec":
        token_lines = [self._tok.tokenize(s) for s in sentences]
        self.vocab = _VocabCache.from_corpus(token_lines, self.min_word_frequency)
        V, D = len(self.vocab), self.layer_size
        if V == 0:
            raise ValueError("empty vocabulary (check min_word_frequency)")
        rng = np.random.default_rng(self.seed)
        centers, contexts = self._pairs(token_lines, rng)
        w_in = jnp.asarray(rng.normal(0, 1.0 / np.sqrt(D), (V, D)), jnp.float32)
        key = jax.random.PRNGKey(self.seed)
        lr = self.learning_rate
        if self.use_hierarchic_softmax:
            codes, points, mask = _build_huffman(self.vocab.counts)
            syn1 = jnp.zeros((max(V - 1, 1), D), jnp.float32)
            codes_j = jnp.asarray(codes)
            points_j = jnp.asarray(points)
            mask_j = jnp.asarray(mask)
            step = _hs_step()
            for _ in range(self.epochs):
                order = rng.permutation(len(centers))
                for s in range(0, len(order), self.batch_size):
                    idx = order[s:s + self.batch_size]
                    ctx = jnp.asarray(contexts[idx])
                    w_in, syn1 = step(
                        w_in, syn1, jnp.asarray(centers[idx]),
                        codes_j[ctx], points_j[ctx], mask_j[ctx], lr)
            self.syn1 = np.asarray(syn1)
        else:
            # unigram^0.75 negative-sampling table (reference's sampling dist)
            p = self.vocab.counts ** 0.75
            p /= p.sum()
            w_out = jnp.zeros((V, D), jnp.float32)
            step = _sgns_step(self.negative)
            probs = jnp.asarray(p, jnp.float32)
            for _ in range(self.epochs):
                order = rng.permutation(len(centers))
                for s in range(0, len(order), self.batch_size):
                    idx = order[s:s + self.batch_size]
                    key, sub = jax.random.split(key)
                    w_in, w_out = step(
                        w_in, w_out, jnp.asarray(centers[idx]),
                        jnp.asarray(contexts[idx]), probs, sub, lr)
            self.syn1 = np.asarray(w_out)
        self.vectors = np.asarray(w_in)
        return self

    def _pairs(self, token_lines, rng):
        idx = self.vocab.index
        counts = self.vocab.counts
        total = counts.sum()
        keep_p = None
        if self.subsample:
            f = counts / total
            keep_p = np.minimum(1.0, np.sqrt(self.subsample / f) + self.subsample / f)
        cs, xs = [], []
        for toks in token_lines:
            ids = [idx[t] for t in toks if t in idx]
            if keep_p is not None:
                ids = [i for i in ids if rng.random() < keep_p[i]]
            for ci, c in enumerate(ids):
                w = rng.integers(1, self.window_size + 1)
                for j in range(max(0, ci - w), min(len(ids), ci + w + 1)):
                    if j != ci:
                        cs.append(c)
                        xs.append(ids[j])
        if not cs:
            raise ValueError("no training pairs (corpus too small)")
        return np.asarray(cs, np.int32), np.asarray(xs, np.int32)


def _build_huffman(counts, max_code: int = 40):
    """Huffman coding over word counts (word2vec.c CreateBinaryTree /
    the reference's Huffman.java). counts MUST be sorted descending (the
    vocab builder guarantees it). Returns (codes, points, mask) arrays of
    shape (V, L): per word, the branch bits along its root→leaf path, the
    internal-node ids taking syn1 rows, and a validity mask."""
    V = len(counts)
    if V < 2:
        return (np.zeros((V, 1), np.float32), np.zeros((V, 1), np.int32),
                np.zeros((V, 1), np.float32))
    count = np.concatenate([np.asarray(counts, np.float64),
                            np.full(V - 1, 1e18)])
    parent = np.zeros(2 * V - 2, np.int64)
    binary = np.zeros(2 * V - 2, np.int8)
    pos1, pos2 = V - 1, V
    for a in range(V - 1):
        mins = []
        for _ in range(2):
            if pos1 >= 0 and count[pos1] < count[pos2]:
                mins.append(pos1)
                pos1 -= 1
            else:
                mins.append(pos2)
                pos2 += 1
        m1, m2 = mins
        count[V + a] = count[m1] + count[m2]
        if m1 < 2 * V - 2:
            parent[m1] = V + a
        if m2 < 2 * V - 2:
            parent[m2] = V + a
            binary[m2] = 1
    root = 2 * V - 2
    codes_l, points_l = [], []
    L = 1
    for a in range(V):
        # walk leaf→root: each step records the branch bit of the child and
        # the internal node (parent) whose output vector decides that branch
        code, parents = [], []
        b = a
        while b != root:
            code.append(int(binary[b]))
            parents.append(int(parent[b]) - V)
            b = parent[b]
        code = code[::-1][:max_code]      # root-side first (word2vec.c order)
        parents = parents[::-1][:max_code]
        codes_l.append(code)
        points_l.append(parents)
        L = max(L, len(code))
    L = min(L, max_code)
    codes = np.zeros((V, L), np.float32)
    points = np.zeros((V, L), np.int32)
    mask = np.zeros((V, L), np.float32)
    for a in range(V):
        n = min(len(codes_l[a]), L)
        codes[a, :n] = codes_l[a][:n]
        points[a, :n] = points_l[a][:n]
        mask[a, :n] = 1.0
    return codes, points, mask


def _hs_step():
    """One jitted hierarchical-softmax SGD step: for each (center, context)
    pair, walk the CONTEXT word's Huffman path with the center's input
    vector — a batched (B,L) sigmoid instead of the reference's per-node
    host loop."""

    @jax.jit
    def step(w_in, syn1, centers, codes, points, mask, lr):
        v = w_in[centers]                         # (B, D)
        nodes = syn1[points]                      # (B, L, D)
        logits = jnp.einsum("bd,bld->bl", v, nodes)
        # label for each branch is 1 - code (word2vec.c convention); mean
        # over the batch (matches the SGNS step's mean-loss gradient scale)
        g = ((1.0 - codes) - jax.nn.sigmoid(logits)) * mask / centers.shape[0]
        dv = jnp.einsum("bl,bld->bd", g, nodes)
        dnodes = g[:, :, None] * v[:, None, :]    # (B, L, D)
        w_in = w_in.at[centers].add(lr * dv)
        syn1 = syn1.at[points.reshape(-1)].add(
            lr * dnodes.reshape(-1, v.shape[-1]))
        return w_in, syn1

    return step


def _sgns_step(n_neg: int):
    @jax.jit
    def step(w_in, w_out, centers, contexts, probs, key, lr):
        B = centers.shape[0]
        negs = jax.random.choice(key, w_in.shape[0], (B, n_neg), p=probs)

        def loss_fn(w_in, w_out):
            vc = w_in[centers]                     # (B,D)
            uo = w_out[contexts]                   # (B,D)
            un = w_out[negs]                       # (B,N,D)
            pos = jnp.sum(vc * uo, axis=-1)
            neg = jnp.einsum("bd,bnd->bn", vc, un)
            l = -jax.nn.log_sigmoid(pos) - jnp.sum(jax.nn.log_sigmoid(-neg), axis=-1)
            return jnp.mean(l)

        gi, go = jax.grad(loss_fn, argnums=(0, 1))(w_in, w_out)
        return w_in - lr * gi, w_out - lr * go

    return step


class ParagraphVectors(Word2Vec):
    """PV-DM: document vectors trained jointly with word vectors
    (ParagraphVectors.java / distributed-memory mode). ``fit`` assigns one
    vector per document; ``infer_vector`` fits a fresh doc vector with words
    frozen (inferVector parity)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.doc_vectors: Optional[np.ndarray] = None

    def fit(self, documents: Sequence[str]) -> "ParagraphVectors":
        super().fit(documents)  # word vectors via SGNS
        token_lines = [self._tok.tokenize(d) for d in documents]
        rng = np.random.default_rng(self.seed + 1)
        D = self.layer_size
        docs = np.zeros((len(documents), D), np.float32)
        for di, toks in enumerate(token_lines):
            docs[di] = self._fit_doc(toks, rng)
        self.doc_vectors = docs
        return self

    def _fit_doc(self, toks: List[str], rng, steps: int = 30) -> np.ndarray:
        ids = [self.vocab.index[t] for t in toks if t in self.vocab.index]
        if not ids:
            return np.zeros((self.layer_size,), np.float32)
        w_out = self.syn1[ids]  # (L,D) contexts this doc must predict
        d = rng.normal(0, 0.01, (self.layer_size,)).astype(np.float32)
        lr = self.learning_rate
        for _ in range(steps):
            z = w_out @ d
            g = (1.0 / (1.0 + np.exp(-z)) - 1.0)[:, None] * w_out  # d(-logσ)/dd
            d -= lr * g.mean(0)
        return d

    def infer_vector(self, text: str) -> np.ndarray:
        return self._fit_doc(self._tok.tokenize(text), np.random.default_rng(0))

    def doc_vector(self, i: int) -> np.ndarray:
        return self.doc_vectors[i]


class GloVe(WordVectorsMixin):
    """GloVe via AdaGrad on the weighted log-co-occurrence objective
    (models/glove/Glove.java parity; Pennington et al.)."""

    def __init__(self, min_word_frequency: int = 1, layer_size: int = 50,
                 window_size: int = 5, epochs: int = 25, learning_rate: float = 0.05,
                 x_max: float = 100.0, alpha: float = 0.75, seed: int = 0):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.seed = seed
        self._tok = DefaultTokenizer()

    def fit(self, sentences: Sequence[str]) -> "GloVe":
        token_lines = [self._tok.tokenize(s) for s in sentences]
        self.vocab = _VocabCache.from_corpus(token_lines, self.min_word_frequency)
        idx = self.vocab.index
        V, D = len(self.vocab), self.layer_size
        cooc: Dict[tuple, float] = {}
        for toks in token_lines:
            ids = [idx[t] for t in toks if t in idx]
            for ci, c in enumerate(ids):
                for j in range(max(0, ci - self.window_size),
                               min(len(ids), ci + self.window_size + 1)):
                    if j != ci:
                        cooc[(c, ids[j])] = cooc.get((c, ids[j]), 0.0) + 1.0 / abs(j - ci)
        keys = np.array(list(cooc.keys()), np.int32).reshape(-1, 2)
        xs = np.array(list(cooc.values()), np.float32)
        wf = np.minimum(1.0, (xs / self.x_max) ** self.alpha)
        logx = np.log(xs)

        rng = np.random.default_rng(self.seed)
        w = jnp.asarray(rng.normal(0, 0.05, (V, D)), jnp.float32)
        wc = jnp.asarray(rng.normal(0, 0.05, (V, D)), jnp.float32)
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        state = (w, wc, b, bc, jnp.ones((V, D)), jnp.ones((V, D)),
                 jnp.ones((V,)), jnp.ones((V,)))
        ii = jnp.asarray(keys[:, 0])
        jj = jnp.asarray(keys[:, 1])
        wfj = jnp.asarray(wf)
        lxj = jnp.asarray(logx)
        step = _glove_step()
        for _ in range(self.epochs):
            state = step(state, ii, jj, wfj, lxj, self.learning_rate)
        w, wc = state[0], state[1]
        self.vectors = np.asarray(w + wc)  # sum, as in the paper/reference
        return self


def _glove_step():
    @jax.jit
    def step(state, ii, jj, wf, logx, lr):
        w, wc, b, bc, gw, gwc, gb, gbc = state

        def loss_fn(w, wc, b, bc):
            diff = jnp.sum(w[ii] * wc[jj], axis=-1) + b[ii] + bc[jj] - logx
            return jnp.sum(wf * diff * diff)

        d = jax.grad(loss_fn, argnums=(0, 1, 2, 3))(w, wc, b, bc)
        gw = gw + d[0] ** 2
        gwc = gwc + d[1] ** 2
        gb = gb + d[2] ** 2
        gbc = gbc + d[3] ** 2
        w = w - lr * d[0] / jnp.sqrt(gw)
        wc = wc - lr * d[1] / jnp.sqrt(gwc)
        b = b - lr * d[2] / jnp.sqrt(gb)
        bc = bc - lr * d[3] / jnp.sqrt(gbc)
        return (w, wc, b, bc, gw, gwc, gb, gbc)

    return step
