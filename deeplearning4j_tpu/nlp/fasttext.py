"""FastText: supervised text classification with subword n-gram hashing.

Reference parity: deeplearning4j-nlp
org/deeplearning4j/models/fasttext/FastText.java (path-cite, mount empty) —
the reference JNI-wraps the fastText C++ library; this is a native
equivalent of its SUPERVISED mode (Joulin et al. 2016): the document
embedding is the mean of word + hashed word-n-gram vectors, classified by
one linear layer, trained with softmax CE. The whole update is ONE jitted
step over a padded id matrix (TPU-friendly: fixed shapes, no per-token
host loop).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import updaters as upd


def _hash(s: str) -> int:
    """FNV-1a 32-bit — the hashing trick for n-gram buckets (fastText uses
    the same family; exact constants differ, which only permutes buckets)."""
    h = 2166136261
    for ch in s.encode("utf-8"):
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h


class FastText:
    """Supervised fastText classifier.

    Parameters mirror the reference's builder: ``dim``, ``epoch``, ``lr``,
    ``word_ngrams`` (n-gram order), ``bucket`` (hash buckets for n-grams),
    ``min_count``."""

    def __init__(self, dim: int = 64, epoch: int = 10, lr: float = 0.5,
                 word_ngrams: int = 2, bucket: int = 1 << 15,
                 min_count: int = 1, max_len: int = 64, seed: int = 0,
                 batch_size: int = 64):
        self.dim = dim
        self.epoch = epoch
        self.lr = lr
        self.word_ngrams = word_ngrams
        self.bucket = bucket
        self.min_count = min_count
        self.max_len = max_len
        self.seed = seed
        self.batch_size = batch_size
        self.vocab: Dict[str, int] = {}
        self.labels: List[str] = []
        self.emb: Optional[np.ndarray] = None   # (V + bucket + 1, dim)
        self.W: Optional[np.ndarray] = None     # (dim, n_classes)

    # ------------------------------------------------------------ features
    def _tokens(self, text: str) -> List[str]:
        return text.lower().split()

    def _ids(self, text: str) -> List[int]:
        toks = self._tokens(text)
        ids = [self.vocab[t] for t in toks if t in self.vocab]
        V = len(self.vocab)
        for n in range(2, self.word_ngrams + 1):
            for i in range(len(toks) - n + 1):
                gram = " ".join(toks[i:i + n])
                ids.append(V + _hash(gram) % self.bucket)
        return ids[: self.max_len]

    def _matrix(self, texts: Sequence[str]):
        """Padded (B, max_len) id matrix + (B, max_len) mask; pad id is the
        last embedding row, pinned to zeros."""
        pad = len(self.vocab) + self.bucket
        ids = np.full((len(texts), self.max_len), pad, np.int32)
        msk = np.zeros((len(texts), self.max_len), np.float32)
        for r, t in enumerate(texts):
            ii = self._ids(t)
            ids[r, :len(ii)] = ii
            msk[r, :len(ii)] = 1.0
        return ids, msk

    # ------------------------------------------------------------ training
    def fit(self, texts: Sequence[str], labels: Sequence[str]) -> "FastText":
        counts: Dict[str, int] = {}
        for t in texts:
            for tok in self._tokens(t):
                counts[tok] = counts.get(tok, 0) + 1
        # ids must be contiguous AFTER min_count filtering — the n-gram
        # bucket range starts at len(vocab) and the pad row is sized off it
        self.vocab = {t: i for i, t in enumerate(
            sorted(t for t, c in counts.items() if c >= self.min_count))}
        self.labels = sorted(set(labels))
        lab_idx = {l: i for i, l in enumerate(self.labels)}
        C = len(self.labels)
        rng = np.random.default_rng(self.seed)
        n_rows = len(self.vocab) + self.bucket + 1
        emb = jnp.asarray(
            rng.uniform(-0.5 / self.dim, 0.5 / self.dim,
                        size=(n_rows, self.dim)).astype(np.float32))
        emb = emb.at[-1].set(0.0)  # pad row
        W = jnp.zeros((self.dim, C), jnp.float32)
        updater = upd.Sgd(self.lr)
        state = updater.init_state({"emb": emb, "W": W})

        ids, msk = self._matrix(texts)
        y = np.asarray([lab_idx[l] for l in labels], np.int32)

        @jax.jit
        def step(params, state, it, bids, bmsk, by):
            def loss_fn(p):
                vecs = p["emb"][bids]                       # (B, L, D)
                denom = jnp.maximum(bmsk.sum(-1, keepdims=True), 1.0)
                doc = (vecs * bmsk[..., None]).sum(1) / denom
                logits = doc @ p["W"]
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.take_along_axis(
                    logp, by[:, None], 1)[:, 0])

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_state = upd.apply_updater(
                updater, params, grads, state, it)
            # keep the pad row silent
            new_params["emb"] = new_params["emb"].at[-1].set(0.0)
            return new_params, new_state, loss

        params = {"emb": emb, "W": W}
        order = np.arange(len(texts))
        it = 0
        B = self.batch_size
        for _ in range(self.epoch):
            rng.shuffle(order)
            for s in range(0, len(order), B):
                sel = order[s:s + B]
                if len(sel) < B:  # pad the tail batch (masked docs are
                    sel = np.concatenate([sel, order[:B - len(sel)]])
                params, state, _ = step(
                    params, state, jnp.asarray(it),
                    jnp.asarray(ids[sel]), jnp.asarray(msk[sel]),
                    jnp.asarray(y[sel]))
                it += 1
        self.emb = np.asarray(params["emb"])
        self.W = np.asarray(params["W"])
        return self

    # ----------------------------------------------------------- inference
    def predict_probabilities(self, text: str) -> Dict[str, float]:
        ids, msk = self._matrix([text])
        vecs = self.emb[ids[0]]
        denom = max(msk[0].sum(), 1.0)
        doc = (vecs * msk[0][:, None]).sum(0) / denom
        logits = doc @ self.W
        p = np.exp(logits - logits.max())
        p /= p.sum()
        return dict(zip(self.labels, p.tolist()))

    def predict(self, text: str) -> str:
        probs = self.predict_probabilities(text)
        return max(probs, key=probs.get)

    # --------------------------------------------------------- persistence
    def save(self, path: str):
        np.savez(
            path, emb=self.emb, W=self.W,
            vocab=np.asarray(list(self.vocab.keys()), dtype=object),
            vocab_ids=np.asarray(list(self.vocab.values()), np.int64),
            labels=np.asarray(self.labels, dtype=object),
            conf=np.asarray([self.dim, self.word_ngrams, self.bucket,
                             self.max_len], np.int64),
            allow_pickle=True)

    @staticmethod
    def load(path: str) -> "FastText":
        z = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=True)
        dim, ngrams, bucket, max_len = (int(v) for v in z["conf"])
        ft = FastText(dim=dim, word_ngrams=ngrams, bucket=bucket,
                      max_len=max_len)
        ft.vocab = {str(k): int(i)
                    for k, i in zip(z["vocab"], z["vocab_ids"])}
        ft.labels = [str(l) for l in z["labels"]]
        ft.emb = z["emb"]
        ft.W = z["W"]
        return ft
