"""WordVectorSerializer: save/load word vectors.

Reference parity: org/deeplearning4j/models/embeddings/loader/
WordVectorSerializer.java (writeWord2VecModel / readWord2VecModel, the
word2vec text format: header "V D" then "word v1 ... vD" lines) —
path-cite, mount empty this round.
"""

from __future__ import annotations

import gzip
from typing import Tuple

import numpy as np

from deeplearning4j_tpu.nlp.word2vec import WordVectorsMixin, _VocabCache


class _LoadedWordVectors(WordVectorsMixin):
    def __init__(self, vocab, vectors):
        self.vocab = vocab
        self.vectors = vectors


class WordVectorSerializer:
    @staticmethod
    def write_word_vectors(model: WordVectorsMixin, path: str):
        """word2vec text format (gzip if path endswith .gz)."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt", encoding="utf-8") as f:
            v, d = model.vectors.shape
            f.write(f"{v} {d}\n")
            for i, w in enumerate(model.vocab.words):
                vec = " ".join(f"{x:.6f}" for x in model.vectors[i])
                f.write(f"{w} {vec}\n")

    @staticmethod
    def read_word_vectors(path: str) -> WordVectorsMixin:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            words = []
            vectors = np.empty((v, d), np.float32)
            for i in range(v):
                parts = f.readline().rstrip("\n").split(" ")
                words.append(parts[0])
                vectors[i] = [float(x) for x in parts[1:d + 1]]
        return _LoadedWordVectors(
            _VocabCache(words, np.ones(len(words))), vectors)

    # reference-name aliases
    writeWord2VecModel = write_word_vectors
    readWord2VecModel = read_word_vectors
