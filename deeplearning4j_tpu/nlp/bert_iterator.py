"""BertIterator: text → BERT training batches.

Reference parity: deeplearning4j-nlp iterator/BertIterator.java — tasks
SEQ_CLASSIFICATION (labeled sentences/pairs → [CLS] readout training) and
UNSUPERVISED (masked-LM with the BertMaskedLMMasker 80/10/10 strategy),
LengthHandling.FIXED_LENGTH truncate/pad, FeatureArrays with segment ids and
masks — path-cite, mount empty this round.

Emits DataSet batches consumable by MultiLayerNetwork: features (B,T,2)
stacked [token_ids, segment_ids] (BertEmbeddingLayer input), features_mask
(B,T); labels one-hot (B,C) for classification, (B,T,V) + labels_mask for
masked LM.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import BertWordPieceTokenizer, Vocab


class BertIterator:
    SEQ_CLASSIFICATION = "seq_classification"
    UNSUPERVISED = "unsupervised"

    def __init__(
        self,
        tokenizer: BertWordPieceTokenizer,
        *,
        task: str = SEQ_CLASSIFICATION,
        max_length: int = 128,
        batch_size: int = 32,
        sentences: Optional[Sequence[str]] = None,
        labels: Optional[Sequence[int]] = None,
        sentence_pairs: Optional[Sequence[Tuple[str, str]]] = None,
        n_classes: Optional[int] = None,
        mask_prob: float = 0.15,
        seed: int = 0,
    ):
        if task not in (self.SEQ_CLASSIFICATION, self.UNSUPERVISED):
            raise ValueError(f"unknown task {task!r}")
        if sentences is None and sentence_pairs is None:
            raise ValueError("provide sentences or sentence_pairs")
        if task == self.SEQ_CLASSIFICATION and labels is None:
            raise ValueError("SEQ_CLASSIFICATION requires labels")
        self.tokenizer = tokenizer
        self.vocab = tokenizer.vocab
        self.task = task
        self.max_length = max_length
        self.batch_size = batch_size
        self.sentences = sentences
        self.labels = labels
        self.sentence_pairs = sentence_pairs
        self.n_classes = n_classes or (int(max(labels)) + 1 if labels is not None and len(labels) else None)
        self.mask_prob = mask_prob
        self._rng = np.random.default_rng(seed)
        self._seed = seed

    def reset(self):
        self._rng = np.random.default_rng(self._seed)

    # ------------------------------------------------------------------
    def _encode_one(self, i: int) -> Tuple[np.ndarray, np.ndarray, int]:
        """→ (ids[T], segments[T], true_len), FIXED_LENGTH truncate/pad."""
        v = self.vocab
        T = self.max_length
        if self.sentence_pairs is not None:
            a, b = self.sentence_pairs[i]
            ta = self.tokenizer.encode(a)
            tb = self.tokenizer.encode(b)
            # [CLS] a [SEP] b [SEP]; truncate the longer side first
            budget = T - 3
            while len(ta) + len(tb) > budget:
                (ta if len(ta) >= len(tb) else tb).pop()
            ids = [v.id(v.CLS)] + ta + [v.id(v.SEP)] + tb + [v.id(v.SEP)]
            segs = [0] * (len(ta) + 2) + [1] * (len(tb) + 1)
        else:
            t = self.tokenizer.encode(self.sentences[i])[: T - 2]
            ids = [v.id(v.CLS)] + t + [v.id(v.SEP)]
            segs = [0] * len(ids)
        L = len(ids)
        out = np.full((T,), v.id(v.PAD), np.int32)
        out[:L] = ids
        so = np.zeros((T,), np.int32)
        so[:L] = segs
        return out, so, L

    def _mask_tokens(self, ids: np.ndarray, L: int):
        """BertMaskedLMMasker parity: each non-special position is chosen with
        ``mask_prob``; chosen → 80% [MASK], 10% random id, 10% unchanged."""
        v = self.vocab
        labels = ids.copy()
        lmask = np.zeros_like(ids, np.float32)
        special = {v.id(v.CLS), v.id(v.SEP), v.id(v.PAD)}
        masked = ids.copy()
        for t in range(L):
            if ids[t] in special or self._rng.random() >= self.mask_prob:
                continue
            lmask[t] = 1.0
            r = self._rng.random()
            if r < 0.8:
                masked[t] = v.id(v.MASK)
            elif r < 0.9:
                masked[t] = self._rng.integers(0, len(v))
        return masked, labels, lmask

    def _emit(self, idxs: List[int]) -> DataSet:
        B, T = len(idxs), self.max_length
        feats = np.zeros((B, T, 2), np.float32)
        fmask = np.zeros((B, T), np.float32)
        if self.task == self.SEQ_CLASSIFICATION:
            y = np.zeros((B, self.n_classes), np.float32)
            for j, i in enumerate(idxs):
                ids, segs, L = self._encode_one(i)
                feats[j, :, 0], feats[j, :, 1] = ids, segs
                fmask[j, :L] = 1.0
                y[j, int(self.labels[i])] = 1.0
            return DataSet(feats, y, features_mask=fmask)
        V = len(self.vocab)
        y = np.zeros((B, T, V), np.float32)
        lmask = np.zeros((B, T), np.float32)
        for j, i in enumerate(idxs):
            ids, segs, L = self._encode_one(i)
            masked, labels, lm = self._mask_tokens(ids, L)
            feats[j, :, 0], feats[j, :, 1] = masked, segs
            fmask[j, :L] = 1.0
            y[j, np.arange(T), labels] = 1.0
            lmask[j] = lm
        return DataSet(feats, y, features_mask=fmask, labels_mask=lmask)

    def __iter__(self):
        n = len(self.sentence_pairs if self.sentence_pairs is not None else self.sentences)
        for s in range(0, n, self.batch_size):
            yield self._emit(list(range(s, min(s + self.batch_size, n))))
