"""Bag-of-words / TF-IDF text vectorizers.

Reference parity: org.deeplearning4j.bagofwords.vectorizer.
{BagOfWordsVectorizer, TfidfVectorizer} (deeplearning4j-nlp, path-cite,
mount empty this round) and datavec-data-nlp's TfidfRecordReader, which
wraps the same weighting. The reference builds a VocabCache over a
LabelAwareIterator and emits one dense row per document;
``vectorize(text, label)`` returns the (features, one-hot label) pair its
DataSet carries.

Weighting (documented choice, matching the reference's TfidfVectorizer):
tf = raw count in the document, idf = log10(N_docs / doc_frequency);
BagOfWords emits raw counts. Vocabulary is filtered by
``min_word_frequency`` (total corpus count) like the reference builder.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizer


class BagOfWordsVectorizer:
    def __init__(self, min_word_frequency: int = 1, tokenizer=None):
        self.min_word_frequency = int(min_word_frequency)
        self.tokenizer = tokenizer or DefaultTokenizer()
        self.vocab: Dict[str, int] = {}
        self.doc_freq: Optional[np.ndarray] = None
        self.n_docs = 0
        self.labels: List[str] = []

    # -- fitting -------------------------------------------------------------
    def fit(self, docs: Sequence[str], labels: Optional[Sequence[str]] = None):
        counts: Dict[str, int] = {}
        per_doc_tokens = []
        for d in docs:
            toks = self.tokenizer.tokenize(d)
            per_doc_tokens.append(toks)
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
        kept = sorted(t for t, c in counts.items()
                      if c >= self.min_word_frequency)
        self.vocab = {t: i for i, t in enumerate(kept)}
        self.n_docs = len(docs)
        df = np.zeros(len(self.vocab), np.int64)
        for toks in per_doc_tokens:
            for t in set(toks):
                i = self.vocab.get(t)
                if i is not None:
                    df[i] += 1
        self.doc_freq = df
        if labels is not None:
            self.labels = sorted(set(labels))
        return self

    # -- weighting (overridden by TfidfVectorizer) ---------------------------
    def _weight(self, tf: np.ndarray) -> np.ndarray:
        return tf.astype(np.float32)

    # -- transform -----------------------------------------------------------
    def transform(self, doc: str) -> np.ndarray:
        if self.doc_freq is None:
            raise RuntimeError("fit() first")
        tf = np.zeros(len(self.vocab), np.float32)
        for t in self.tokenizer.tokenize(doc):
            i = self.vocab.get(t)
            if i is not None:
                tf[i] += 1.0
        return self._weight(tf)

    def fit_transform(self, docs: Sequence[str],
                      labels: Optional[Sequence[str]] = None) -> np.ndarray:
        self.fit(docs, labels)
        return np.stack([self.transform(d) for d in docs])

    def vectorize(self, text: str, label: str):
        """(features, one-hot label) — the reference's DataSet pair."""
        if label not in self.labels:
            raise ValueError(f"unknown label {label!r}; fit() with labels")
        y = np.zeros(len(self.labels), np.float32)
        y[self.labels.index(label)] = 1.0
        return self.transform(text), y

    def index_of(self, word: str) -> int:
        return self.vocab.get(word, -1)


class TfidfVectorizer(BagOfWordsVectorizer):
    """tf * log10(N/df) weighting (reference TfidfVectorizer.tfidfWord)."""

    def _weight(self, tf: np.ndarray) -> np.ndarray:
        idf = np.zeros_like(tf)
        nz = self.doc_freq > 0
        idf[nz] = np.log10(self.n_docs / self.doc_freq[nz])
        return (tf * idf).astype(np.float32)

    def tfidf_word(self, word: str, count_in_doc: int) -> float:
        i = self.vocab.get(word)
        if i is None or self.doc_freq[i] == 0:
            return 0.0
        return count_in_doc * math.log10(self.n_docs / self.doc_freq[i])
