"""NLP: tokenizers, BERT data pipeline, word vectors.

Reference parity: deeplearning4j-nlp (SURVEY.md §2.2 J15) —
text/tokenization/**, iterator/BertIterator.java, models/** (Word2Vec et al.).
"""

from deeplearning4j_tpu.nlp.tokenization import (  # noqa: F401
    BertWordPieceTokenizer,
    DefaultTokenizer,
    Vocab,
)
from deeplearning4j_tpu.nlp.bert_iterator import BertIterator  # noqa: F401
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, GloVe, ParagraphVectors  # noqa: F401
from deeplearning4j_tpu.nlp.fasttext import FastText  # noqa: F401
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer  # noqa: F401
from deeplearning4j_tpu.nlp.vectorizer import (  # noqa: F401
    BagOfWordsVectorizer,
    TfidfVectorizer,
)
