"""Round-8 importer satellite fixes (ADVICE r5 #2/#4/#5):

- ONNX Quantize/DequantizeLinear per-axis detection for NON-constant scales
  (declared 1-D shape => per-axis; undecidable => loud NotImplementedError,
  never a silent per-tensor broadcast along the wrong axis);
- TF MatrixDiagV3 const-folds num_rows/num_cols/padding_value and refuses
  non-default values instead of emitting a silently wrong square matrix;
- TF sorted SegmentMax/SegmentMin fill EMPTY segments with TF's documented
  0, not the unsorted kernels' dtype ±lowest/highest.
"""

import numpy as np
import pytest
import tensorflow as tf

from deeplearning4j_tpu.imports import import_graph_def, import_onnx
from deeplearning4j_tpu.imports.tf_import import UnsupportedOpError

from test_imports import (  # noqa: E402
    _freeze,
    _golden_match,
    _onnx_attr_i,
    _onnx_input,
    _onnx_model,
    _onnx_node,
    _onnx_tensor,
)

R = np.random.default_rng(8)


class TestQdqNonConstScale:
    def test_quantize_per_axis_runtime_scale(self):
        """1-D size-3 scale as a GRAPH INPUT (not an initializer): the
        declared shape must trigger per-axis reshaping along axis=1."""
        model = _onnx_model(
            nodes=[_onnx_node("QuantizeLinear", ["x", "scale", "zp"], ["y"],
                              _onnx_attr_i("axis", 1))],
            initializers=[_onnx_tensor("zp", np.zeros(3, np.uint8))],
            inputs=[_onnx_input("x", (2, 3, 4)), _onnx_input("scale", (3,))],
            outputs=["y"],
        )
        sd = import_onnx(model)
        x = R.normal(size=(2, 3, 4)).astype(np.float32) * 5
        scale = np.asarray([0.1, 0.5, 2.0], np.float32)
        y = sd.output({"x": x, "scale": scale}, ["y"])["y"]
        ref = np.clip(np.rint(x / scale.reshape(1, 3, 1)), 0, 255) \
            .astype(np.uint8)
        np.testing.assert_array_equal(y, ref)

    def test_dequantize_per_axis_runtime_scale(self):
        model = _onnx_model(
            nodes=[_onnx_node("DequantizeLinear", ["x", "scale"], ["y"],
                              _onnx_attr_i("axis", 0))],
            initializers=[_onnx_tensor(
                "x", R.integers(-100, 100, (3, 4)).astype(np.int8))],
            inputs=[_onnx_input("scale", (3,))],
            outputs=["y"],
        )
        sd = import_onnx(model)
        scale = np.asarray([0.5, 1.5, 3.0], np.float32)
        xv = sd._arrays["x"]
        y = sd.output({"scale": scale}, ["y"])["y"]
        ref = xv.astype(np.float32) * scale.reshape(3, 1)
        np.testing.assert_allclose(y, ref, rtol=1e-6)

    def test_scalar_runtime_scale_stays_per_tensor(self):
        model = _onnx_model(
            nodes=[_onnx_node("QuantizeLinear", ["x", "scale"], ["y"])],
            initializers=[],
            inputs=[_onnx_input("x", (2, 5)), _onnx_input("scale", ())],
            outputs=["y"],
        )
        sd = import_onnx(model)
        x = R.normal(size=(2, 5)).astype(np.float32)
        y = sd.output({"x": x, "scale": np.float32(0.3)}, ["y"])["y"]
        ref = np.clip(np.rint(x / 0.3), 0, 255).astype(np.uint8)
        np.testing.assert_array_equal(y, ref)

    def test_rank2_runtime_scale_fails_loudly(self):
        model = _onnx_model(
            nodes=[_onnx_node("QuantizeLinear", ["x", "scale"], ["y"])],
            initializers=[],
            inputs=[_onnx_input("x", (2, 3, 4)),
                    _onnx_input("scale", (3, 4))],
            outputs=["y"],
        )
        with pytest.raises(NotImplementedError, match="rank-2"):
            import_onnx(model)


class TestMatrixDiagV3Defaults:
    def test_default_form_still_imports(self):
        v = R.normal(size=(5,)).astype(np.float32)
        gd, golden, in_names, out_names = _freeze(
            lambda x: tf.linalg.diag(x), [v])
        _golden_match(gd, golden, in_names, out_names, [v])

    def test_num_rows_rejected(self):
        v = R.normal(size=(4,)).astype(np.float32)
        gd, _, _, _ = _freeze(
            lambda x: tf.linalg.diag(x, num_rows=6), [v])
        with pytest.raises(UnsupportedOpError, match="num_rows"):
            import_graph_def(gd)

    def test_num_cols_rejected(self):
        v = R.normal(size=(4,)).astype(np.float32)
        gd, _, _, _ = _freeze(
            lambda x: tf.linalg.diag(x, num_cols=7), [v])
        with pytest.raises(UnsupportedOpError, match="num_cols"):
            import_graph_def(gd)

    def test_padding_value_rejected(self):
        v = R.normal(size=(4,)).astype(np.float32)
        gd, _, _, _ = _freeze(
            lambda x: tf.linalg.diag(x, padding_value=9.0), [v])
        with pytest.raises(UnsupportedOpError, match="padding_value"):
            import_graph_def(gd)


class TestSortedSegmentEmptyFill:
    def test_segment_max_empty_segment_zero_fill(self):
        # ids [0, 0, 2, 2]: segment 1 is EMPTY -> TF documents output 0
        data = np.asarray([[1., -5.], [3., -2.], [7., -9.], [2., -1.]],
                          np.float32)
        ids = np.asarray([0, 0, 2, 2], np.int64)
        gd, golden, in_names, out_names = _freeze(
            lambda d: tf.math.segment_max(d, ids), [data])
        _golden_match(gd, golden, in_names, out_names, [data])

    def test_segment_min_empty_segment_zero_fill(self):
        data = np.asarray([[4., 5.], [3., 2.], [7., 9.]], np.float32)
        ids = np.asarray([0, 0, 3], np.int64)  # segments 1 and 2 empty
        gd, golden, in_names, out_names = _freeze(
            lambda d: tf.math.segment_min(d, ids), [data])
        _golden_match(gd, golden, in_names, out_names, [data])
        assert not np.isinf(golden[0]).any()  # the golden itself is 0-filled

    def test_unsorted_semantics_unchanged(self):
        """The registry's unsorted kernels keep their ±lowest/highest fill —
        the 0 fill is opt-in for the SORTED TF ops only."""
        from deeplearning4j_tpu.ops import registry

        data = np.asarray([1., 2., 3.], np.float32)
        ids = np.asarray([0, 0, 2], np.int32)
        out = np.asarray(registry.exec_op(
            "segment_max", data, ids, num_segments=3))
        assert out[1] < -1e30  # dtype-lowest fill, untouched
        filled = np.asarray(registry.exec_op(
            "segment_max", data, ids, num_segments=3, empty_fill=0))
        assert filled[1] == 0.0
