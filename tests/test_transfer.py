"""Transfer learning: freeze, replace, featurize.

Reference test parity: deeplearning4j-core TransferLearning* tests
(SURVEY.md §4 DL4J integration row)."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    FineTuneConfiguration,
    FrozenLayer,
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd


def _base_net(rng, n_classes=3):
    conf = (
        NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01)).list()
        .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
        .layer(DenseLayer(n_in=16, n_out=8, activation="relu"))
        .layer(OutputLayer(n_in=8, n_out=n_classes, loss="mcxent",
                           activation="softmax"))
        .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    xs = rng.standard_normal((64, 4)).astype(np.float32)
    ys = np.eye(n_classes, dtype=np.float32)[rng.integers(0, n_classes, 64)]
    net.fit(xs, ys, epochs=5)
    return net, xs, ys


def test_frozen_layers_do_not_move(rng):
    net, xs, ys = _base_net(rng)
    new = (TransferLearning.Builder(net)
           .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.1)))
           .set_feature_extractor(0)
           .build())
    assert isinstance(new.layers[0], FrozenLayer)
    w0 = np.asarray(new.params[0]["W"]).copy()
    w1 = np.asarray(new.params[1]["W"]).copy()
    new.fit(xs, ys, epochs=3)
    np.testing.assert_array_equal(np.asarray(new.params[0]["W"]), w0)
    assert np.abs(np.asarray(new.params[1]["W"]) - w1).max() > 1e-6


def test_nout_replace_new_head(rng):
    net, xs, _ = _base_net(rng, n_classes=3)
    new = (TransferLearning.Builder(net)
           .set_feature_extractor(1)
           .n_out_replace(2, 5)
           .build())
    out = new.output(xs)
    assert out.shape == (64, 5)
    # frozen features preserved from the base net
    np.testing.assert_allclose(np.asarray(new.params[0]["W"]),
                               np.asarray(net.params[0]["W"]))


def test_remove_and_add_layers(rng):
    net, xs, _ = _base_net(rng)
    new = (TransferLearning.Builder(net)
           .set_feature_extractor(0)
           .remove_output_layer()
           .add_layer(OutputLayer(n_in=8, n_out=7, loss="mcxent",
                                  activation="softmax"))
           .build())
    assert new.output(xs).shape == (64, 7)
    ys = np.eye(7, dtype=np.float32)[np.random.default_rng(0).integers(0, 7, 64)]
    new.fit(xs, ys, epochs=3)  # trains without error


def test_helper_featurize_matches_prefix(rng):
    net, xs, ys = _base_net(rng)
    frozen = (TransferLearning.Builder(net).set_feature_extractor(1).build())
    helper = TransferLearningHelper(frozen, frozen_until=1)
    feats = np.asarray(helper.featurize(xs))
    assert feats.shape == (64, 8)
    acts = net.feed_forward(xs)
    np.testing.assert_allclose(feats, np.asarray(acts[2]), atol=1e-5)
    tail = helper.unfrozen_graph()
    out = tail.output(feats)
    np.testing.assert_allclose(out, np.asarray(net.output(xs)), atol=1e-5)
    # training the tail moves the shared (unfrozen) head params
    w = np.asarray(tail.params[0]["W"]).copy()
    tail.fit(feats, ys, epochs=2)
    assert np.abs(np.asarray(tail.params[0]["W"]) - w).max() > 1e-7


def test_tail_training_does_not_delete_source_buffers(rng):
    net, xs, ys = _base_net(rng)
    frozen = TransferLearning.Builder(net).set_feature_extractor(1).build()
    helper = TransferLearningHelper(frozen, frozen_until=1)
    feats = np.asarray(helper.featurize(xs))
    tail = helper.unfrozen_graph()
    tail.fit(feats, ys, epochs=2)
    # the source network must remain fully usable (no donated-buffer deletion)
    out = frozen.output(xs)
    assert np.isfinite(np.asarray(out)).all()
    # and copy_back writes the trained tail into the source
    helper.copy_back()
    np.testing.assert_allclose(np.asarray(frozen.params[2]["W"]),
                               np.asarray(tail.params[0]["W"]))


def test_nout_replace_reinits_shape_ripple_layers(rng):
    # a width change ripples into BatchNormalization (no n_in field): stale
    # (16,) stats must not be grafted over the fresh (10,) ones
    from deeplearning4j_tpu.nn.layers import BatchNormalization

    conf = (
        NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01)).list()
        .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
        .layer(BatchNormalization())
        .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                           activation="softmax"))
        .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    xs = rng.standard_normal((8, 4)).astype(np.float32)
    new = TransferLearning.Builder(net).n_out_replace(0, 10).build()
    out = new.output(xs)  # must not crash on stale BN shapes
    assert np.asarray(out).shape == (8, 3)
