"""PackedTrainer: flattened-state training (DL4J flattened-params parity,
TPU-motivated — one buffer per dtype instead of hundreds of leaf handles
through the tunnel). Must be numerically identical to the plain step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.util.packed import PackedTrainer, StatePacker


def _mln(seed=7):
    from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
        SubsamplingLayer,
    )
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DenseLayer(n_in=64, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.convolutional(8, 8, 2))
            .build())
    return MultiLayerNetwork(conf).init()


def test_state_packer_roundtrip(rng):
    tree = {"a": jnp.asarray(rng.normal(size=(3, 4)).astype(np.float32)),
            "b": {"c": jnp.asarray(rng.integers(0, 9, (5,))),
                  "d": jnp.asarray(rng.normal(size=()).astype(np.float32))}}
    p = StatePacker(tree)
    back = p.unpack(p.pack(tree))
    for k1, k2 in (("a", None), ("b", "c"), ("b", "d")):
        want = tree[k1] if k2 is None else tree[k1][k2]
        got = back[k1] if k2 is None else back[k1][k2]
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert got.dtype == want.dtype


def test_packed_matches_plain_mln(rng):
    """Same seed, same data: 4 packed steps == 4 plain steps, to float32
    round-off (identical math, different operand packaging)."""
    xs = rng.normal(size=(8, 8, 8, 2)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]

    plain = _mln()
    packed_net = _mln()
    pt = PackedTrainer(packed_net)
    for _ in range(4):
        plain._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
        pt._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    pt.unpack_to_model()
    np.testing.assert_allclose(float(pt.score_value),
                               float(plain.score_value), rtol=1e-6)
    for lp, pp in zip(plain.params, packed_net.params):
        for k in lp:
            np.testing.assert_allclose(np.asarray(pp[k]), np.asarray(lp[k]),
                                       atol=1e-6, rtol=1e-5, err_msg=k)


# tier-1 runtime guard (ISSUE 11 satellite): ~24s — the MLN variant above
# proves the same packed==plain contract on the cheap topology; the CG
# twin stays in the full-suite CI leg
@pytest.mark.slow
def test_packed_matches_plain_cg(rng):
    from deeplearning4j_tpu.zoo import ResNet50

    xs = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 2)]
    plain = ResNet50(num_classes=4, input_shape=(32, 32, 3)).init()
    pnet = ResNet50(num_classes=4, input_shape=(32, 32, 3)).init()
    pt = PackedTrainer(pnet)
    for _ in range(2):
        plain._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
        pt._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    pt.unpack_to_model()
    np.testing.assert_allclose(float(pt.score_value),
                               float(plain.score_value), rtol=1e-5)
    for name in plain.params:
        for k in plain.params[name]:
            np.testing.assert_allclose(
                np.asarray(pnet.params[name][k]),
                np.asarray(plain.params[name][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{name}/{k}")


def test_unpack_resumes_plain_training_at_right_iteration(rng):
    """After unpack_to_model, plain _fit_batch must continue from the
    ADVANCED iteration counter (Adam bias correction / LR schedules) —
    review finding, round 3."""
    xs = rng.normal(size=(4, 8, 8, 2)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]

    a = _mln()
    b = _mln()
    # a: 1 plain + 3 packed + 1 plain;  b: 5 plain
    a._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    pt = PackedTrainer(a)
    for _ in range(3):
        pt._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    pt.unpack_to_model()
    a._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    for _ in range(5):
        b._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    assert a.iteration == b.iteration == 5
    for lp, pp in zip(b.params, a.params):
        for k in lp:
            np.testing.assert_allclose(np.asarray(pp[k]), np.asarray(lp[k]),
                                       atol=1e-6, rtol=1e-5, err_msg=k)
