"""Round-5 TF importer rules: linalg tail, image tail, 3-D conv/pool,
bitwise, FFT, fake-quant, random family — golden-tested against the
installed TensorFlow wherever outputs are deterministic (decompositions
compare reconstructions, not sign-ambiguous factors)."""

import numpy as np
import pytest
import tensorflow as tf

from deeplearning4j_tpu.imports import import_graph_def

from test_imports import _freeze, _golden_match

R = np.random.default_rng(21)


def _golden(fn, feeds, atol=1e-5):
    gd, golden, in_names, out_names = _freeze(fn, feeds)
    _golden_match(gd, golden, in_names, out_names, feeds, atol=atol)


def _import_run(fn, feeds):
    gd, golden, in_names, out_names = _freeze(fn, feeds)
    sd = import_graph_def(gd)
    keys = [sd.tf_name_map[o if ":" in o else o + ":0"] for o in out_names]
    res = sd.output({n: v for n, v in zip(in_names, feeds)}, keys)
    return [np.asarray(res[k]) for k in keys], golden


class TestLinalgTail:
    def test_exact_ops(self):
        a = R.normal(size=(3, 3)).astype(np.float32)
        spd = (a @ a.T + 3 * np.eye(3)).astype(np.float32)
        b = R.normal(size=(3, 2)).astype(np.float32)
        _golden(lambda x: tf.linalg.cholesky(x), [spd], atol=1e-4)
        _golden(lambda x: tf.linalg.inv(x), [spd], atol=1e-4)
        _golden(tf.linalg.solve, [spd, b], atol=1e-3)
        _golden(lambda x: tf.linalg.trace(x), [spd])
        _golden(lambda x: tf.linalg.diag_part(x), [spd])
        _golden(lambda x: tf.nn.l2_loss(x), [spd])

    def test_triangular_solve(self):
        l = np.tril(R.normal(size=(3, 3)).astype(np.float32)) \
            + 2 * np.eye(3, dtype=np.float32)
        b = R.normal(size=(3, 2)).astype(np.float32)
        _golden(lambda x, y: tf.linalg.triangular_solve(x, y, lower=True),
                [l, b], atol=1e-4)

    def test_cross_and_diag(self):
        a = R.normal(size=(4, 3)).astype(np.float32)
        b = R.normal(size=(4, 3)).astype(np.float32)
        _golden(tf.linalg.cross, [a, b])
        v = R.normal(size=(5,)).astype(np.float32)
        _golden(lambda x: tf.linalg.diag(x), [v])

    def test_svd_reconstruction(self):
        a = R.normal(size=(4, 4)).astype(np.float32)
        (s, u, v), (ref_s, ref_u, ref_v) = _import_run(
            lambda x: tf.linalg.svd(x), [a])
        np.testing.assert_allclose(np.sort(s)[::-1], np.sort(ref_s)[::-1],
                                   atol=1e-4)
        rec = u @ np.diag(s) @ v.T
        np.testing.assert_allclose(rec, a, atol=1e-4)

    def test_eigh_reconstruction(self):
        a = R.normal(size=(4, 4)).astype(np.float32)
        spd = (a + a.T).astype(np.float32)
        (e, v), (ref_e, ref_v) = _import_run(
            lambda x: tf.linalg.eigh(x), [spd])
        np.testing.assert_allclose(np.sort(e), np.sort(ref_e), atol=1e-4)
        np.testing.assert_allclose(v @ np.diag(e) @ v.T, spd, atol=1e-3)

    def test_qr_reconstruction(self):
        a = R.normal(size=(4, 3)).astype(np.float32)
        (q, r), _ = _import_run(lambda x: tf.linalg.qr(x), [a])
        np.testing.assert_allclose(q @ r, a, atol=1e-4)
        np.testing.assert_allclose(np.tril(r, -1), 0, atol=1e-6)

    def test_special_functions(self):
        a = (R.random((8,)) * 2 + 0.5).astype(np.float32)
        b = (R.random((8,)) * 2 + 0.5).astype(np.float32)
        x = R.random((8,)).astype(np.float32) * 0.8 + 0.1
        _golden(tf.math.betainc, [a, b, x], atol=1e-4)
        _golden(tf.math.zeta, [a + 1.5, b], atol=1e-3)
        _golden(tf.math.polygamma,
                [np.ones(8, np.float32), a + 0.5], atol=1e-3)


class TestImageTail:
    def test_colorspace_roundtrip(self):
        img = R.random((2, 5, 5, 3)).astype(np.float32)
        _golden(tf.image.rgb_to_hsv, [img], atol=1e-5)
        hsv = tf.image.rgb_to_hsv(img).numpy()
        _golden(tf.image.hsv_to_rgb, [hsv], atol=1e-5)

    def test_adjust_ops(self):
        img = R.random((1, 6, 6, 3)).astype(np.float32)
        _golden(lambda x: tf.image.adjust_hue(x, 0.15), [img], atol=1e-4)
        _golden(lambda x: tf.image.adjust_saturation(x, 1.4), [img],
                atol=1e-4)
        _golden(lambda x: tf.image.adjust_contrast(x, 1.7), [img],
                atol=1e-4)

    def test_crop_and_resize(self):
        img = R.random((2, 8, 8, 2)).astype(np.float32)
        boxes = np.asarray([[0.1, 0.1, 0.8, 0.9], [0.0, 0.0, 1.0, 1.0]],
                           np.float32)
        bidx = np.asarray([0, 1], np.int32)
        _golden(lambda x, b, i: tf.image.crop_and_resize(x, b, i, (4, 4)),
                [img, boxes, bidx], atol=1e-4)

    def test_dilation2d(self):
        x = R.normal(size=(1, 6, 6, 2)).astype(np.float32)
        f = (R.normal(size=(2, 2, 2)) * 0.1).astype(np.float32)
        _golden(lambda a, b: tf.nn.dilation2d(
            a, b, strides=[1, 1, 1, 1], padding="VALID",
            data_format="NHWC", dilations=[1, 1, 1, 1]), [x, f],
            atol=1e-5)

    def test_non_max_suppression(self):
        boxes = np.asarray([[0, 0, 1, 1], [0.05, 0.05, 1, 1],
                            [0.5, 0.5, 1.5, 1.5], [2, 2, 3, 3]],
                           np.float32)
        scores = np.asarray([0.9, 0.8, 0.7, 0.6], np.float32)
        (sel,), (ref,) = _import_run(
            lambda b, s: tf.image.non_max_suppression(b, s, 3, 0.5),
            [boxes, scores])
        np.testing.assert_array_equal(sel[:len(ref)], ref)

    def test_nms_v5_scores_and_valid_outputs(self):
        boxes = np.asarray([[0, 0, 1, 1], [0.05, 0.05, 1, 1],
                            [2, 2, 3, 3]], np.float32)
        scores = np.asarray([0.9, 0.8, 0.7], np.float32)

        def f(b, s):
            sel, ssc, valid = tf.raw_ops.NonMaxSuppressionV5(
                boxes=b, scores=s, max_output_size=3, iou_threshold=0.5,
                score_threshold=float("-inf"), soft_nms_sigma=0.0,
                pad_to_max_output_size=False)
            return sel, ssc, valid

        (sel, ssc, valid), (rsel, rssc, rvalid) = _import_run(
            f, [boxes, scores])
        assert int(valid) == int(rvalid) == 2
        np.testing.assert_array_equal(sel[:2], rsel[:2])
        np.testing.assert_allclose(ssc[:2], rssc[:2], atol=1e-6)


class TestConv3D:
    def test_conv3d(self):
        x = R.normal(size=(1, 5, 5, 5, 2)).astype(np.float32)
        w = (R.normal(size=(2, 2, 2, 2, 3)) * 0.2).astype(np.float32)
        _golden(lambda a, b: tf.nn.conv3d(
            a, b, strides=[1, 1, 1, 1, 1], padding="SAME"), [x, w],
            atol=1e-4)

    def test_pool3d(self):
        x = R.normal(size=(1, 4, 4, 4, 2)).astype(np.float32)
        _golden(lambda a: tf.nn.max_pool3d(a, 2, 2, "VALID"), [x])
        _golden(lambda a: tf.nn.avg_pool3d(a, 2, 2, "VALID"), [x],
                atol=1e-5)


class TestBitwiseFFT:
    def test_bitwise(self):
        a = np.asarray([1, 2, 12, -7], np.int32)
        b = np.asarray([1, 2, 2, 1], np.int32)
        _golden(tf.bitwise.left_shift, [a, b])
        _golden(tf.bitwise.right_shift, [a, b])
        _golden(tf.bitwise.invert, [a])

    def test_popcount_vs_tf(self):
        a = np.asarray([0, 1, 255, 1023], np.int32)
        (got,), (ref,) = _import_run(
            lambda x: tf.raw_ops.PopulationCount(x=x), [a])
        np.testing.assert_array_equal(got, ref.astype(np.int32))

    def test_rfft_roundtrip(self):
        x = R.normal(size=(2, 16)).astype(np.float32)
        (got,), (ref,) = _import_run(
            lambda a: tf.signal.rfft(a), [x])
        np.testing.assert_allclose(got.real, ref.real, atol=1e-4)
        np.testing.assert_allclose(got.imag, ref.imag, atol=1e-4)
        (inv,), (ref_inv,) = _import_run(
            lambda a: tf.signal.irfft(tf.signal.rfft(a)), [x])
        np.testing.assert_allclose(inv, ref_inv, atol=1e-4)


class TestFakeQuant:
    def test_args(self):
        x = np.linspace(-8, 8, 33).astype(np.float32)
        _golden(lambda a: tf.quantization.fake_quant_with_min_max_args(
            a, min=-4.0, max=4.0), [x], atol=1e-5)

    def test_vars_asymmetric_exact(self):
        # asymmetric range: the nudge is NOT on the .5 boundary -> exact
        x = R.normal(size=(4, 3)).astype(np.float32) * 4

        def v(a):
            return tf.quantization.fake_quant_with_min_max_vars(
                a, tf.constant(-3.1), tf.constant(2.9))

        _golden(v, [x], atol=1e-5)

    def test_vars_symmetric_within_one_quantum(self):
        # symmetric range: the true zero point is exactly .5 and fp32
        # rounding decides the side — TF's own Args/Vars kernels disagree
        # there (see ops/elementwise.py nudge comment). Allow one quantum.
        x = R.normal(size=(4, 3)).astype(np.float32) * 4

        def v(a):
            return tf.quantization.fake_quant_with_min_max_vars(
                a, tf.constant(-3.0), tf.constant(3.0))

        gd, golden, in_names, out_names = _freeze(v, [x])
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_names[0]]
        got = np.asarray(sd.output({in_names[0]: x}, [key])[key])
        np.testing.assert_allclose(got, golden[0], atol=6.0 / 255.0 + 1e-6)

        def pc(a):
            return tf.quantization.fake_quant_with_min_max_vars_per_channel(
                a, tf.constant([-1.0, -2.0, -4.1]),
                tf.constant([1.0, 2.0, 3.9]))

        gd, golden, in_names, out_names = _freeze(pc, [x])
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_names[0]]
        got = np.asarray(sd.output({in_names[0]: x}, [key])[key])
        np.testing.assert_allclose(got, golden[0], atol=4.0 / 255.0 + 1e-6)


class TestRandomMisc:
    def test_random_shapes_and_determinism(self):
        def f(x):
            return x + tf.random.normal((3, 4), seed=7)

        gd, _, in_names, out_names = _freeze(
            f, [np.zeros((3, 4), np.float32)])
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_names[0] if ":" in out_names[0]
                             else out_names[0] + ":0"]
        feeds = {in_names[0]: np.zeros((3, 4), np.float32)}
        a = np.asarray(sd.output(feeds, [key])[key])
        b = np.asarray(sd.output(feeds, [key])[key])
        assert a.shape == (3, 4)
        np.testing.assert_array_equal(a, b)
        assert np.std(a) > 0.3  # actually random-looking

    def test_stateless_random(self):
        def f(x):
            return x + tf.random.stateless_normal((2, 5), seed=[3, 9])

        gd, _, in_names, out_names = _freeze(
            f, [np.zeros((2, 5), np.float32)])
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_names[0] if ":" in out_names[0]
                             else out_names[0] + ":0"]
        a = np.asarray(sd.output(
            {in_names[0]: np.zeros((2, 5), np.float32)}, [key])[key])
        assert a.shape == (2, 5) and np.isfinite(a).all()

    def test_tensor_scatter_add_and_hist(self):
        t = np.zeros((5, 2), np.float32)
        idx = np.asarray([[1], [3]], np.int32)
        upd = np.ones((2, 2), np.float32)
        _golden(tf.tensor_scatter_nd_add, [t, idx, upd])
        x = R.normal(size=(50,)).astype(np.float32)
        _golden(lambda a: tf.histogram_fixed_width(a, [-2.0, 2.0], nbins=8),
                [x])

    def test_in_top_k_and_segment_max(self):
        preds = R.normal(size=(4, 6)).astype(np.float32)
        targets = np.asarray([0, 3, 5, 2], np.int32)
        _golden(lambda p, t: tf.math.in_top_k(t, p, k=2), [preds, targets])
        data = R.normal(size=(6, 3)).astype(np.float32)
        segs = np.asarray([0, 0, 1, 1, 1, 2], np.int32)
        _golden(lambda d: tf.math.segment_max(d, segs), [data])

    def test_sparse_dense_matmul(self):
        b = R.normal(size=(4, 3)).astype(np.float32)
        a_idx = np.asarray([[0, 1], [2, 3]], np.int64)
        a_vals = np.asarray([2.0, -1.5], np.float32)

        def f(bm):
            return tf.raw_ops.SparseTensorDenseMatMul(
                a_indices=a_idx, a_values=a_vals, a_shape=[3, 4], b=bm)

        _golden(f, [b], atol=1e-5)

    def test_conv3d_dilation_forwarded(self):
        x = R.normal(size=(1, 6, 6, 6, 1)).astype(np.float32)
        w = (R.normal(size=(2, 2, 2, 1, 2)) * 0.3).astype(np.float32)
        _golden(lambda a, b: tf.nn.conv3d(
            a, b, strides=[1, 1, 1, 1, 1], padding="VALID",
            dilations=[1, 2, 2, 2, 1]), [x, w], atol=1e-4)

    def test_diag_part_rank4_rejected(self):
        x = R.normal(size=(2, 3, 2, 3)).astype(np.float32)
        gd, _, in_names, out_names = _freeze(
            lambda a: tf.raw_ops.DiagPart(input=a), [x])
        with pytest.raises(NotImplementedError, match="rank"):
            import_graph_def(gd)

    def test_bitcast(self):
        x = np.asarray([1.0, -2.5], np.float32)
        _golden(lambda a: tf.bitcast(a, tf.int32), [x])
        _golden(lambda a: tf.bitcast(a, tf.uint8), [x])
