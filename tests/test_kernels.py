"""Hot-path kernel engine equivalence suite (docs/KERNELS.md).

Every claim the kernel engine makes is proven here against the exact XLA
path, on the CPU container via the Pallas INTERPRETER (``kernel_impl=
"pallas"`` off-TPU == interpret mode — bit-faithful to the kernel's block
program, so kernel==exact proven here holds for the compiled kernel's
math):

- Pallas conv2d forward + input/filter gradients across the
  stride/dilation/groups/padding grid vs ``lax.conv_general_dilated``.
- Fused LSTM cell/sequence (fwd + grads + TBPTT-segment full-fit
  trajectory) vs the exact scan.
- Fused donated optimizer apply: BIT-identical trajectories vs the
  per-leaf walk for SGD/Adam (fp32), composition with the GSPMD
  ParallelWrapper's ZeRO sharding, fp32 master-weight accumulation for
  bf16 param groups, and the dynamic loss-scale step/skip automaton.
- Flash-attention (B, Sk) padding-mask support: masked-vs-exact value and
  gradient equivalence on both the Pallas-interpret and jnp blockwise
  paths (the nn/transformer.py r14 gap burn-down).
- Per-dtype DL4J_TPU_PEAK_FLOPS parsing and the
  ``optimizer_update_share`` report field.

Tolerances: value equivalence 2e-5 absolute on unit-scale inputs (fp32
tap-order reassociation); gradient equivalence 2e-4; full-fit param
trajectories 1e-4 relative after 4 steps (the r12 trajectory-test
convention). Fused-vs-per-leaf fp32 comparisons are exact
(``array_equal``), not allclose — elementwise updater math is
position-independent, so anything less than bit-identity is a bug.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax import lax

from deeplearning4j_tpu.ops import kernels as K
from deeplearning4j_tpu.ops.kernels import conv as kconv
from deeplearning4j_tpu.ops.kernels import lstm as klstm

R = np.random.default_rng(42)


def _leaves(tree):
    return [np.asarray(t) for t in jax.tree_util.tree_leaves(tree)]


def _max_err(a, b):
    return max(float(jnp.max(jnp.abs(x - y))) for x, y in zip(a, b)) \
        if isinstance(a, (list, tuple)) else float(jnp.max(jnp.abs(a - b)))


# ---------------------------------------------------------------------------
# dispatch seam
# ---------------------------------------------------------------------------


class TestDispatch:
    def test_resolution_order(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        assert K.resolve_impl() == "auto"
        monkeypatch.setenv("DL4J_TPU_KERNEL_IMPL", "exact")
        assert K.resolve_impl() == "exact"
        with K.impl_scope("pallas"):
            assert K.resolve_impl() == "pallas"
        assert K.resolve_impl() == "exact"

    def test_auto_is_exact_off_tpu(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        if jax.default_backend() == "tpu":
            pytest.skip("auto engages the compiled kernel on TPU")
        assert K.dispatch(True)[0] is None       # CPU cannot rank kernels
        with K.impl_scope("pallas"):
            assert K.dispatch(True) == ("interpret", {})
            assert K.dispatch(False)[0] is None  # unsupported geometry

    def test_bad_values_raise(self, monkeypatch):
        with pytest.raises(ValueError):
            K.validate_impl("fast")
        monkeypatch.setenv("DL4J_TPU_KERNEL_IMPL", "warp")
        with pytest.raises(ValueError):
            K.resolve_impl()


# ---------------------------------------------------------------------------
# Pallas conv2d vs lax.conv_general_dilated
# ---------------------------------------------------------------------------

_CONV_GRID = [
    # (hw, k, strides, dilation, groups, cin, cout, padding)
    ((9, 9), (3, 3), (1, 1), (1, 1), 1, 4, 6, "SAME"),
    ((10, 8), (3, 2), (2, 2), (1, 1), 1, 4, 6, "VALID"),
    ((11, 11), (3, 3), (2, 1), (2, 2), 2, 4, 6, (1, 2)),
    ((8, 8), (2, 2), (3, 3), (1, 1), 4, 4, 8, "SAME"),   # depthwise-style
    ((7, 7), (1, 1), (1, 1), (1, 1), 1, 3, 5, "VALID"),  # pointwise
    ((12, 6), (5, 3), (1, 2), (2, 1), 1, 2, 4, "SAME"),
]


def _ref_conv(x, w, strides, pads, dil, g):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, strides, list(pads), rhs_dilation=dil, dimension_numbers=dn,
        feature_group_count=g)


class TestPallasConv:
    # the full grid is ~10s of interpret-mode execution: the dedicated CI
    # kernel leg runs it every time; tier-1 (-m 'not slow') keeps the two
    # structurally distinct cases below for breadth
    @pytest.mark.slow
    @pytest.mark.parametrize(
        "hw,k,s,d,g,cin,cout,pad", _CONV_GRID,
        ids=[f"hw{c[0]}k{c[1]}s{c[2]}d{c[3]}g{c[4]}p{c[7]}"
             for c in _CONV_GRID])
    def test_fwd_and_grads_match_exact(self, hw, k, s, d, g, cin, cout, pad):
        x = jnp.asarray(R.normal(size=(2,) + hw + (cin,)).astype(np.float32))
        w = jnp.asarray(
            (R.normal(size=k + (cin // g, cout)) * 0.3).astype(np.float32))
        pads = kconv.resolve_padding(pad, hw, k, s, d)
        out = kconv.conv2d_pallas(x, w, s, pads, d, g, True)
        ref = _ref_conv(x, w, s, pads, d, g)
        assert out.shape == ref.shape
        assert _max_err(out, ref) < 2e-5

        f_p = lambda x, w: jnp.sum(  # noqa: E731
            jnp.sin(kconv.conv2d_pallas(x, w, s, pads, d, g, True)))
        f_r = lambda x, w: jnp.sum(  # noqa: E731
            jnp.sin(_ref_conv(x, w, s, pads, d, g)))
        gp = jax.grad(f_p, argnums=(0, 1))(x, w)
        gr = jax.grad(f_r, argnums=(0, 1))(x, w)
        assert _max_err(list(gp), list(gr)) < 2e-4

    def test_fwd_and_grads_one_case_fast(self):
        """One strided/dilated/grouped case in tier-1 (the full grid runs
        under the CI kernel leg — see the slow mark above)."""
        self.test_fwd_and_grads_match_exact(
            *_CONV_GRID[2][:5], *_CONV_GRID[2][5:])

    def test_ops_conv2d_dispatch(self):
        """ops.nn.conv2d under the forced-pallas scope == exact path,
        including bias and the registry entry point."""
        from deeplearning4j_tpu.ops import nn as nnops

        x = jnp.asarray(R.normal(size=(2, 9, 9, 4)).astype(np.float32))
        w = jnp.asarray((R.normal(size=(3, 3, 4, 6)) * 0.3)
                        .astype(np.float32))
        b = jnp.asarray(R.normal(size=(6,)).astype(np.float32))
        exact = nnops.conv2d(x, w, b, strides=(2, 1), padding="SAME",
                             dilation=(1, 2))
        with K.impl_scope("pallas"):
            pal = nnops.conv2d(x, w, b, strides=(2, 1), padding="SAME",
                               dilation=(1, 2))
        assert _max_err(pal, exact) < 2e-5

    def test_unsupported_geometries_fall_back(self):
        """NCHW / fp64 / preferred_element_type stay on the exact path even
        under forced pallas (supports() gate)."""
        from deeplearning4j_tpu.ops import nn as nnops

        xn = jnp.asarray(R.normal(size=(2, 4, 9, 9)).astype(np.float32))
        wn = jnp.asarray((R.normal(size=(3, 3, 4, 6)) * 0.3)
                         .astype(np.float32))
        with K.impl_scope("pallas"):
            out = nnops.conv2d(xn, wn, data_format="NCHW")
        assert out.shape == (2, 6, 9, 9)
        assert not kconv.supports(xn, wn, "NCHW", 1, None)
        x = jnp.asarray(R.normal(size=(1, 5, 5, 2)).astype(np.float32))
        w = jnp.asarray(R.normal(size=(3, 3, 2, 2)).astype(np.float32))
        assert not kconv.supports(x, w, "NHWC", 1, jnp.float32)

    def test_bf16_inputs_fp32_accumulation(self):
        x = jnp.asarray(R.normal(size=(2, 8, 8, 4))).astype(jnp.bfloat16)
        w = (jnp.asarray(R.normal(size=(3, 3, 4, 8)) * 0.3)
             .astype(jnp.bfloat16))
        pads = kconv.resolve_padding("SAME", (8, 8), (3, 3), (1, 1), (1, 1))
        out = kconv.conv2d_pallas(x, w, (1, 1), pads, (1, 1), 1, True)
        ref = _ref_conv(x, w, (1, 1), pads, (1, 1), 1).astype(jnp.bfloat16)
        assert out.dtype == jnp.bfloat16
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        assert err < 0.1  # bf16 output quantization, fp32 accumulation

    @pytest.mark.slow
    def test_conv_layer_full_fit_trajectory(self):
        """4-step conv-net fit: kernel_impl=pallas trajectory tracks exact
        within 1e-4 relative (the r12 trajectory-test convention)."""
        params = {}
        x = R.normal(size=(8, 10, 10, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(5).integers(0, 4, 8)]
        for impl in ("exact", "pallas"):
            net = _conv_net(impl)
            for _ in range(4):
                net._fit_batch(x, y)
            params[impl] = _leaves(net.params)
        for a, b in zip(params["exact"], params["pallas"]):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestConvRowTiles:
    """Tuned row-tile parameterization (ISSUE 11 satellite): equivalence
    re-proven at two NON-DEFAULT tile points — the autotuner's first
    search space is real, not declared (docs/AUTOTUNE.md)."""

    @pytest.mark.parametrize("row_tile", [1, 2])
    def test_tiled_fwd_and_grads_match_exact(self, row_tile):
        hw, k, s, d, g, cin, cout, pad = _CONV_GRID[1]  # strided, OH=4
        x = jnp.asarray(R.normal(size=(2,) + hw + (cin,)).astype(np.float32))
        w = jnp.asarray(
            (R.normal(size=k + (cin // g, cout)) * 0.3).astype(np.float32))
        pads = kconv.resolve_padding(pad, hw, k, s, d)
        ref = _ref_conv(x, w, s, pads, d, g)
        oh = ref.shape[1]
        assert kconv.valid_row_tile(oh, row_tile), (oh, row_tile)
        out = kconv.conv2d_pallas(x, w, s, pads, d, g, True, row_tile)
        assert _max_err(out, ref) < 2e-5

        f_t = lambda x, w: jnp.sum(jnp.sin(  # noqa: E731
            kconv.conv2d_pallas(x, w, s, pads, d, g, True, row_tile)))
        f_r = lambda x, w: jnp.sum(  # noqa: E731
            jnp.sin(_ref_conv(x, w, s, pads, d, g)))
        gt = jax.grad(f_t, argnums=(0, 1))(x, w)
        gr = jax.grad(f_r, argnums=(0, 1))(x, w)
        assert _max_err(list(gt), list(gr)) < 2e-4

    def test_invalid_tile_raises_and_guard_agrees(self):
        x = jnp.asarray(R.normal(size=(1, 8, 8, 2)).astype(np.float32))
        w = jnp.asarray(R.normal(size=(3, 3, 2, 4)).astype(np.float32))
        pads = kconv.resolve_padding("SAME", (8, 8), (3, 3), (1, 1), (1, 1))
        assert not kconv.valid_row_tile(8, 3)
        with pytest.raises(ValueError, match="row_tile"):
            kconv.conv2d_pallas(x, w, (1, 1), pads, (1, 1), 1, True, 3)
        # per-candidate VMEM accounting scales with the tile
        assert None in kconv.valid_row_tiles(8)
        assert kconv.valid_row_tiles(8)[1:] == [1, 2, 4]


def _conv_net(impl, fused=False, updater=None, seed=3):
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                              OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.nn.updaters import Adam

    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater or Adam(1e-3)).kernel_impl(impl))
    if fused:
        b = b.fused_update(True)
    conf = (b.list()
            .layer(ConvolutionLayer(n_out=6, kernel_size=(3, 3),
                                    stride=(1, 1), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    padding="VALID", activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=4))
            .set_input_type(InputType.convolutional(10, 10, 3)).build())
    return MultiLayerNetwork(conf).init()


def _lstm_net(impl, tbptt=0, seed=11):
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
         .kernel_impl(impl))
    if tbptt:
        b = b.tbptt_length(tbptt)
    conf = (b.list()
            .layer(LSTM(n_in=6, n_out=12))
            .layer(RnnOutputLayer(n_in=12, n_out=6))
            .set_input_type(InputType.recurrent(6, 8)).build())
    return MultiLayerNetwork(conf).init()


# ---------------------------------------------------------------------------
# fused LSTM cell / sequence
# ---------------------------------------------------------------------------


class TestFusedLstm:
    def _exact_seq(self, xp, h0, c0, U):
        def step(carry, xt):
            h, c = carry
            z = xt + h @ U
            i, f, o, g = jnp.split(z, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (hf, cf), ys = lax.scan(step, (h0, c0), xp)
        return ys, (hf, cf)

    def test_cell_and_sequence_match_exact(self):
        T, B, H = 5, 3, 8
        xp = jnp.asarray(R.normal(size=(T, B, 4 * H)).astype(np.float32))
        h0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32))
        c0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32))
        U = jnp.asarray((R.normal(size=(H, 4 * H)) * 0.3).astype(np.float32))
        ys, (hf, cf) = klstm.lstm_sequence_fused(
            xp, h0, c0, U, klstm.ORDER_IFOG, "interpret")
        ye, (he, ce) = self._exact_seq(xp, h0, c0, U)
        assert _max_err(ys, ye) < 2e-5
        assert _max_err(cf, ce) < 2e-5

        lk = lambda *a: jnp.sum(jnp.cos(klstm.lstm_sequence_fused(  # noqa
            *a, klstm.ORDER_IFOG, "interpret")[0]))
        le = lambda *a: jnp.sum(jnp.cos(self._exact_seq(*a)[0]))  # noqa
        gk = jax.grad(lk, argnums=(0, 1, 2, 3))(xp, h0, c0, U)
        ge = jax.grad(le, argnums=(0, 1, 2, 3))(xp, h0, c0, U)
        assert _max_err(list(gk), list(ge)) < 2e-4

    @pytest.mark.parametrize("b_tile", [2, 3])
    def test_batch_tiled_cell_matches_exact(self, b_tile):
        """Tuned batch-tile parameterization (ISSUE 11 satellite):
        equivalence re-proven at two NON-DEFAULT tile points, values and
        gradients, through the whole scan-fused sequence path."""
        T, B, H = 4, 6, 8
        xp = jnp.asarray(R.normal(size=(T, B, 4 * H)).astype(np.float32))
        h0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32))
        c0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32))
        U = jnp.asarray((R.normal(size=(H, 4 * H)) * 0.3).astype(np.float32))
        assert klstm.valid_b_tile(B, b_tile)
        ys, (hf, cf) = klstm.lstm_sequence_fused(
            xp, h0, c0, U, klstm.ORDER_IFOG, "interpret", b_tile)
        ye, (he, ce) = self._exact_seq(xp, h0, c0, U)
        assert _max_err(ys, ye) < 2e-5
        assert _max_err(cf, ce) < 2e-5

        lk = lambda *a: jnp.sum(jnp.cos(klstm.lstm_sequence_fused(  # noqa
            *a, klstm.ORDER_IFOG, "interpret", b_tile)[0]))
        le = lambda *a: jnp.sum(jnp.cos(self._exact_seq(*a)[0]))  # noqa
        gk = jax.grad(lk, argnums=(0, 1, 2, 3))(xp, h0, c0, U)
        ge = jax.grad(le, argnums=(0, 1, 2, 3))(xp, h0, c0, U)
        assert _max_err(list(gk), list(ge)) < 2e-4
        with pytest.raises(ValueError, match="b_tile"):
            klstm.lstm_cell_fused(xp[0], h0, c0, U, klstm.ORDER_IFOG,
                                  "interpret", 4)

    @pytest.mark.slow
    def test_layer_masked_equivalence(self):
        """nn.recurrent.LSTM with a ragged (B,T) mask: pallas == exact for
        values and gradients (mask passthrough stays in the shared _scan)."""
        from deeplearning4j_tpu.nn.recurrent import LSTM

        lyr = LSTM(n_in=5, n_out=8)
        p, _ = lyr.initialize(jax.random.PRNGKey(0), (None, 5))
        x = jnp.asarray(R.normal(size=(3, 6, 5)).astype(np.float32))
        mask = jnp.asarray((R.random((3, 6)) > 0.3).astype(np.float32))

        def loss(p, impl):
            with K.impl_scope(impl):
                y, _ = lyr.apply_seq(p, x, lyr.init_carry(3), mask=mask)
            return jnp.sum(jnp.sin(y))

        with K.impl_scope("exact"):
            ye, _ = lyr.apply_seq(p, x, lyr.init_carry(3), mask=mask)
        with K.impl_scope("pallas"):
            yp, _ = lyr.apply_seq(p, x, lyr.init_carry(3), mask=mask)
        assert _max_err(yp, ye) < 2e-5
        ge = jax.grad(loss)(p, "exact")
        gp = jax.grad(loss)(p, "pallas")
        assert _max_err(_leaves(gp), _leaves(ge)) < 2e-4

    def test_onnx_lstm_layer_op(self):
        """ops.rnn.lstm_layer (ONNX i,o,f,c gate order + seq_lens) under
        forced pallas == exact."""
        from deeplearning4j_tpu.ops import rnn as rnnops

        T, B, I, H = 6, 3, 5, 7
        x = jnp.asarray(R.normal(size=(T, B, I)).astype(np.float32))
        W = jnp.asarray((R.normal(size=(1, 4 * H, I)) * 0.3)
                        .astype(np.float32))
        Rw = jnp.asarray((R.normal(size=(1, 4 * H, H)) * 0.3)
                         .astype(np.float32))
        b = jnp.asarray((R.normal(size=(1, 8 * H)) * 0.3).astype(np.float32))
        sl = jnp.asarray([6, 4, 2])
        Ye, Yhe, Yce = rnnops.lstm_layer(x, W, Rw, b, sl, hidden_size=H)
        with K.impl_scope("pallas"):
            Yp, Yhp, Ycp = rnnops.lstm_layer(x, W, Rw, b, sl, hidden_size=H)
        assert _max_err(Yp, Ye) < 2e-5
        assert _max_err(Ycp, Yce) < 2e-5

    @pytest.mark.slow
    def test_tbptt_full_fit_trajectory(self):
        """TBPTT-segmented LSTM fit (carries across segments, update per
        segment): pallas trajectory tracks exact within 1e-4."""
        traj = {}
        x = R.normal(size=(4, 8, 6)).astype(np.float32)
        y = np.eye(6, dtype=np.float32)[
            np.random.default_rng(9).integers(0, 6, (4, 8))]
        for impl in ("exact", "pallas"):
            net = _lstm_net(impl, tbptt=4)
            for _ in range(3):
                net._fit_batch(x, y)
            traj[impl] = _leaves(net.params)
        for a, b in zip(traj["exact"], traj["pallas"]):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_exotic_activation_falls_back(self):
        """Non-default cell activations have no kernel: supports() is
        False, so forced pallas silently takes the exact path (same
        numbers, no error)."""
        from deeplearning4j_tpu.nn.recurrent import LSTM

        lyr = LSTM(n_in=4, n_out=6, activation="softsign")
        p, _ = lyr.initialize(jax.random.PRNGKey(1), (None, 4))
        x = jnp.asarray(R.normal(size=(2, 5, 4)).astype(np.float32))
        with K.impl_scope("exact"):
            ye, _ = lyr.apply_seq(p, x, lyr.init_carry(2))
        with K.impl_scope("pallas"):
            yp, _ = lyr.apply_seq(p, x, lyr.init_carry(2))
        np.testing.assert_array_equal(np.asarray(ye), np.asarray(yp))


# ---------------------------------------------------------------------------
# fused donated optimizer apply
# ---------------------------------------------------------------------------


class TestFusedOptimizer:
    @pytest.mark.parametrize("updater_name", ["sgd", "adam", "nesterovs",
                                              "rmsprop"])
    def test_bit_trajectory_vs_per_leaf(self, updater_name):
        from deeplearning4j_tpu.nn.updaters import (Adam, Nesterovs, RmsProp,
                                                    Sgd)

        U = {"sgd": Sgd(0.1), "adam": Adam(1e-3),
             "nesterovs": Nesterovs(0.05), "rmsprop": RmsProp(0.01)}[
            updater_name]
        x = R.normal(size=(8, 10, 10, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(1).integers(0, 4, 8)]
        a = _conv_net("exact", fused=False, updater=U)
        b = _conv_net("exact", fused=True, updater=U)
        for _ in range(5):
            a._fit_batch(x, y)
            b._fit_batch(x, y)
        for p, q in zip(_leaves(a.params), _leaves(b.params)):
            np.testing.assert_array_equal(p, q)
        assert float(a.score_value) == float(b.score_value)

    def test_zero_sharded_fused_matches_per_leaf(self):
        """ParallelWrapper + ZeRO over the fused flat buffers == the
        per-leaf wrapper fit (the gspmd.apply_updaters engine branch)."""
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh

        n_dev = min(len(jax.devices()), 8)
        x = R.normal(size=(16, 12)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(2).integers(0, 4, 16)]

        def run(fused):
            from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                               NeuralNetConfiguration)
            from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
            from deeplearning4j_tpu.nn.updaters import Adam

            b = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3)))
            if fused:
                b = b.fused_update(True)
            conf = (b.list()
                    .layer(DenseLayer(n_in=12, n_out=32, activation="relu"))
                    .layer(OutputLayer(n_in=32, n_out=4))
                    .set_input_type(InputType.feed_forward(12)).build())
            net = MultiLayerNetwork(conf).init()
            pw = ParallelWrapper(
                net, mesh=TrainingMesh(data=n_dev),
                zero_optimizer=True, skew_every=0)
            pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=3)
            return _leaves(net.params)

        for p, q in zip(run(False), run(True)):
            np.testing.assert_allclose(p, q, rtol=1e-6, atol=1e-7)

    def test_bf16_master_weights(self):
        """bf16 param groups accumulate in an fp32 master: many tiny
        updates that individually round to zero in bf16 must still move
        the params (the mixed-precision raison d'être)."""
        from deeplearning4j_tpu.nn.updaters import FusedUpdateEngine, Sgd

        params = [{"w": jnp.ones((64,), jnp.bfloat16)}]
        grads = [{"w": jnp.full((64,), 1e-4, jnp.bfloat16)}]
        eng = FusedUpdateEngine([Sgd(0.1)], params)
        state = eng.init_state(params)
        assert state["groups"][0]["master"].dtype == jnp.float32
        p = params
        for it in range(200):
            p, state = eng.apply(p, grads, state, jnp.asarray(it))
        # 200 * 0.1 * 1e-4 = 2e-3 drop; a bf16-only accumulator would stay
        # at exactly 1.0 (1.0 - 1e-5 rounds back to 1.0 in bf16). The
        # buffer pads to 512 elements — only the real 64 carry params.
        master = np.asarray(state["groups"][0]["master"])[:64]
        np.testing.assert_allclose(master, 1.0 - 2e-3, rtol=1e-3)
        assert float(p[0]["w"][0].astype(jnp.float32)) < 1.0

    def test_dynamic_loss_scale_step_skip_and_growth(self):
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
                .fused_update(True)
                .loss_scale("dynamic", value=2.0 ** 8, growth_interval=3)
                .list()
                .layer(DenseLayer(n_in=12, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=4))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf).init()
        x = R.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(3).integers(0, 4, 8)]
        assert float(net.opt_states["scale"]["scale"]) == 2.0 ** 8
        # poisoned batch: the step must be SKIPPED (params bit-unchanged)
        # and the scale halved
        xn = x.copy()
        xn[0, 0] = np.nan
        before = _leaves(net.params)
        net._fit_batch(xn, y)
        after = _leaves(net.params)
        for p, q in zip(before, after):
            np.testing.assert_array_equal(p, q)
        assert float(net.opt_states["scale"]["scale"]) == 2.0 ** 7
        # 3 clean steps: params move and the scale grows back
        net._fit_batch(x, y)
        moved = _leaves(net.params)
        assert any(not np.array_equal(p, q) for p, q in zip(after, moved))
        net._fit_batch(x, y)
        net._fit_batch(x, y)
        assert float(net.opt_states["scale"]["scale"]) == 2.0 ** 8

    def test_static_scale_matches_unscaled(self):
        """Static loss scaling is numerically transparent for fp32: the
        scaled-then-unscaled trajectory tracks the unscaled one."""
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd

        def build(policy):
            b = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
                 .fused_update(True))
            if policy:
                b = b.loss_scale("static", value=2.0 ** 10)
            conf = (b.list()
                    .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
                    .layer(OutputLayer(n_in=16, n_out=4))
                    .set_input_type(InputType.feed_forward(12)).build())
            return MultiLayerNetwork(conf).init()

        x = R.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(4).integers(0, 4, 8)]
        a, b = build(False), build(True)
        for _ in range(4):
            a._fit_batch(x, y)
            b._fit_batch(x, y)
        for p, q in zip(_leaves(a.params), _leaves(b.params)):
            np.testing.assert_allclose(p, q, rtol=1e-5, atol=1e-6)
        # the reported loss is the UNSCALED one
        np.testing.assert_allclose(float(a.score_value),
                                   float(b.score_value), rtol=1e-5)

    def test_loss_scale_requires_fused(self):
        from deeplearning4j_tpu.nn import NeuralNetConfiguration

        with pytest.raises(ValueError, match="fused_update"):
            NeuralNetConfiguration.builder().loss_scale("dynamic")

    def test_conf_json_round_trip(self):
        from deeplearning4j_tpu.nn import (InputType,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

        conf = (NeuralNetConfiguration.builder().seed(1)
                .kernel_impl("pallas").fused_update(True)
                .loss_scale("dynamic", value=1024.0, growth_interval=7)
                .list()
                .layer(DenseLayer(n_in=4, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        rt = MultiLayerConfiguration.from_json(conf.to_json())
        assert rt.kernel_impl == "pallas"
        assert rt.fused_update is True
        assert rt.loss_scale == "dynamic"
        assert rt.loss_scale_value == 1024.0
        assert rt.loss_scale_growth == 7

    def test_fused_state_serializes(self, tmp_path):
        """ModelSerializer round-trips the fused optimizer state (the flat
        buffers + scale automaton are ordinary pytree leaves)."""
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        net = _conv_net("exact", fused=True)
        x = R.normal(size=(8, 10, 10, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(6).integers(0, 4, 8)]
        net._fit_batch(x, y)
        path = str(tmp_path / "fused.zip")
        ModelSerializer.write_model(net, path, save_updater=True)
        restored = ModelSerializer.restore_multi_layer_network(
            path, load_updater=True)
        for p, q in zip(_leaves(net.opt_states),
                        _leaves(restored.opt_states)):
            np.testing.assert_array_equal(p, q)
        # both continue to the SAME next step
        net._fit_batch(x, y)
        restored._fit_batch(x, y)
        for p, q in zip(_leaves(net.params), _leaves(restored.params)):
            np.testing.assert_array_equal(p, q)

    def test_restore_without_updater_state_resyncs_masters(self, tmp_path):
        """Loading a fused model WITHOUT updater state must resync the
        resident master buffers to the loaded params — otherwise the first
        fit() step snaps the trained weights back to init()'s randoms
        (review finding, r14)."""
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        net = _conv_net("exact", fused=True)
        x = R.normal(size=(8, 10, 10, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[
            np.random.default_rng(8).integers(0, 4, 8)]
        for _ in range(3):
            net._fit_batch(x, y)
        path = str(tmp_path / "fused_no_upd.zip")
        ModelSerializer.write_model(net, path, save_updater=False)
        restored = ModelSerializer.restore_multi_layer_network(path)
        trained = _leaves(restored.params)
        for t, p in zip(trained, _leaves(net.params)):
            np.testing.assert_array_equal(t, p)
        restored._fit_batch(x, y)
        # one fresh-moment Adam step moves params ~lr; a master desync
        # would jump them all the way back to the random init (~0.1)
        for t, a in zip(trained, _leaves(restored.params)):
            assert float(np.max(np.abs(t - a))) < 0.02


# ---------------------------------------------------------------------------
# flash-attention padding mask (satellite 1)
# ---------------------------------------------------------------------------


class TestFlashPaddingMask:
    def _qkv(self, B=2, H=3, S=16, D=8):
        mk = lambda: jnp.asarray(  # noqa: E731
            R.normal(size=(B, H, S, D)).astype(np.float32))
        mask = np.ones((B, S), np.float32)
        mask[0, 10:] = 0.0
        mask[1, 3:] = 0.0
        return mk(), mk(), mk(), jnp.asarray(mask)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("use_pallas", ["interpret", False],
                             ids=["pallas-interpret", "jnp-blockwise"])
    def test_masked_matches_exact(self, causal, use_pallas):
        from deeplearning4j_tpu.ops.attention import (dot_product_attention,
                                                      flash_attention)

        q, k, v, mask = self._qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                              use_pallas=use_pallas, mask=mask)
        ref = dot_product_attention(q, k, v, mask=mask[:, None, None, :],
                                    causal=causal)
        assert _max_err(out, ref) < 2e-5

    def test_masked_gradients_match_exact(self):
        from deeplearning4j_tpu.ops.attention import (dot_product_attention,
                                                      flash_attention)

        q, k, v, mask = self._qkv()
        f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(  # noqa: E731
            q, k, v, block_q=8, block_k=8, use_pallas="interpret",
            mask=mask)))
        f2 = lambda q, k, v: jnp.sum(jnp.sin(dot_product_attention(  # noqa
            q, k, v, mask=mask[:, None, None, :])))
        g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        assert _max_err(list(g1), list(g2)) < 2e-4

    def test_resolve_flash_accepts_padding_masks(self):
        from deeplearning4j_tpu.ops.attention import resolve_flash

        pad = jnp.ones((2, 16))
        full = jnp.ones((2, 1, 16, 16))
        assert resolve_flash(True, 16, 16, pad) is True
        assert resolve_flash(True, 16, 16, full) is False

    def test_mha_masked_flash_vs_exact(self):
        from deeplearning4j_tpu.ops.attention import (
            multi_head_dot_product_attention)

        B, T, F, Hh = 2, 16, 24, 4
        xq = jnp.asarray(R.normal(size=(B, T, F)).astype(np.float32))
        Ws = [jnp.asarray((R.normal(size=(F, F)) * 0.2).astype(np.float32))
              for _ in range(4)]
        mask = np.ones((B, T), np.float32)
        mask[0, 9:] = 0.0
        mask = jnp.asarray(mask)
        o_flash = multi_head_dot_product_attention(
            xq, xq, xq, *Ws, n_heads=Hh, mask=mask, flash=True)
        o_exact = multi_head_dot_product_attention(
            xq, xq, xq, *Ws, n_heads=Hh, mask=mask, flash=False)
        assert _max_err(o_flash, o_exact) < 2e-5


# ---------------------------------------------------------------------------
# per-dtype peak FLOPs + optimizer update share (satellites)
# ---------------------------------------------------------------------------


class TestPeakFlopsTable:
    def test_bare_number(self, monkeypatch):
        from deeplearning4j_tpu.util import cost_model as cm

        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1.97e14")
        assert cm.peak_flops_from_env() == 1.97e14
        assert cm.peak_flops_from_env("bfloat16") == 1.97e14

    def test_dtype_table(self, monkeypatch):
        from deeplearning4j_tpu.util import cost_model as cm

        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS",
                           "bf16=1.97e14, fp32=9.85e13")
        assert cm.peak_flops_from_env("bfloat16") == 1.97e14
        assert cm.peak_flops_from_env("bf16") == 1.97e14
        assert cm.peak_flops_from_env("float32") == 9.85e13
        # no dtype: multi-entry table falls back to the fp32 entry
        assert cm.peak_flops_from_env() == 9.85e13
        # unknown dtype: no silent guesses
        assert cm.peak_flops_from_env("int4") is None

    def test_garbage_degrades_to_none(self, monkeypatch):
        from deeplearning4j_tpu.util import cost_model as cm

        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "fast")
        assert cm.peak_flops_from_env("bf16") is None
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "bf16=oops")
        assert cm.peak_flops_from_env("bf16") is None

    def test_mfu_uses_dtype_peak(self, monkeypatch):
        """A bf16 net's cost_report computes MFU against the bf16 entry."""
        from deeplearning4j_tpu.util import cost_model as cm

        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS",
                           "bf16=2e14,fp32=1e14")
        assert cm.peak_flops_from_env("bfloat16") == 2e14

    def test_optimizer_update_share(self):
        from deeplearning4j_tpu.util.cost_model import (OPTIMIZER_ROW,
                                                        CostReport, CostRow)

        rows = [
            CostRow(layer="0_conv", device_time_fwd_s=0.006,
                    device_time_bwd_s=0.012),
            CostRow(layer=OPTIMIZER_ROW, device_time_fwd_s=0.002),
        ]
        rep = CostReport(rows=rows, totals={}, batch=8, params_total=1,
                         source="xla")
        assert abs(rep.optimizer_update_share - 0.1) < 1e-12
        assert rep.to_dict()["optimizer_update_share"] == \
            rep.optimizer_update_share
        # no profiled times -> None, never a guess
        rep2 = CostReport(rows=[CostRow(layer="0_conv")], totals={},
                          batch=8, params_total=1, source="xla")
        assert rep2.optimizer_update_share is None
