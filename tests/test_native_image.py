"""Native image pipeline: decode correctness vs PIL, async batching,
ImageRecordReader integration, throughput measurement (VERDICT r1 weak #3 /
next #6)."""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu import native

pytestmark = pytest.mark.skipif(
    not native.image_available(),
    reason=f"native image decode unavailable: {native.build_error()}")


def _make_corpus(tmp_path, n_per_class=6, size=(64, 48), fmt="JPEG"):
    from PIL import Image

    rng = np.random.default_rng(0)
    items = []
    for ci, cls in enumerate(("cats", "dogs")):
        d = tmp_path / cls
        d.mkdir(exist_ok=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 255, size=(size[1], size[0], 3),
                               dtype=np.uint8)
            p = str(d / f"img{i}.{'jpg' if fmt == 'JPEG' else 'png'}")
            Image.fromarray(arr).save(p, fmt, quality=95)
            items.append((p, ci))
    return items


class TestDecode:
    @pytest.mark.parametrize("fmt", ["JPEG", "PNG"])
    def test_matches_pil_at_native_size(self, tmp_path, fmt):
        from PIL import Image

        items = _make_corpus(tmp_path, n_per_class=2, fmt=fmt)
        path = items[0][0]
        pil = np.asarray(Image.open(path).convert("RGB"), np.float32)
        got = native.decode_image_file(path, pil.shape[0], pil.shape[1], 3)
        # same libjpeg underneath → exact for PNG, near-exact for JPEG
        assert np.abs(got - pil).mean() < 1.0, np.abs(got - pil).mean()

    def test_grayscale(self, tmp_path):
        items = _make_corpus(tmp_path, n_per_class=1)
        out = native.decode_image_file(items[0][0], 24, 24, 1)
        assert out.shape == (24, 24, 1) and np.isfinite(out).all()

    def test_resize_plausible(self, tmp_path):
        from PIL import Image

        # smooth gradient: point-sampling bilinear and PIL's area-averaging
        # filter agree on smooth content (they diverge on per-pixel noise)
        g = np.stack(np.meshgrid(np.linspace(0, 255, 48),
                                 np.linspace(0, 255, 64),
                                 indexing="ij"), -1)
        arr = np.concatenate([g, g[..., :1]], axis=-1).astype(np.uint8)
        path = str(tmp_path / "grad.png")
        Image.fromarray(arr).save(path, "PNG")
        got = native.decode_image_file(path, 24, 32, 3)
        ref = np.asarray(Image.open(path).convert("RGB")
                         .resize((32, 24), Image.BILINEAR), np.float32)
        assert np.abs(got[2:-2, 2:-2] - ref[2:-2, 2:-2]).mean() < 6.0

    def test_undecodable_raises(self, tmp_path):
        p = str(tmp_path / "junk.jpg")
        with open(p, "wb") as f:
            f.write(b"not an image at all")
        with pytest.raises(ValueError):
            native.decode_image_file(p, 8, 8, 3)


class TestAsyncPipeline:
    def test_batches_cover_corpus(self, tmp_path):
        items = _make_corpus(tmp_path, n_per_class=6)
        pipe = native.AsyncImagePipeline(
            [p for p, _ in items], [l for _, l in items],
            height=32, width=32, channels=3, batch=5)
        seen = []
        for x, labels, idx in pipe:
            assert x.shape[1:] == (32, 32, 3)
            assert np.isfinite(x).all()
            seen.extend(idx.tolist())
            for j, i in enumerate(idx):
                assert labels[j] == items[i][1]
        assert sorted(seen) == list(range(len(items)))

    def test_failed_files_skipped_and_counted(self, tmp_path):
        items = _make_corpus(tmp_path, n_per_class=3)
        bad = str(tmp_path / "bad.jpg")
        with open(bad, "wb") as f:
            f.write(b"garbage")
        paths = [p for p, _ in items] + [bad]
        labels = [l for _, l in items] + [0]
        pipe = native.AsyncImagePipeline(paths, labels, height=16, width=16,
                                         channels=3, batch=4)
        n = sum(len(x) for x, _, _ in pipe)
        assert n == len(items)
        assert pipe.failed == 1


class TestIteratorIntegration:
    def test_dataset_iterator_from_directory(self, tmp_path):
        from deeplearning4j_tpu.data import AsyncImageDataSetIterator

        _make_corpus(tmp_path, n_per_class=6)
        it = AsyncImageDataSetIterator(root=str(tmp_path), height=32, width=32,
                                       channels=3, batch=4)
        total = 0
        for ds in it:
            assert ds.features.shape[1:] == (32, 32, 3)
            assert ds.features.max() <= 1.0 + 1e-6  # scaled
            assert ds.labels.shape[1] == 2
            total += len(ds.features)
        assert total == 12
        # second epoch after reset covers the corpus again
        assert sum(len(d.features) for d in it) == 12
        it.close()

    def test_image_record_reader_uses_native(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.datavec import ImageRecordReader

        items = _make_corpus(tmp_path, n_per_class=2)
        rr = ImageRecordReader(height=20, width=20, channels=3,
                               paths_labels=items)
        rec = next(iter(rr))
        assert rec[0].shape == (20, 20, 3)


@pytest.mark.slow
def test_throughput_report(tmp_path):
    """Measure and print pipeline throughput on a synthetic 224x224 JPEG
    corpus (recorded in BASELINE.md; the >=3k img/s target from VERDICT
    assumes a multi-core host — this CI box has ONE core)."""
    from PIL import Image

    rng = np.random.default_rng(0)
    paths = []
    for i in range(64):
        arr = rng.integers(0, 255, size=(224, 224, 3), dtype=np.uint8)
        p = str(tmp_path / f"i{i}.jpg")
        Image.fromarray(arr).save(p, "JPEG", quality=90)
        paths.append(p)
    t0 = time.perf_counter()
    pipe = native.AsyncImagePipeline(paths * 4, [0] * len(paths) * 4,
                                     height=224, width=224, channels=3,
                                     batch=32, n_threads=os.cpu_count() or 2)
    n = sum(len(x) for x, _, _ in pipe)
    dt = time.perf_counter() - t0
    print(f"\nnative image pipeline: {n / dt:.0f} img/s "
          f"({os.cpu_count()} cores)")
    assert n == len(paths) * 4


class TestAsyncPrefetchOverlap:
    """VERDICT r3 weak #5: prove the async pipeline actually DECOUPLES
    decode from consumption. On this 1-core host true parallel overlap is
    physically impossible (decode threads and XLA compute share the core —
    BASELINE.md documents the ceiling), so the honest testable invariant is
    the mechanism that yields overlap on real hosts: the C++ threads decode
    AUTONOMOUSLY (no consumer driving them) into the prefetch buffer, and a
    consumer that was busy elsewhere then drains batches at buffer speed,
    not decode speed. The chip-side wall-time comparison (async-fed vs
    device-resident train steps on the real TPU, where host decode genuinely
    overlaps device compute) is recorded in BASELINE.md."""

    N, HW, BATCH = 64, 48, 16

    def _mk_files(self, tmp_path, rng):
        from PIL import Image

        paths = []
        for i in range(self.N):
            arr = (rng.random((self.HW, self.HW, 3)) * 255).astype(np.uint8)
            p = str(tmp_path / f"ov{i}.jpg")
            Image.fromarray(arr).save(p, "JPEG", quality=90)
            paths.append((p, i % 4))
        return paths

    def test_prefetch_is_autonomous_and_buffer_bounded(self, tmp_path, rng):
        import time

        from deeplearning4j_tpu.data.image_iterator import (
            AsyncImageDataSetIterator,
        )

        items = self._mk_files(tmp_path, rng)

        def drain(it):
            t0 = time.perf_counter()
            n = 0
            for ds in it:
                n += ds.features.shape[0]
            return time.perf_counter() - t0, n

        # 1) demand-driven decode time (consumer drains immediately)
        it1 = AsyncImageDataSetIterator(
            items, height=self.HW, width=self.HW, batch=self.BATCH,
            n_threads=2, prefetch=self.N)
        t_decode, n1 = drain(it1)
        it1.close()
        assert n1 == self.N

        # 2) autonomous prefetch: start the pipeline, let the consumer be
        # "busy" (idle here — the core is free for the decode threads, as it
        # is on a real host while the accelerator computes), then drain.
        it2 = AsyncImageDataSetIterator(
            items, height=self.HW, width=self.HW, batch=self.BATCH,
            n_threads=2, prefetch=self.N)
        iter(it2)
        next(it2)  # force pipeline start
        time.sleep(max(0.5, 3.0 * t_decode))  # decode proceeds unaided
        t0 = time.perf_counter()
        n2 = self.BATCH
        try:
            while True:
                ds = next(it2)
                n2 += ds.features.shape[0]
        except StopIteration:
            pass
        t_drain = time.perf_counter() - t0
        it2.close()
        assert n2 == self.N
        # buffer-bounded: draining pre-decoded batches must be much faster
        # than decoding them was (0.5 = generous CI margin; measured ~0.1)
        assert t_drain < max(0.5 * t_decode, 0.05), (t_drain, t_decode)
