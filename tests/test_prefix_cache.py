"""Shared-prefix KV reuse + chunked prefill (ISSUE 16).

The acceptance contracts: prefix-shared decode TOKEN-IDENTICAL to the
unshared paged path and the O(T²) recompute oracle — greedy, sampled
(stream-exact) and speculative — across cold cache, warm cache and the
COW-split case (block-aligned full-prompt hit); eos early-exit and
rollback decrement refcounts instead of freeing shared blocks; block
refcount conservation holds across randomized interleavings of (admit,
share, COW-split, eos, rollback, pool-grow, exception-reset) and is
asserted by the health probe; chunked prefill is window-width-invariant;
mixed hit/miss + chunked traffic traces NOTHING after warmup."""

import random

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.serving import (BatchScheduler, BlockPool,
                                        Generator, PrefixCache,
                                        ServingModel)
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import get_watcher
from deeplearning4j_tpu.zoo.bert import Bert

VOCAB = 43
MAXLEN = 32
BUCKETS = dict(batch_buckets=(1, 2, 4), prefill_buckets=(8, 16))

#: a 9-token shared "system prompt" (crosses two block_size=4 pages) plus
#: per-stream suffixes — the serving traffic shape the radix cache exists
#: for
SYSTEM = [5, 6, 7, 8, 9, 10, 11, 12, 13]
SHARED = [SYSTEM + [20, 21], SYSTEM + [22, 23, 24], SYSTEM + [25]]
RAGGED = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10, 11, 12, 13, 14, 15, 16, 17]]


@pytest.fixture(scope="module")
def target_net():
    return Bert.tiny(causal=True, task="mlm", vocab_size=VOCAB,
                     max_length=MAXLEN, hidden_dropout=0.0).init()


@pytest.fixture(scope="module")
def draft_net():
    return Bert.draft(vocab_size=VOCAB, max_length=MAXLEN, seed=7).init()


@pytest.fixture(scope="module")
def gen_contiguous(target_net):
    return Generator(target_net, paged=False, **BUCKETS)


@pytest.fixture(scope="module")
def gen_prefix(target_net):
    return Generator(target_net, paged=True, block_size=4,
                     prefix_cache=True, **BUCKETS)


@pytest.fixture(scope="module")
def gen_both(target_net):
    return Generator(target_net, paged=True, block_size=4,
                     prefix_cache=True, prefill_chunk=8, **BUCKETS)


def _conserved(gen):
    ok, detail = gen.pool.conservation()
    assert ok, detail
    if gen.cache is not None:
        ok, detail = gen.cache.check()
        assert ok, detail


class TestPrefixIdentity:
    def test_cold_and_warm_identity(self, gen_prefix, gen_contiguous):
        """The acceptance bit: cold-cache (miss) AND warm-cache (shared
        blocks, resumed prefill) greedy decode == contiguous == O(T²)
        recompute, token-for-token."""
        ref = gen_contiguous.generate(SHARED, max_new_tokens=8)
        cold_stats, warm_stats = {}, {}
        cold = gen_prefix.generate(SHARED, max_new_tokens=8,
                                   stats=cold_stats)
        warm = gen_prefix.generate(SHARED, max_new_tokens=8,
                                   stats=warm_stats)
        assert cold == ref == warm
        assert warm == gen_contiguous.generate_full_recompute(
            SHARED, max_new_tokens=8)
        # warm run resumed past the shared full blocks
        assert warm_stats["prefix_hit_rate"] > 0
        assert any(p > 0 for p in warm_stats["resumed_positions"])
        _conserved(gen_prefix)

    def test_sampled_identity_stream_exact(self, gen_prefix,
                                           gen_contiguous):
        """temperature>0 on a WARM cache: resumed prefill consumes the
        same key stream, so sampled output is identical too."""
        gen_prefix.generate(SHARED, max_new_tokens=4)  # warm the trie
        key = jax.random.PRNGKey(11)
        a = gen_prefix.generate(SHARED, max_new_tokens=6, temperature=0.7,
                                key=key)
        b = gen_contiguous.generate(SHARED, max_new_tokens=6,
                                    temperature=0.7, key=key)
        assert a == b
        _conserved(gen_prefix)

    def test_cow_split_on_block_aligned_hit(self, gen_prefix,
                                            gen_contiguous):
        """A prompt that is EXACTLY full blocks fully hits the trie; the
        last block must be COW-split (decode writes into it) — identity
        preserved, split counted, nothing double-freed."""
        prompt = [[3, 4, 5, 6, 7, 8, 9, 10]]  # 8 = 2 whole blocks
        ref = gen_contiguous.generate(prompt, max_new_tokens=6)
        before = tm.get_telemetry().counter_total(
            "serving.prefix_cow_splits_total")
        first = gen_prefix.generate(prompt, max_new_tokens=6)
        second = gen_prefix.generate(prompt, max_new_tokens=6)  # COW here
        after = tm.get_telemetry().counter_total(
            "serving.prefix_cow_splits_total")
        assert first == ref == second
        assert after > before
        _conserved(gen_prefix)

    def test_eos_early_exit_decrefs_shared_blocks(self, gen_prefix,
                                                  gen_contiguous):
        """The satellite bugfix: eos early-exit on a stream whose prefix
        blocks are SHARED with the trie must decref, not free — the trie
        keeps serving the prefix afterwards, conservation intact."""
        gen_prefix.generate(SHARED, max_new_tokens=8)  # warm
        ref = gen_contiguous.generate([SHARED[0]], max_new_tokens=8)
        eos = ref[0][2]
        out = gen_prefix.generate([SHARED[0]], max_new_tokens=8,
                                  eos_id=eos)
        assert out[0] == ref[0][:ref[0].index(eos) + 1]
        _conserved(gen_prefix)
        # the prefix is still cached and still correct
        warm = gen_prefix.generate(SHARED, max_new_tokens=8,
                                   stats=(st := {}))
        assert warm == gen_contiguous.generate(SHARED, max_new_tokens=8)
        assert st["prefix_hit_rate"] > 0

    @pytest.mark.slow
    def test_speculative_identity(self, target_net, draft_net,
                                  gen_contiguous):
        """Speculative decode over a warm prefix cache: rollback of
        rejected draft tokens never touches shared blocks; output equals
        plain greedy cold AND warm."""
        gen = Generator(target_net, paged=True, block_size=4,
                        prefix_cache=True, draft_net=draft_net,
                        spec_tokens=3, **BUCKETS)
        ref = gen_contiguous.generate(SHARED, max_new_tokens=8)
        assert gen.generate(SHARED, max_new_tokens=8) == ref
        assert gen.generate(SHARED, max_new_tokens=8) == ref
        _conserved(gen)


class TestChunkedPrefill:
    def test_chunk_width_invariant(self, target_net, gen_contiguous):
        """Chunked prefill is pure mechanism: every window width yields
        the same tokens as the whole-prompt prefill."""
        ref = gen_contiguous.generate(RAGGED, max_new_tokens=6)
        gen = Generator(target_net, paged=True, block_size=4,
                        prefill_chunk=4, **BUCKETS)
        stats = {}
        out = gen.generate(RAGGED, max_new_tokens=6, stats=stats)
        assert out == ref
        assert stats["prefill_chunks"] >= 2  # 9-token prompt, 4-wide
        _conserved(gen)

    @pytest.mark.slow  # tier-1 budget: covered by chunk-width invariance
    def test_chunked_plus_cache_identity(self, gen_both, gen_contiguous):
        """Both features together: chunked prefill resuming from a warm
        prefix — cold == warm == oracle."""
        long = [SYSTEM + list(range(14, 14 + 9)),
                SYSTEM + list(range(23, 23 + 7))]
        ref = gen_contiguous.generate(long, max_new_tokens=6)
        before = tm.get_telemetry().counter_total(
            "serving.chunked_prefill_chunks_total")
        cold = gen_both.generate(long, max_new_tokens=6)
        warm = gen_both.generate(long, max_new_tokens=6, stats=(st := {}))
        after = tm.get_telemetry().counter_total(
            "serving.chunked_prefill_chunks_total")
        assert cold == ref == warm
        assert st["prefix_hit_rate"] > 0
        assert after > before
        _conserved(gen_both)

    @pytest.mark.slow  # tier-1 budget: decode_smoke asserts this over HTTP
    def test_zero_steady_state_recompiles_mixed_traffic(self, gen_both):
        """The compile-once substrate survives the new machinery: after
        warmup, mixed hit/miss/chunked/ragged traffic traces NOTHING."""
        gen_both.warmup()
        w = get_watcher()
        with w.scope() as s:
            gen_both.generate(SHARED, max_new_tokens=4)      # mixed hit
            gen_both.generate(SHARED, max_new_tokens=4)      # full hit
            gen_both.generate([[40, 41, 42]], max_new_tokens=4)  # miss
            gen_both.generate([SYSTEM + list(range(14, 30))],
                              max_new_tokens=4)              # chunked
            gen_both.generate(RAGGED, max_new_tokens=4)
        assert s.traces == 0, f"steady-state traced {s.traces}x"
        _conserved(gen_both)


class TestRefcountConservation:
    def test_property_random_interleavings(self, gen_prefix):
        """The satellite property test, on the accounting layer directly:
        hundreds of random (admit, share, COW-split, eos/finish,
        rollback, evict, pool-grow, exception-reset) interleavings, with
        pool conservation AND trie consistency asserted after EVERY op."""
        rng = random.Random(1234)
        net_blocks = gen_prefix.blocks
        pool = BlockPool(net_blocks, block_size=4, num_blocks=12,
                         max_length=MAXLEN)
        cache = PrefixCache(pool)
        prefixes = [tuple(SYSTEM), tuple(range(1, 9)), (30, 31, 32, 33)]
        active = []  # (table, pending_nodes)

        def check():
            ok, detail = pool.conservation()
            assert ok, detail
            ok, detail = cache.check()
            assert ok, detail

        def admit():
            base = list(rng.choice(prefixes))
            tokens = base + [rng.randrange(1, VOCAB)
                             for _ in range(rng.randrange(0, 4))]
            need = pool.blocks_needed(len(tokens), 4)
            with pool._lock:
                blocks, committed = cache.match(tokens)
                try:
                    table = blocks + pool.reserve(
                        [need - len(blocks)])[0]
                except Exception:
                    pool.decref(blocks)
                    return
                if committed and committed == len(tokens):
                    bi = (committed - 1) // pool.block_size
                    try:
                        table[bi] = pool.cow_split(table[bi])
                    except Exception:
                        pool.release([table])
                        return
                pending = cache.insert(tokens, table)
            active.append((table, pending))

        def finish():  # eos / normal completion: commit then release
            if not active:
                return
            table, pending = active.pop(rng.randrange(len(active)))
            cache.commit(pending)
            pool.release([table])

        def abort():  # exception path: rollback then release
            if not active:
                return
            table, pending = active.pop(rng.randrange(len(active)))
            cache.rollback(pending)
            pool.release([table])

        def evict():
            cache.evict(rng.randrange(1, 4))

        def grow():  # the _grow transaction: flush, rebind to a new pool
            nonlocal pool
            if active:  # live streams pin the old pool — as in Generator
                return
            cache.flush()
            pool = BlockPool(net_blocks, block_size=4,
                             num_blocks=pool.num_blocks + 4,
                             max_length=MAXLEN)
            cache.rebind(pool)

        def reset():  # the _reset_pools transaction
            while active:
                abort()
            cache.flush()

        ops = [admit, admit, admit, finish, finish, abort, evict, grow,
               reset]
        for _ in range(400):
            rng.choice(ops)()
            check()
        reset()
        check()
        assert pool.free_blocks() == pool.num_blocks

    def test_double_free_detected(self, target_net):
        gen = Generator(target_net, paged=True, block_size=4,
                        pool_blocks=8, **BUCKETS)
        (tbl,) = gen.pool.reserve([1])
        gen.pool.decref(tbl)
        with pytest.raises(ValueError, match="double-free"):
            gen.pool.decref(tbl)

    @pytest.mark.slow  # tier-1 budget: grow op covered by the property test
    def test_pool_grow_flushes_and_rebinds_cache(self, target_net,
                                                 gen_contiguous):
        """Auto-pool growth under prefix caching: the trie is flushed,
        rebound to the grown pool, and keeps caching correctly after."""
        gen = Generator(target_net, paged=True, block_size=4,
                        prefix_cache=True, **BUCKETS)
        gen.pool = type(gen.pool)(gen.blocks, block_size=4, num_blocks=4,
                                  max_length=gen.max_length)
        gen.cache.rebind(gen.pool)
        assert gen._pool_auto
        ref = gen_contiguous.generate(SHARED, max_new_tokens=8)
        out = gen.generate(SHARED, max_new_tokens=8)  # needs > 4 blocks
        assert out == ref
        assert gen.pool.num_blocks > 4
        assert gen.generate(SHARED, max_new_tokens=8) == ref  # re-warms
        _conserved(gen)

    def test_exception_reset_clears_cache(self, gen_prefix,
                                          gen_contiguous):
        """_reset_pools (the exception path) flushes the trie and returns
        every block; the next request rebuilds the cache correctly."""
        gen_prefix.generate(SHARED, max_new_tokens=4)
        assert gen_prefix.cache.stats()["nodes"] > 0
        gen_prefix._reset_pools()
        assert gen_prefix.cache.stats()["nodes"] == 0
        assert gen_prefix.pool.free_blocks() == gen_prefix.pool.num_blocks
        _conserved(gen_prefix)
        assert gen_prefix.generate(SHARED, max_new_tokens=8) == \
            gen_contiguous.generate(SHARED, max_new_tokens=8)


class TestHealthProbe:
    def test_probe_asserts_conservation(self, gen_prefix):
        gen_prefix.generate(SHARED, max_new_tokens=4)
        assert gen_prefix.health_probe()

    def test_probe_catches_refcount_leak(self, target_net):
        """The satellite bugfix's tripwire: a manufactured refcount leak
        (block allocated but unreachable) flips the all-trash probe to
        unhealthy via the conservation check."""
        gen = Generator(target_net, paged=True, block_size=4,
                        pool_blocks=8, prefix_cache=True, **BUCKETS)
        assert gen.health_probe()
        leaked = gen.pool._free.pop()        # vanish a block: allocated
        gen.pool._ref[leaked] = 1            # by nobody, freed by nobody
        try:
            assert not gen.health_probe()
            ok, _ = tm.get_telemetry().health_report()
            assert not ok
        finally:
            del gen.pool._ref[leaked]
            gen.pool._free.append(leaked)
            assert gen.health_probe()


class TestObservability:
    def test_gauges_and_counters(self, gen_prefix):
        gen_prefix.generate(SHARED, max_new_tokens=4)
        gen_prefix.generate(SHARED, max_new_tokens=4)
        t = tm.get_telemetry()
        hits = t.gauge_values("serving.prefix_cache_hit_rate")
        assert hits and hits[-1] > 0
        assert t.gauge_values("serving.prefix_blocks_shared")

    @pytest.mark.slow
    def test_flight_recorder_and_spans_attribution(self, target_net):
        """Per-phase attribution rides the scheduler: flight records and
        trace spans carry prefix_hit_rate / resumed_position /
        prefill_chunks for warm chunked requests."""
        model = ServingModel(target_net, "prefix-m", kind="generate",
                             bucketing="batch=1,2;seq=8,16",
                             max_length=MAXLEN, block_size=4,
                             pool_blocks=64, prefix_cache=True,
                             prefill_chunk=8)
        model.warmup()
        sched = BatchScheduler(model, max_wait_ms=1.0)
        sched.start()
        try:
            prompt = np.asarray(SYSTEM + [20, 21], np.int32)
            sched.submit(prompt, max_new_tokens=4).result(timeout=60)
            fut = sched.submit(prompt, max_new_tokens=4)  # warm: hits
            fut.result(timeout=60)
            rec = sched.flight.dump(last=1)[0]
            assert rec["prefix_hit_rate"] > 0
            assert rec["resumed_position"] > 0
            assert rec["prefill_chunks"] >= 1
        finally:
            sched.shutdown()
