"""Encoded gradient collectives on the DP hot path (ISSUE 10).

What CPU can honestly prove (the r6 convention, docs/DISTRIBUTED.md):

- **Error-feedback conservation, bit-exact**: decode(encode(g, res, t)) +
  new_res == g + res with EXACT float equality — the encoder snaps its
  threshold to a power of two (ops/compression.pow2_floor), which makes the
  residual subtraction exact for every element within 7 decades of the
  threshold.
- **threshold→0 bit-identity**: the compressed wrapper at t=0 (the exact
  identity encode) reproduces the uncompressed deterministic lane fit
  BIT-for-bit — params, Adam moments, RNG key.
- **Deterministic wire accounting**: the wire-bytes/ratio stats are pure
  functions of the data, identical across runs.
- **Convergence parity**: a compressed fit on the same data order reaches
  the exact fit's loss neighborhood (error feedback: nothing is lost, only
  delayed).

What CPU cannot prove: that fewer wire bytes are faster — that ranking
belongs to real ICI/DCN hardware (BENCH record carries the honest A/B).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.ops import compression as C
from deeplearning4j_tpu.parallel import (GradCompressor, ParallelWrapper,
                                         TrainingMesh, gspmd)
from deeplearning4j_tpu.parallel.compression import (resolve_scheme,
                                                     validate_scheme)
from deeplearning4j_tpu.util.checkpoint import (ShardedCheckpointer,
                                                load_tree_npz,
                                                save_tree_npz)


def _mesh8():
    return TrainingMesh(data=8)


def _mesh1():
    return TrainingMesh(data=1, devices=jax.devices()[:1])


def _leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


def _assert_tree_equal(a, b, what):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb), what
    for i, (u, v) in enumerate(zip(la, lb)):
        assert u.shape == v.shape, (what, i)
        assert (u == v).all(), (
            f"{what} leaf {i} differs: maxdiff "
            f"{np.abs(u.astype(np.float64) - v.astype(np.float64)).max()}")


def _dense_conf(comp=None, threshold=1e-3, target=1e-3, fused=False,
                loss_scale=None, updater=None, seed=7):
    b = NeuralNetConfiguration.builder().seed(seed).updater(
        updater or Adam(0.01))
    if fused:
        b = b.fused_update(True)
    if loss_scale:
        b = b.loss_scale(loss_scale)
    if comp:
        b = b.grad_compression(comp, threshold=threshold,
                               target_sparsity=target)
    return (b.list()
            .layer(DenseLayer(n_in=6, n_out=32, activation="relu"))
            .layer(DenseLayer(n_in=32, n_out=32, activation="tanh"))
            .layer(OutputLayer(n_in=32, n_out=4, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(6)).build())


def _net(**kw):
    return MultiLayerNetwork(_dense_conf(**kw)).init()


def _data(rng, n=32):
    xs = rng.standard_normal((n, 6)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return xs, ys


# ---------------------------------------------------------------------------
# 1. error-feedback conservation — EXACT
# ---------------------------------------------------------------------------
class TestConservationExact:
    @pytest.mark.parametrize("scale", [1e-6, 1e-3, 1.0, 1e3])
    @pytest.mark.parametrize("threshold", [1e-4, 1e-3, 1e-2, 0.3])
    def test_threshold_encode_exact_conserves_bitwise(self, rng, scale,
                                                      threshold):
        """decode(encode(g, res, t)) + new_res == g + res EXACTLY: the
        pow2-snapped threshold makes the residual subtraction exact (see
        ops/compression.pow2_floor) across 9 decades of gradient scale."""
        g = jnp.asarray(rng.standard_normal(20000) * scale, jnp.float32)
        res = jnp.asarray(rng.standard_normal(20000) * scale * 0.3,
                          jnp.float32)
        carried = g + res
        q, new_res = C.threshold_encode_exact(carried, threshold)
        back = q + new_res  # decode of the dense quantized IS identity
        np.testing.assert_array_equal(np.asarray(back), np.asarray(carried))

    def test_onebit_encode_conserves_bitwise(self, rng):
        g = jnp.asarray(rng.standard_normal(20000) * 0.01, jnp.float32)
        q, r, s = C.onebit_encode(g)
        np.testing.assert_array_equal(np.asarray(q + r), np.asarray(g))
        # the scale is an exact power of two
        e = np.frexp(float(s))
        assert e[0] == 0.5, float(s)
        # only |g| >= s transmitted (the exactness condition)
        qa = np.asarray(q)
        assert (np.abs(np.asarray(g))[qa != 0] >= float(s)).all()

    def test_pow2_floor_is_exact_pow2(self):
        for t in (1e-6, 1e-3, 0.1, 0.5, 1.0, 3.7):
            v = float(C.pow2_floor(t))
            m, _ = np.frexp(np.float32(v))
            assert m == 0.5 or v == 0.0, (t, v)
            assert v <= t < 2 * v, (t, v)

    def test_zero_threshold_is_exact_identity(self, rng):
        g = jnp.asarray(rng.standard_normal(512), jnp.float32)
        q, r = C.threshold_encode_exact(g, 0.0)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(g))
        assert not np.asarray(r).any()

    def test_compressor_step_conserves_through_state(self, rng):
        """The full GradCompressor.encode_combine conserves: what left each
        worker (quantized) plus what stayed (new residual) equals grad +
        old residual, bit-for-bit, every step."""
        comp = GradCompressor(scheme="threshold", initial_threshold=1e-2)
        stacked = {"w": jnp.asarray(
            rng.standard_normal((8, 64)) * 0.01, jnp.float32)}
        state = comp.init_state({"w": np.zeros((64,), np.float32)}, 8)
        for _ in range(5):
            carried = stacked["w"] + state["residual"]["w"]
            _, new_state, _ = comp.encode_combine(
                stacked, state, jnp.asarray(1.0, jnp.float32))
            # reconstruct this step's transmitted payload from conservation
            q = carried - new_state["residual"]["w"]
            np.testing.assert_array_equal(
                np.asarray(q + new_state["residual"]["w"]),
                np.asarray(carried))
            state = new_state


# ---------------------------------------------------------------------------
# 2. threshold→0 bit-identity with the uncompressed path
# ---------------------------------------------------------------------------
@pytest.mark.multichip
class TestThresholdZeroBitIdentity:
    def _fit(self, net, xs, ys, mesh, epochs=3, **kw):
        pw = ParallelWrapper(net, mesh=mesh, skew_every=0, **kw)
        pw.fit([DataSet(xs, ys)], epochs=epochs)
        return pw

    def test_t0_compressed_equals_deterministic(self, rng):
        xs, ys = _data(rng)
        exact = _net()
        self._fit(exact, xs, ys, _mesh8(), deterministic=True, replicas=8)
        comp = _net(comp="threshold", threshold=0.0)
        self._fit(comp, xs, ys, _mesh8(), replicas=8)
        _assert_tree_equal(exact.params, comp.params, "params(t=0)")
        _assert_tree_equal(exact.opt_states, comp.opt_states, "moments(t=0)")
        np.testing.assert_array_equal(np.asarray(exact._rng_key),
                                      np.asarray(comp._rng_key))

    def test_t0_hierarchical_equals_flat(self, rng):
        """pow2 host grouping preserves the pairwise-tree association, so
        the hierarchical mode's t=0 fit is the SAME bits as flat."""
        xs, ys = _data(rng)
        flat = _net(comp="threshold", threshold=0.0)
        self._fit(flat, xs, ys, _mesh8(), replicas=8)
        hier = _net(comp="threshold", threshold=0.0)
        self._fit(hier, xs, ys, _mesh8(), replicas=8, compression_hosts=2)
        _assert_tree_equal(flat.params, hier.params, "params(hier t=0)")

    def test_t0_fused_zero_composes_bit_identical(self, rng):
        """The fused-engine variant (encode on flat per-(rule,dtype)
        buffers, ZeRO-sharded update) at t=0 equals the plain fused
        deterministic fit bit-for-bit."""
        xs, ys = _data(rng)
        exact = _net(fused=True)
        self._fit(exact, xs, ys, _mesh8(), deterministic=True, replicas=8)
        comp = _net(fused=True, comp="threshold", threshold=0.0)
        pw = self._fit(comp, xs, ys, _mesh8(), replicas=8)
        _assert_tree_equal(exact.params, comp.params, "params(fused t=0)")
        # residual really is the flat buffer layout: one (8, total) leaf
        # per (rule, dtype) group
        res = pw._comp_state["residual"]
        assert isinstance(res, list) and len(res) == len(comp._fused.groups)
        for buf, grp in zip(res, comp._fused.groups):
            assert tuple(buf.shape) == (8, grp.total)


# ---------------------------------------------------------------------------
# 3. wire accounting: deterministic, scheme-shaped, gauged
# ---------------------------------------------------------------------------
@pytest.mark.multichip
class TestWireAccounting:
    def test_stats_deterministic_across_runs(self, rng):
        xs, ys = _data(rng)
        runs = []
        for _ in range(2):
            net = _net(comp="threshold")
            pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
            pw.fit([DataSet(xs, ys)], epochs=3)
            runs.append(pw.compression_stats())
        assert runs[0] == runs[1]
        assert runs[0]["wire_bytes"] > 0

    def test_bitmap_ratio_is_nnz_independent(self, rng):
        xs, ys = _data(rng)
        net = _net(comp="bitmap")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=2)
        stats = pw.compression_stats()
        # 2 bits/element + one word per leaf: strictly under 0.1, whatever
        # the data did
        assert stats["ratio"] < 0.1
        assert abs(stats["ratio"] - 1 / 16) < 0.05, stats

    def test_onebit_runs_and_reports(self, rng):
        xs, ys = _data(rng)
        net = _net(comp="onebit")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=2)
        stats = pw.compression_stats()
        assert np.isfinite(float(net.score_value))
        assert 0 < stats["ratio"] < 0.1

    def test_adaptive_threshold_drives_sparsity_down(self, rng):
        """The adaptive threshold climbs until the transmitted fraction
        reaches the target band — on this dense-gradient toy the sparse
        wire ratio must fall well below dense within a few dozen steps."""
        xs, ys = _data(rng, n=64)
        net = _net(comp="threshold", threshold=1e-3, target=1e-2,
                   updater=Sgd(0.05))
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        it = [DataSet(xs[i:i + 8], ys[i:i + 8]) for i in range(0, 64, 8)]
        pw.fit(it, epochs=8)
        stats = pw.compression_stats()
        assert stats["threshold"] > 1e-3  # adapted upward
        assert stats["ratio"] < 0.5, stats
        # sparsity sits inside the adaptive dead band (3x each way),
        # modulo one trailing adjustment step
        sparsity = stats["nnz"] / (stats["workers"] * stats["elements"])
        assert sparsity < 3 * 1e-2 * 1.5, stats

    def test_hierarchical_prices_cross_host_only(self, rng):
        xs, ys = _data(rng)
        net = _net(comp="threshold")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0,
                             compression_hosts=2)
        pw.fit([DataSet(xs, ys)], epochs=2)
        stats = pw.compression_stats()
        assert stats["workers"] == 2.0  # hosts, not lanes
        assert pw.layout["grad_compression"]["hosts"] == 2

    def test_wrapper_gauges_published(self, rng):
        from deeplearning4j_tpu.util import telemetry as tm

        xs, ys = _data(rng)
        net = _net(comp="threshold")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=1)
        pw.compression_stats()  # publish
        tele = tm.get_telemetry()
        metrics = {k[0] for k in tele.gauges}
        assert "parallel.allreduce_wire_bytes" in metrics
        assert "parallel.allreduce_compression_ratio" in metrics


# ---------------------------------------------------------------------------
# 4. convergence parity on a real fit
# ---------------------------------------------------------------------------
@pytest.mark.multichip
class TestConvergenceParity:
    def test_compressed_fit_tracks_exact_fit(self, rng):
        """Same data order, same seeds: the error-feedback compressed fit
        must land in the exact fit's loss neighborhood (nothing lost, only
        delayed)."""
        xs, ys = _data(rng, n=64)
        batches = [DataSet(xs[i:i + 16], ys[i:i + 16])
                   for i in range(0, 64, 16)]

        exact = _net(updater=Sgd(0.1))
        ParallelWrapper(exact, mesh=_mesh8(), deterministic=True,
                        replicas=8, skew_every=0).fit(batches, epochs=15)
        comp = _net(comp="threshold", threshold=1e-3, target=3e-2,
                    updater=Sgd(0.1))
        ParallelWrapper(comp, mesh=_mesh8(), replicas=8,
                        skew_every=0).fit(batches, epochs=15)
        le, lc = float(exact.score_value), float(comp.score_value)
        assert np.isfinite(lc)
        # both learned (initial mcxent ~ ln4 = 1.386) and the compressed
        # endpoint is within tolerance of the exact one
        assert le < 1.0 and lc < 1.0, (le, lc)
        assert abs(lc - le) < 0.25, (le, lc)


# ---------------------------------------------------------------------------
# 5. loss_scale under ParallelWrapper (satellite)
# ---------------------------------------------------------------------------
@pytest.mark.multichip
class TestLossScaleUnderWrapper:
    def test_static_scale_lane_fit_bit_identical_across_meshes(self, rng):
        """The scaled lane step keeps the r12 contract: 8-dev == 1-dev
        BIT-identical with loss_scale='static' on the fused engine."""
        xs, ys = _data(rng)
        nets = []
        for mesh in (_mesh1(), _mesh8()):
            net = _net(fused=True, loss_scale="static")
            ParallelWrapper(net, mesh=mesh, deterministic=True, replicas=8,
                            skew_every=0).fit([DataSet(xs, ys)], epochs=3)
            nets.append(net)
        _assert_tree_equal(nets[0].params, nets[1].params, "params(scaled)")
        _assert_tree_equal(nets[0].opt_states, nets[1].opt_states,
                           "opt(scaled)")

    def test_static_scale_matches_single_host_scaled_path(self, rng):
        """Trajectory test vs the single-host scaled path (the satellite's
        acceptance): same conf fitted through net.fit and through the lane
        wrapper tracks to float tolerance."""
        xs, ys = _data(rng)
        solo = _net(fused=True, loss_scale="static")
        for _ in range(6):
            solo.fit(xs, ys)
        laned = _net(fused=True, loss_scale="static")
        pw = ParallelWrapper(laned, mesh=_mesh8(), deterministic=True,
                             replicas=8, skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=6)
        for a, b in zip(_leaves(solo.params), _leaves(laned.params)):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

    def test_dynamic_scale_automaton_advances_under_wrapper(self, rng):
        xs, ys = _data(rng)
        net = _net(fused=True, loss_scale="dynamic")
        pw = ParallelWrapper(net, mesh=_mesh8(), deterministic=True,
                             replicas=8, skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=4)
        scale_state = net.opt_states["scale"]
        assert int(scale_state["good"]) == 4  # every step was finite
        assert float(scale_state["scale"]) == 2.0 ** 15
        assert np.isfinite(float(net.score_value))

    def test_masters_still_refuse_scaled_models(self, rng):
        """The guard moved, it did not vanish: a master whose lane grads
        are unscaled must still refuse a scaling policy loudly."""
        net = _net(fused=True, loss_scale="static")
        with pytest.raises(NotImplementedError, match="loss_scale"):
            gspmd.apply_updaters(net, net.params,
                                 jax.tree_util.tree_map(jnp.zeros_like,
                                                        net.params),
                                 net.opt_states, jnp.asarray(0))

    def test_dynamic_plus_compression_rejected(self, rng):
        net = _net(fused=True, loss_scale="dynamic", comp="threshold")
        with pytest.raises(ValueError, match="dynamic"):
            ParallelWrapper(net, mesh=_mesh8(), skew_every=0)

    def test_static_plus_compression_composes(self, rng):
        xs, ys = _data(rng)
        net = _net(fused=True, loss_scale="static", comp="threshold",
                   threshold=0.0)
        pw = ParallelWrapper(net, mesh=_mesh8(), replicas=8, skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=3)
        exact = _net(fused=True, loss_scale="static")
        ParallelWrapper(exact, mesh=_mesh8(), deterministic=True,
                        replicas=8, skew_every=0).fit([DataSet(xs, ys)],
                                                      epochs=3)
        _assert_tree_equal(exact.params, net.params,
                           "params(scaled, compressed t=0)")


# ---------------------------------------------------------------------------
# 6. cost_report for lane-decomposed wrappers (satellite)
# ---------------------------------------------------------------------------
@pytest.mark.multichip
class TestLaneCostReport:
    def test_deterministic_wrapper_cost_report(self, rng):
        xs, ys = _data(rng)
        net = _net()
        pw = ParallelWrapper(net, mesh=_mesh8(), deterministic=True,
                             replicas=8, skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=1)
        rep = pw.cost_report(batch_size=32, publish=False)
        assert rep.devices == 8
        if rep.source == "xla":
            assert rep.totals.get("flops", 0) > 0
            tags = {r.layer for r in rep.rows}
            assert any("dense" in t.lower() or "output" in t.lower()
                       or "layer" in t.lower() for t in tags), tags
            assert "(optimizer)" in tags, tags

    def test_compressed_wrapper_cost_report(self, rng):
        xs, ys = _data(rng)
        net = _net(comp="threshold")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=1)
        rep = pw.cost_report(batch_size=32, publish=False)
        assert rep.devices == 8
        if rep.source == "xla":
            assert rep.totals.get("flops", 0) > 0


# ---------------------------------------------------------------------------
# 7. residual migration: checkpoint-resume + reshard
# ---------------------------------------------------------------------------
@pytest.mark.multichip
class TestResidualMigration:
    def test_checkpoint_resume_trajectory_exact(self, rng, tmp_path):
        """Stop/restore mid-compressed-fit and continue: the resumed run's
        params, moments, residual, and threshold equal the uninterrupted
        run's bit-for-bit."""
        xs, ys = _data(rng, n=64)
        batches = [DataSet(xs[i:i + 8], ys[i:i + 8])
                   for i in range(0, 64, 8)]
        ckpt = ShardedCheckpointer(str(tmp_path / "ck"), log_fn=None)

        net_a = _net(comp="threshold")
        pw_a = ParallelWrapper(net_a, mesh=_mesh8(), skew_every=0)
        for ds in batches[:4]:
            pw_a.step_batch(ds)
        ckpt.save(net_a.iteration, net_a)
        for ds in batches[4:]:
            pw_a.step_batch(ds)

        net_b = _net(comp="threshold")
        ckpt.restore(net_b)
        assert net_b._grad_comp_state is not None
        pw_b = ParallelWrapper(net_b, mesh=_mesh8(), skew_every=0)
        for ds in batches[4:]:
            pw_b.step_batch(ds)

        _assert_tree_equal(net_a.params, net_b.params, "params(resume)")
        _assert_tree_equal(net_a.opt_states, net_b.opt_states, "opt(resume)")
        _assert_tree_equal(net_a._grad_comp_state, net_b._grad_comp_state,
                           "residual+threshold(resume)")
        # the carried residual is non-trivial (the test would pass
        # vacuously if nothing ever stayed behind)
        assert any(np.asarray(l).any()
                   for l in _leaves(net_a._grad_comp_state))

    def test_checkpoint_without_sidecar_resets_residual(self, rng, tmp_path):
        xs, ys = _data(rng)
        plain = _net()
        ckpt = ShardedCheckpointer(str(tmp_path / "ck"), log_fn=None)
        ckpt.save(0, plain)
        net = _net(comp="threshold")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        pw.fit([DataSet(xs, ys)], epochs=2)  # residual accumulated
        ckpt.restore(net)
        assert net._grad_comp_state is None
        pw.step_batch(DataSet(xs, ys))  # re-adopts: fresh zeros, no crash
        assert net._grad_comp_state is not None

    def test_reshard_migrates_residual_bit_exact_then_tracks(self, rng):
        """Elastic regroup (8→4 devices) mid-compressed-fit: the lane count
        is fixed, so the residual/threshold MIGRATE BIT-EXACTLY through
        reshard (asserted at the regroup instant), and the continued fit
        tracks the no-regroup run within the r12 lane-fold boundary — a
        2-lanes-per-device shard vectorizes some elementwise tails
        differently than 1-lane-per-device, a pre-existing XLA:CPU
        property measured at ~1 ulp on the UNCOMPRESSED deterministic
        path too (docs/DISTRIBUTED.md)."""
        xs, ys = _data(rng, n=64)
        batches = [DataSet(xs[i:i + 8], ys[i:i + 8])
                   for i in range(0, 64, 8)]

        net_a = _net(comp="threshold")
        pw_a = ParallelWrapper(net_a, mesh=_mesh8(), replicas=8,
                               skew_every=0)
        for ds in batches[:4]:
            pw_a.step_batch(ds)
        mid_state = jax.tree_util.tree_map(np.asarray,
                                           net_a._grad_comp_state)
        for ds in batches[4:]:
            pw_a.step_batch(ds)

        net_b = _net(comp="threshold")
        pw_b = ParallelWrapper(net_b, mesh=_mesh8(), replicas=8,
                               skew_every=0)
        for ds in batches[:4]:
            pw_b.step_batch(ds)
        pw_b.reshard(TrainingMesh(data=4, devices=jax.devices()[:4]))
        # the migration itself is EXACT: nothing about the residual or
        # threshold may change at the regroup boundary
        _assert_tree_equal(mid_state, net_b._grad_comp_state,
                           "residual+threshold at regroup")
        for ds in batches[4:]:
            pw_b.step_batch(ds)

        for a, b in zip(_leaves(net_a.params), _leaves(net_b.params)):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
        for a, b in zip(_leaves(net_a._grad_comp_state),
                        _leaves(net_b._grad_comp_state)):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4)

    def test_warmup_does_not_perturb_residual(self, rng):
        """warmup() primes executables on shadow state: the REAL resident
        residual/threshold must come back untouched (the compressed step
        donates its state — a naive warmup would consume and advance
        it)."""
        xs, ys = _data(rng)
        net = _net(comp="threshold")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        pw.step_batch(DataSet(xs, ys))
        before = jax.tree_util.tree_map(np.asarray, net._grad_comp_state)
        assert pw.warmup([16], input_shape=(6,), label_shape=(4,)) == 1
        _assert_tree_equal(before, net._grad_comp_state, "residual(warmup)")
        pw.step_batch(DataSet(xs, ys))  # still steps fine
        assert np.isfinite(float(net.score_value))

    def test_mismatched_restored_state_fails_loudly(self, rng):
        net = _net(comp="threshold")
        net._grad_comp_state = {"residual": [np.zeros((3, 3), np.float32)],
                                "threshold": np.float32(1e-3)}
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        xs, ys = _data(rng)
        with pytest.raises(ValueError, match="grad-compression state"):
            pw.step_batch(DataSet(xs, ys))


# ---------------------------------------------------------------------------
# 8. knobs: conf round-trip, env default, validation, sidecar format
# ---------------------------------------------------------------------------
class TestKnobsAndFormats:
    def test_conf_json_round_trip_mln(self):
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

        conf = _dense_conf(comp="bitmap", threshold=5e-3, target=1e-2)
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.grad_compression == "bitmap"
        assert back.grad_compression_threshold == 5e-3
        assert back.grad_compression_target == 1e-2

    def test_conf_json_round_trip_cg(self):
        from deeplearning4j_tpu.nn.computation_graph import (
            ComputationGraphConfiguration)

        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
                .grad_compression("onebit")
                .graph_builder()
                .add_inputs("in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                              activation="softmax"), "in")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        back = ComputationGraphConfiguration.from_json(conf.to_json())
        assert back.grad_compression == "onebit"

    def test_scheme_validation(self):
        assert validate_scheme(None) is None
        assert validate_scheme("bitmap") == "bitmap"
        with pytest.raises(ValueError, match="grad_compression"):
            validate_scheme("zstd")

    def test_env_default_flows_into_builder(self):
        from deeplearning4j_tpu.config import get_environment

        env = get_environment()
        old = env.default_grad_compression
        try:
            env.default_grad_compression = "bitmap"
            conf = _dense_conf()
            assert conf.grad_compression == "bitmap"
            env.default_grad_compression = "zstd"
            with pytest.raises(ValueError, match="DL4J_TPU_GRAD_COMPRESSION"):
                _dense_conf()
        finally:
            env.default_grad_compression = old

    def test_wrapper_arg_overrides_conf(self, rng):
        net = _net(comp="threshold")
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0,
                             grad_compression="none")
        assert pw._compressor is None
        assert resolve_scheme(None, net.conf) == "threshold"

    def test_sidecar_npz_round_trip(self, tmp_path):
        tree = {"residual": [{"W": np.arange(6, dtype=np.float32)
                              .reshape(2, 3), "b": np.zeros(3)},
                             {}],
                "threshold": np.float32(0.25),
                "none_slot": None}
        path = str(tmp_path / "comp.npz")
        save_tree_npz(path, tree)
        back = load_tree_npz(path)
        assert back["none_slot"] is None
        np.testing.assert_array_equal(back["residual"][0]["W"],
                                      tree["residual"][0]["W"])
        assert float(back["threshold"]) == 0.25
        assert back["residual"][1] == {}

    def test_hosts_must_divide_replicas(self):
        comp = GradCompressor(scheme="threshold", hosts=3)
        with pytest.raises(ValueError, match="divide"):
            comp.exchange_axis(8)

    def test_target_sparsity_threshold_algorithm(self):
        """The proportional-control variant (accumulator.py parity): always
        corrects toward the target — up when too dense, down when too
        sparse — and clips to its bounds."""
        from deeplearning4j_tpu.parallel import (
            TargetSparsityThresholdAlgorithm)

        algo = TargetSparsityThresholdAlgorithm(initial=1e-3,
                                                target_ratio=1e-2,
                                                gain=1.5)
        t = algo.init_state()
        t_up = algo.update(t, jnp.asarray(0.5))    # too dense -> raise
        t_down = algo.update(t, jnp.asarray(1e-4))  # too sparse -> lower
        assert float(t_up) == pytest.approx(1.5e-3)
        assert float(t_down) == pytest.approx(1e-3 / 1.5)
        # converges into a band under alternating pressure, never past
        # the clips
        for _ in range(200):
            t = algo.update(t, jnp.asarray(1.0))
        assert float(t) == algo.max_threshold
        for _ in range(200):
            t = algo.update(t, jnp.asarray(0.0))
        assert float(t) == pytest.approx(algo.min_threshold)
