"""Autotuning subsystem tests (ISSUE 11, docs/AUTOTUNE.md): search-space
registry, equivalence-gated measurement driver, persistent tuning
database, and trace-time consultation by ``auto`` dispatch + conf-time
defaulting.

The satellite contract (mirrored from the checkpoint suite's corruption
discipline and the compile-cache suite's warm-read discipline):

- warm-read: a SECOND database reader (fresh instance over the same
  directory — what a new process sees) re-measures NOTHING, asserted via
  the ``tuning.measurements_total`` counter;
- corrupt/truncated entries are skipped with a loud warning (mirroring
  ``restore_latest_good``), never believed, never a crash;
- keys invalidate when backend/topology changes;
- gate self-tests: a PLANTED slow candidate loses the sweep, a planted
  wrong-output candidate is rejected by the equivalence check.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import tuning
from deeplearning4j_tpu.ops import kernels as K
from deeplearning4j_tpu.ops.kernels import conv as kconv
from deeplearning4j_tpu.ops.kernels import lstm as klstm
from deeplearning4j_tpu.tuning import database as tdb
from deeplearning4j_tpu.util import telemetry as tm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CONV = {"x_shape": (2, 8, 8, 4), "w_shape": (3, 3, 4, 8),
             "strides": (1, 1), "padding": "SAME", "dilation": (1, 1),
             "groups": 1, "dtype": "float32"}
TINY_LSTM = {"batch": 6, "hidden": 8, "timesteps": 4, "dtype": "float32"}


def _counter(name):
    tele = tm.get_telemetry()
    return tele.counters.get((name, ()), 0.0)


@pytest.fixture
def db(tmp_path):
    """An armed process-global database in a tmp dir; always disarmed on
    exit so no other test sees tuned dispatch."""
    d = tuning.set_database(str(tmp_path / "tdb"))
    try:
        yield d
    finally:
        tuning.set_database(None)


def _driver(db, **kw):
    kw.setdefault("min_window_s", 0.002)
    return tuning.MeasurementDriver(db, **kw)


# ---------------------------------------------------------------------------
# search-space registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_builtin_spaces_registered(self):
        names = tuning.space_names()
        for want in ("conv2d_tiles", "lstm_tiles", "remat_policy",
                     "xla_flags", "bucket_sets", "compression_hosts"):
            assert want in names
        assert "conv2d_tiles" in tuning.measurable_spaces()
        assert "xla_flags" not in tuning.measurable_spaces()

    def test_unknown_space_raises(self):
        with pytest.raises(ValueError, match="unknown search space"):
            tuning.get_space("warp_speed")

    def test_conv_candidates_typed_and_guarded(self):
        sp = tuning.get_space("conv2d_tiles")
        cands = sp.enumerate(TINY_CONV)
        labels = [c.label for c in cands]
        assert "exact" in labels and "pallas:rt=whole" in labels
        by_label = {c.label: c for c in cands}
        # oh=8 -> divisors 1,2,4 below 8
        assert by_label["pallas:rt=2"].params == {"row_tile": 2}
        # the validated-shape guard: a non-dividing tile is rejected
        bad = tuning.Candidate("pallas:rt=3", impl="pallas",
                               params={"row_tile": 3})
        ok, reason = sp.validate(bad, TINY_CONV)
        assert not ok and "does not divide" in reason
        # ... and a VMEM-overflow candidate is rejected (giant imaginary
        # feature map, whole-OH accumulator)
        huge = dict(TINY_CONV, x_shape=(1, 4096, 4096, 64),
                    w_shape=(3, 3, 64, 64))
        ok, reason = sp.validate(
            tuning.Candidate("pallas:rt=whole", impl="pallas",
                             params={"row_tile": None}), huge)
        assert not ok and "VMEM" in reason

    def test_lstm_candidates_guarded(self):
        sp = tuning.get_space("lstm_tiles")
        cands = sp.enumerate(TINY_LSTM)
        assert any(c.params.get("b_tile") == 3 for c in cands)
        ok, reason = sp.validate(
            tuning.Candidate("pallas:bt=4", impl="pallas",
                             params={"b_tile": 4}), TINY_LSTM)
        assert not ok and "does not divide" in reason

    def test_signature_shared_with_dispatch_site(self):
        """The space's DB signature and the ops/nn.py dispatch site use
        ONE builder — drift here would orphan every committed winner."""
        sp = tuning.get_space("conv2d_tiles")
        assert sp.signature(TINY_CONV) == kconv.shape_signature(
            TINY_CONV["x_shape"], TINY_CONV["w_shape"],
            TINY_CONV["strides"], TINY_CONV["padding"],
            TINY_CONV["dilation"], TINY_CONV["groups"])
        sp2 = tuning.get_space("lstm_tiles")
        assert sp2.signature(TINY_LSTM) == klstm.shape_signature(6, 8)

    def test_register_custom_space(self):
        class Dummy(tuning.SearchSpace):
            name = "dummy_space"
            op = "dummy"
            measurable = False
            requires = "nothing"

            def signature(self, ctx):
                return "conf-default"

            def enumerate(self, ctx):
                return [tuning.Candidate("a", is_default=True)]

        tuning.register_space(Dummy())
        try:
            assert "dummy_space" in tuning.space_names()
            with pytest.raises(RuntimeError, match="declared"):
                _driver(tuning.TuningDatabase("/tmp/unused-db")).sweep(
                    tuning.get_space("dummy_space"), {})
        finally:
            tuning.space._REGISTRY.pop("dummy_space", None)


# ---------------------------------------------------------------------------
# measurement driver: equivalence gate + planted self-tests
# ---------------------------------------------------------------------------


class TestDriverGates:
    @pytest.mark.slow
    def test_planted_slow_candidate_loses(self, db):
        """A config handicapped by a per-call sleep must demonstrably
        LOSE the sweep — the gate that proves measurements rank.

        slow-marked (r19 tier-1 budget): the same planted-slow gate runs
        against the real sweep in benchmarks/autotune_smoke.py on EVERY
        CI pass."""
        drv = _driver(db)
        entry = drv.sweep(tuning.get_space("conv2d_tiles"), TINY_CONV,
                          handicap={"exact": 0.05})
        assert entry["status"] == "measured"
        assert entry["winner"]["label"] != "exact"
        rows = {r["label"]: r for r in entry["measured"]}
        assert rows["exact"]["admitted"]            # slow, but correct
        assert rows["exact"]["ms"] > entry["winner"]["ms"]

    @pytest.mark.slow
    def test_planted_wrong_output_rejected(self, db):
        """A candidate whose outputs diverge from the exact path must be
        REJECTED by the equivalence gate — and never timed.

        slow-marked (r19 tier-1 budget): the planted-wrong rejection also
        runs in benchmarks/autotune_smoke.py on EVERY CI pass."""
        drv = _driver(db)
        m0 = _counter("tuning.measurements_total")
        r0 = _counter("tuning.equivalence_rejects_total")
        entry = drv.sweep(
            tuning.get_space("conv2d_tiles"), TINY_CONV,
            corrupt={"pallas:rt=2": lambda o: (o[0] + 1.0,) + tuple(o[1:])})
        rows = {r["label"]: r for r in entry["measured"]}
        assert rows["pallas:rt=2"]["admitted"] is False
        assert "equivalence" in rows["pallas:rt=2"]["reason"]
        assert "ms" not in rows["pallas:rt=2"]      # gate before stopwatch
        assert entry["winner"]["label"] != "pallas:rt=2"
        assert _counter("tuning.equivalence_rejects_total") == r0 + 1
        # only the admitted candidates were measured
        admitted = sum(1 for r in entry["measured"] if r["admitted"])
        assert _counter("tuning.measurements_total") == m0 + admitted

    def test_all_wrong_refuses_to_commit(self, db):
        """A space whose every candidate fails the gate is a bug, not a
        tuning result: the driver refuses to commit any winner."""
        drv = _driver(db)
        sp = tuning.get_space("lstm_tiles")
        corrupt = {c.label: (lambda o: (o[0] + 1.0,) + tuple(o[1:]))
                   for c in sp.enumerate(TINY_LSTM)}
        with pytest.raises(RuntimeError, match="no candidate passed"):
            drv.sweep(sp, TINY_LSTM, corrupt=corrupt)
        assert db.entries() == 0

    def test_deterministic_random_selection(self, db):
        """Random search with one seed picks the same candidates (the
        deterministic-seeding contract); the default is always included."""
        drv_a = _driver(db, search="random", samples=3, seed=7)
        drv_b = _driver(db, search="random", samples=3, seed=7)
        sp = tuning.get_space("conv2d_tiles")
        sel_a = [c.label for c in drv_a._select(sp, sp.enumerate(TINY_CONV))]
        sel_b = [c.label for c in drv_b._select(sp, sp.enumerate(TINY_CONV))]
        assert sel_a == sel_b
        assert "exact" in sel_a
        sel_c = [c.label for c in _driver(db, search="random", samples=3,
                                          seed=8)
                 ._select(sp, sp.enumerate(TINY_CONV))]
        assert len(sel_c) == len(sel_a)


# ---------------------------------------------------------------------------
# tuning database: persistence contracts
# ---------------------------------------------------------------------------


class TestDatabase:
    def test_warm_read_second_reader_measures_nothing(self, db):
        """The cross-process contract in-process: a FRESH database
        instance over the same directory (what a second process sees) and
        a fresh driver re-measure NOTHING — asserted via the
        tuning.measurements_total counter."""
        drv = _driver(db)
        sp = tuning.get_space("lstm_tiles")
        cold = drv.sweep(sp, TINY_LSTM)
        assert cold["status"] == "measured"
        m0 = _counter("tuning.measurements_total")
        db2 = tuning.TuningDatabase(db.dir)        # fresh reader
        warm = _driver(db2).sweep(sp, TINY_LSTM)
        assert warm["status"] == "warm"
        assert warm["winner"] == cold["winner"]
        assert _counter("tuning.measurements_total") == m0

    def test_changed_candidate_set_remeasures(self, db):
        """A drifted search space must NOT trust a stale winner: the
        candidates digest mismatch forces a re-measure."""
        drv = _driver(db)
        sp = tuning.get_space("lstm_tiles")
        drv.sweep(sp, TINY_LSTM)
        key = sp.key(TINY_LSTM)
        entry = db.lookup(key)
        entry = dict(entry, candidates_digest="stale")
        db.commit(key, entry)
        m0 = _counter("tuning.measurements_total")
        again = _driver(tuning.TuningDatabase(db.dir)).sweep(sp, TINY_LSTM)
        assert again["status"] == "measured"
        assert _counter("tuning.measurements_total") > m0

    def test_corrupt_entry_skipped_with_warning(self, db, caplog):
        """A truncated/garbage entry is skipped with a loud warning and a
        counter (the restore_latest_good convention) — the database
        degrades to 'unmeasured', it never crashes or believes garbage."""
        drv = _driver(db)
        sp = tuning.get_space("lstm_tiles")
        drv.sweep(sp, TINY_LSTM)
        path = db.entry_paths()[0]
        blob = open(path).read()
        open(path, "w").write(blob[: len(blob) // 2])  # truncate mid-JSON
        c0 = _counter("tuning.corrupt_skipped_total")
        db2 = tuning.TuningDatabase(db.dir)
        with caplog.at_level("WARNING"):
            assert db2.lookup(sp.key(TINY_LSTM)) is None
        assert any("corrupt" in r.message for r in caplog.records)
        assert _counter("tuning.corrupt_skipped_total") == c0 + 1
        # all_records skips it too (the stats surface stays up)
        assert db2.all_records() == []

    def test_hand_written_entry_missing_key_skipped(self, db, caplog):
        """A hand-authored entry (the documented xla_flags path) that
        forgot the \"key\" field is corrupt-skipped, not a trace-time
        KeyError — the 'never a crash' contract covers schema holes."""
        sp = tuning.get_space("lstm_tiles")
        key = sp.key(TINY_LSTM)
        path = db._path(key)
        os.makedirs(db.dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"schema": tdb.SCHEMA_VERSION,
                       "winner": {"label": "exact", "impl": "exact",
                                  "params": {}, "ms": 1.0}}, f)
        c0 = _counter("tuning.corrupt_skipped_total")
        with caplog.at_level("WARNING"):
            assert db.lookup(key) is None
        assert _counter("tuning.corrupt_skipped_total") == c0 + 1

    def test_key_invalidates_on_backend_and_topology_change(self, db,
                                                            monkeypatch):
        """Entries are keyed by (backend, topology): a database harvested
        on one topology must MISS on another, never answer for it."""
        drv = _driver(db)
        sp = tuning.get_space("lstm_tiles")
        drv.sweep(sp, TINY_LSTM)
        assert db.lookup(sp.key(TINY_LSTM)) is not None
        monkeypatch.setattr(tdb, "current_topology", lambda: "tpu:16:v5e")
        db.invalidate_cache()
        assert db.lookup(sp.key(TINY_LSTM)) is None
        monkeypatch.setattr(tdb, "current_backend", lambda: "tpu")
        db.invalidate_cache()
        assert db.lookup(sp.key(TINY_LSTM)) is None

    def test_atomic_commit_leaves_no_tmp(self, db):
        drv = _driver(db)
        drv.sweep(tuning.get_space("lstm_tiles"), TINY_LSTM)
        assert not [f for f in os.listdir(db.dir) if f.endswith(".tmp")]

    def test_stats_and_status_surfaces(self, db):
        drv = _driver(db)
        drv.sweep(tuning.get_space("lstm_tiles"), TINY_LSTM)
        st = db.stats()
        assert st["entries"] == 1
        assert st["entries_by_op"] == {"lstm_cell": 1}
        status = tuning.current_status()
        assert status["entries"] == 1
        assert "tuning.measurements_total" in status["counters"]
        gauges = dict(((n, tuple(sorted(l.items()))), v)
                      for n, l, v in tdb.collect_tuning_gauges())
        assert gauges[("tuning.db_enabled", ())] == 1
        assert gauges[("tuning.db_entries", ())] == 1

    def test_disarmed_status_empty(self):
        assert tuning.get_database() is None
        assert tuning.current_status() == {}
        assert tdb.collect_tuning_gauges() == [("tuning.db_enabled", {}, 0)]

    def test_consultation_is_read_only(self, tmp_path, monkeypatch):
        """resolve() through a DL4J_TPU_TUNING_DB that points nowhere
        must neither crash nor create the directory — consultation is a
        pure read (a typo'd env knob or a read-only mount degrades to
        'unmeasured'); only commit() creates the directory."""
        monkeypatch.setattr(tdb, "_db_dir", tdb._UNSET)
        monkeypatch.setattr(tdb, "_db", None)
        missing = str(tmp_path / "not-yet-harvested")
        monkeypatch.setenv("DL4J_TPU_TUNING_DB", missing)
        assert tdb.resolve("conv2d", "nope", "float32") is None
        assert not os.path.exists(missing)
        db = tuning.get_database()
        db.commit(tdb.TuningKey.for_op("conv2d", "nope", "float32"),
                  {"winner": {"label": "exact", "impl": "exact",
                              "params": {}, "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        assert os.path.isdir(missing)
        assert tdb.resolve("conv2d", "nope", "float32")["label"] == "exact"

    def test_set_database_none_disarms_over_env(self, tmp_path,
                                                monkeypatch):
        """set_database(None) is explicit OFF, not 'defer to env': the
        fixture/bench teardown contract holds even in a shell where
        DL4J_TPU_TUNING_DB is exported."""
        monkeypatch.setattr(tdb, "_db_dir", tdb._UNSET)
        monkeypatch.setattr(tdb, "_db", None)
        monkeypatch.setenv("DL4J_TPU_TUNING_DB", str(tmp_path / "envdb"))
        assert tuning.get_database() is not None
        tuning.set_database(None)
        assert tdb.database_dir() is None
        assert tuning.get_database() is None
        assert tdb.resolve("conv2d", "nope", "float32") is None


# ---------------------------------------------------------------------------
# trace-time consultation: auto dispatch + conf defaulting
# ---------------------------------------------------------------------------


class TestAutoDispatch:
    @pytest.mark.slow
    def test_auto_resolves_winner_through_db(self, db, monkeypatch):
        """kernel_impl=auto consults the database: a committed pallas
        winner (with its tile) engages the kernel on the exact geometry,
        and the output still matches the exact path.

        slow-marked (r19 tier-1 budget): auto-dispatch resolving through
        an armed DB is asserted by benchmarks/autotune_smoke.py on EVERY
        CI pass (tuning.hits_total > 0 + tuned == exact)."""
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        from deeplearning4j_tpu.ops import nn as nnops

        drv = _driver(db)
        # plant-slow exact so a pallas tile wins and dispatch has a
        # non-default decision to apply
        entry = drv.sweep(tuning.get_space("conv2d_tiles"), TINY_CONV,
                          handicap={"exact": 0.05})
        assert entry["winner"]["impl"] == "pallas"
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=TINY_CONV["x_shape"]), jnp.float32)
        w = jnp.asarray(rng.normal(size=TINY_CONV["w_shape"]) * 0.1,
                        jnp.float32)
        h0 = _counter("tuning.hits_total")
        out = nnops.conv2d(x, w)
        assert _counter("tuning.hits_total") > h0
        with K.impl_scope("exact"):
            exact = nnops.conv2d(x, w)
        assert float(jnp.max(jnp.abs(out - exact))) < 2e-4

    def test_auto_miss_keeps_honest_prior(self, db, monkeypatch):
        """No entry for the geometry -> auto keeps the r14 behaviour
        (exact on CPU); an exact winner entry also resolves exact."""
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        sig = kconv.shape_signature((1, 4, 4, 2), (3, 3, 2, 2), (1, 1),
                                    "SAME", (1, 1), 1)
        mode, params = K.dispatch(True, op="conv2d", sig=sig,
                                  dtype="float32")
        assert mode is None and params == {}
        db.commit(tdb.TuningKey.for_op("conv2d", sig, "float32"),
                  {"winner": {"label": "exact", "impl": "exact",
                              "params": {}, "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        mode, params = K.dispatch(True, op="conv2d", sig=sig,
                                  dtype="float32")
        assert mode is None
        # explicit scopes ignore the database entirely
        with K.impl_scope("exact"):
            assert K.dispatch(True, op="conv2d", sig=sig,
                              dtype="float32")[0] is None

    def test_lstm_auto_uses_tuned_b_tile(self, db, monkeypatch):
        """The recurrent-layer dispatch site consults op=lstm_cell and
        threads the winner's b_tile; layer output matches the exact
        path."""
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        from deeplearning4j_tpu.nn.recurrent import LSTM as LSTMLayer

        b, h, t, n_in = 6, 8, 5, 4
        sig = klstm.shape_signature(b, h)
        db.commit(tdb.TuningKey.for_op("lstm_cell", sig, "float32"),
                  {"winner": {"label": "pallas:bt=2", "impl": "pallas",
                              "params": {"b_tile": 2}, "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        lyr = LSTMLayer(n_in=n_in, n_out=h)
        params, _ = lyr.initialize(jax.random.PRNGKey(0), (b, t, n_in))
        x = jnp.asarray(np.random.default_rng(1).normal(size=(b, t, n_in)),
                        jnp.float32)
        carry = lyr.init_carry(b)
        h1 = _counter("tuning.hits_total")
        out_tuned, _ = lyr.apply_seq(params, x, carry)
        assert _counter("tuning.hits_total") > h1
        with K.impl_scope("exact"):
            out_exact, _ = lyr.apply_seq(params, x, carry)
        assert float(jnp.max(jnp.abs(out_tuned - out_exact))) < 1e-4

    def test_tiled_winner_reachable_beyond_whole_block_vmem(
            self, db, monkeypatch):
        """The trace-time VMEM guard is tile-aware: a committed tiled
        winner on a feature map whose WHOLE-block accumulator busts the
        budget still engages the kernel with its own (validated) tile —
        the shapes the harvest targets most. A stale non-dividing tile
        degrades to the exact path instead of crashing."""
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        from deeplearning4j_tpu.ops import nn as nnops

        x_shape, w_shape = (1, 256, 16, 8), (3, 3, 8, 512)
        pads = ((1, 1), (1, 1))
        assert not kconv.fits_vmem(x_shape, w_shape, pads, 1, 4)
        assert kconv.fits_vmem(x_shape, w_shape, pads, 1, 4, row_tile=2)
        sig = kconv.shape_signature(x_shape, w_shape, (1, 1), "SAME",
                                    (1, 1), 1)
        db.commit(tdb.TuningKey.for_op("conv2d", sig, "float32"),
                  {"winner": {"label": "pallas:rt=2", "impl": "pallas",
                              "params": {"row_tile": 2}, "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=x_shape), jnp.float32)
        w = jnp.asarray(rng.normal(size=w_shape) * 0.05, jnp.float32)
        h0 = _counter("tuning.hits_total")
        out = nnops.conv2d(x, w)
        assert _counter("tuning.hits_total") > h0
        with K.impl_scope("exact"):
            exact = nnops.conv2d(x, w)
        scale = max(1.0, float(jnp.max(jnp.abs(exact))))
        assert float(jnp.max(jnp.abs(out - exact))) / scale < 1e-4
        # stale winner naming a tile that no longer divides OH: the
        # tile-aware guard rejects it and the call takes the exact path
        db.commit(tdb.TuningKey.for_op("conv2d", sig, "float32"),
                  {"winner": {"label": "pallas:rt=3", "impl": "pallas",
                              "params": {"row_tile": 3}, "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        stale = nnops.conv2d(x, w)
        assert float(jnp.max(jnp.abs(stale - exact))) == 0.0

    def test_lstm_tiled_winner_reachable_beyond_whole_batch_vmem(
            self, db, monkeypatch):
        """Same tile-aware-guard contract on the LSTM seam: a committed
        b_tile winner on a cell whose WHOLE-batch block busts the VMEM
        budget engages the kernel with its validated batch tile."""
        monkeypatch.delenv("DL4J_TPU_KERNEL_IMPL", raising=False)
        from deeplearning4j_tpu.nn.recurrent import LSTM as LSTMLayer

        b, h, t, n_in = 2048, 256, 2, 8
        xp = jnp.zeros((b, 4 * h), jnp.float32)
        u = jnp.zeros((h, 4 * h), jnp.float32)
        assert not klstm.fits_vmem(xp, u)
        assert klstm.fits_vmem(xp, u, 64)
        sig = klstm.shape_signature(b, h)
        db.commit(tdb.TuningKey.for_op("lstm_cell", sig, "float32"),
                  {"winner": {"label": "pallas:bt=64", "impl": "pallas",
                              "params": {"b_tile": 64}, "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        lyr = LSTMLayer(n_in=n_in, n_out=h)
        params, _ = lyr.initialize(jax.random.PRNGKey(0), (b, t, n_in))
        x = jnp.asarray(np.random.default_rng(4).normal(size=(b, t, n_in)),
                        jnp.float32)
        carry = lyr.init_carry(b)
        h0 = _counter("tuning.hits_total")
        out_tuned, _ = lyr.apply_seq(params, x, carry)
        assert _counter("tuning.hits_total") > h0
        with K.impl_scope("exact"):
            out_exact, _ = lyr.apply_seq(params, x, carry)
        assert float(jnp.max(jnp.abs(out_tuned - out_exact))) < 1e-4


class TestConfDefaulting:
    def test_remat_policy_defaults_from_db(self, db):
        """An unset remat_policy takes the committed conf-default winner
        at builder time; explicit choices and the env knob always win."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        db.commit(tdb.TuningKey.for_op("remat_policy", "conf-default",
                                       "any"),
                  {"winner": {"label": "policy:save_conv", "impl": "conf",
                              "params": {"remat_policy": "save_conv"},
                              "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        b = NeuralNetConfiguration.builder()
        assert b._remat_policy == "save_conv"
        # explicit wins over tuned
        b2 = NeuralNetConfiguration.builder().remat_policy("full")
        assert b2._remat_policy == "full"

    def test_env_knob_wins_over_db(self, db, monkeypatch):
        from deeplearning4j_tpu.config import Environment
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        db.commit(tdb.TuningKey.for_op("remat_policy", "conf-default",
                                       "any"),
                  {"winner": {"label": "policy:save_conv", "impl": "conf",
                              "params": {"remat_policy": "save_conv"},
                              "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        monkeypatch.setenv("DL4J_TPU_REMAT_POLICY", "save_dots")
        monkeypatch.setattr(Environment, "_instance", None)
        try:
            b = NeuralNetConfiguration.builder()
            assert b._remat_policy == "save_dots"
        finally:
            monkeypatch.setattr(Environment, "_instance", None)

    def test_stale_unknown_policy_ignored(self, db):
        """A database naming an unregistered policy degrades to the safe
        default — a stale DB must never crash a config build."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        db.commit(tdb.TuningKey.for_op("remat_policy", "conf-default",
                                       "any"),
                  {"winner": {"label": "policy:gone", "impl": "conf",
                              "params": {"remat_policy": "gone_policy"},
                              "ms": 1.0},
                   "candidates_digest": "t", "measured": []})
        b = NeuralNetConfiguration.builder()
        assert b._remat_policy is None

    def test_no_db_no_change(self, monkeypatch):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        monkeypatch.delenv("DL4J_TPU_TUNING_DB", raising=False)
        assert tuning.get_database() is None
        assert NeuralNetConfiguration.builder()._remat_policy is None


# ---------------------------------------------------------------------------
# the one-command sweep, cross-process (slow: subprocess jax imports)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestCrossProcess:
    def test_second_process_remeasures_nothing(self, tmp_path):
        """True cross-process warm read through benchmarks/autotune.py:
        the second PROCESS reports measurements_total == 0 and the
        identical winner (the CI smoke leg asserts the same plus the
        planted gates — this pins the pytest-visible contract)."""
        db_dir = str(tmp_path / "xproc-db")
        cmd = [sys.executable,
               os.path.join(REPO, "benchmarks", "autotune.py"),
               "--db", db_dir, "--spaces", "lstm_tiles",
               "--min-window", "0.005", "--json"]
        env = dict(os.environ)
        env.pop("DL4J_TPU_TUNING_DB", None)

        def run():
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  env=env, cwd=REPO, timeout=300)
            assert proc.returncode == 0, proc.stderr
            line = [ln for ln in proc.stdout.splitlines()
                    if ln.startswith("{")][-1]
            return json.loads(line)

        cold = run()
        assert cold["counters"].get("tuning.measurements_total", 0) > 0
        warm = run()
        assert warm["counters"].get("tuning.measurements_total", 0) == 0
        assert [s["status"] for s in warm["spaces"]] == ["warm"]
        assert warm["spaces"][0]["winner"] == cold["spaces"][0]["winner"]
