"""Diffusion U-Net zoo workload (ROADMAP item 5 chip, ISSUE 10 satellite).

One conv-heavy encoder/decoder DAG with skip connections, exercised two
ways: the per-layer conv cost model must attribute a resolution-split DAG,
and the compressed-DP path must train it end-to-end (the slow leg)."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data import MultiDataSet
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh
from deeplearning4j_tpu.zoo import DiffusionUNet


def _batch(rng, n=8, size=16, c=3):
    img = rng.standard_normal((n, size, size, c)).astype(np.float32)
    t = rng.uniform(0, 1, size=(n, 1)).astype(np.float32)
    noise = rng.standard_normal((n, size, size, c)).astype(np.float32)
    return MultiDataSet(features=[img, t], labels=[noise])


def test_unet_builds_and_fits_one_batch(rng):
    net = DiffusionUNet(input_shape=(16, 16, 3), base_channels=8,
                        depth=2).init()
    ds = _batch(rng)
    net.fit([ds], epochs=2)
    assert np.isfinite(float(net.score_value))
    # skip concats really feed the decoder: dec0_a consumes 8 (up) + 8
    # (skip) channels
    dec0 = next(n for n in net.conf.nodes if n.name == "dec0_a_conv")
    assert "dec0_cat" in dec0.inputs


def test_unet_conv_cost_model_attributes_the_dag(rng):
    net = DiffusionUNet(input_shape=(16, 16, 3), base_channels=8,
                        depth=2).init()
    rep = net.cost_report(batch_size=4, publish=False)
    tags = {r.layer for r in rep.rows}
    # encoder, bottleneck conditioning, and decoder rows all present
    assert any(t.startswith("enc0_down") for t in tags), tags
    assert any(t.startswith("mid_") for t in tags), tags
    assert any(t.startswith("dec0") for t in tags), tags
    assert any(t.startswith("t_embed") for t in tags), tags
    if rep.source == "xla":
        assert rep.totals.get("flops", 0) > 0
        # conv stacks dominate a U-Net: the conv rows must carry most of
        # the attributed FLOPs (the conv cost model's valid-tap walk)
        conv_flops = sum(r.flops_fwd + r.flops_bwd for r in rep.rows
                         if "_conv" in r.layer or r.layer == "noise")
        total_attr = sum(r.flops_fwd + r.flops_bwd for r in rep.rows)
        assert conv_flops > 0.5 * total_attr, (conv_flops, total_attr)


@pytest.mark.slow
@pytest.mark.multichip
def test_unet_compressed_dp_fit_end_to_end(rng):
    """The ISSUE's one slow leg: the diffusion U-Net trains through the
    encoded-gradient DP path (threshold scheme, adaptive sparsity) on the
    8-virtual-device mesh — loss decreases, the wire accounting reports,
    and the residual state matches the DAG's gradient structure."""
    net = DiffusionUNet(input_shape=(16, 16, 3), base_channels=8,
                        depth=2).init()
    pw = ParallelWrapper(net, mesh=TrainingMesh(data=8), skew_every=0,
                         grad_compression="threshold",
                         compression_target_sparsity=1e-2)
    batches = [_batch(rng) for _ in range(4)]
    first = None
    for _ in range(4):
        for ds in batches:
            pw.step_batch(ds)
            if first is None:
                first = float(net.score_value)
    last = float(net.score_value)
    assert np.isfinite(last)
    assert last < first, (first, last)
    stats = pw.compression_stats()
    assert stats["wire_bytes"] > 0 and stats["dense_bytes"] > 0
    assert stats["threshold"] > 0
    # residual mirrors the graph's per-node gradient trees (dict-keyed)
    res = pw._comp_state["residual"]
    assert set(res.keys()) == set(net.params.keys())
    leading = {np.shape(l)[0]
               for l in jax.tree_util.tree_leaves(res)}
    assert leading == {8}  # worker-stacked
