"""Multi-device tests on the 8-virtual-device CPU mesh — the reference's
'distributed without a cluster' strategy (SURVEY.md §4: embedded transport /
local[N]); here: xla_force_host_platform_device_count=8."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import ParallelInference, ParallelWrapper, TrainingMesh


def _net(seed=42, updater=None):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater or Sgd(0.1))
        .list()
        .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
        .layer(OutputLayer(n_in=16, n_out=3))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _blobs(rng, n=256, n_classes=3, dim=4):
    centers = rng.standard_normal((n_classes, dim)) * 3.0
    ys = rng.integers(0, n_classes, n)
    xs = (centers[ys] + rng.standard_normal((n, dim))).astype(np.float32)
    return xs, np.eye(n_classes, dtype=np.float32)[ys]


def test_mesh_construction(devices):
    m = TrainingMesh(data=8)
    assert m.n_devices == 8
    m2 = TrainingMesh(data=4, model=2)
    assert m2.mesh.shape == {"data": 4, "model": 2, "seq": 1, "pipe": 1}
    m3 = TrainingMesh(data=2, model=2, pipe=2)
    assert m3.mesh.shape == {"data": 2, "model": 2, "seq": 1, "pipe": 2}
    assert m3.n_devices == 8
    with pytest.raises(ValueError):
        TrainingMesh(data=16)


def test_mesh_sharding_placement(devices):
    m = TrainingMesh(data=8)
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    xs = m.shard_batch(x)
    assert len(xs.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(xs), x)


def test_parallel_wrapper_matches_single_device(rng, devices):
    """DP over 8 devices must be numerically equivalent to single-device
    training on the same global batch (sync averaging is exact)."""
    xs, ys = _blobs(rng, n=64)
    single = _net()
    parallel_net = _net()
    pw = ParallelWrapper(parallel_net, mesh=TrainingMesh(data=8))
    it = ArrayDataSetIterator(xs, ys, batch=64)
    single.fit(it, epochs=3)
    pw.fit(ArrayDataSetIterator(xs, ys, batch=64), epochs=3)
    np.testing.assert_allclose(
        np.asarray(single.params[0]["W"]),
        np.asarray(parallel_net.params[0]["W"]),
        rtol=2e-4, atol=1e-5,
    )


def test_parallel_wrapper_learns(rng, devices):
    xs, ys = _blobs(rng)
    net = _net(updater=Adam(0.01))
    pw = ParallelWrapper(net, mesh=TrainingMesh(data=8))
    pw.fit(ArrayDataSetIterator(xs, ys, batch=64, shuffle=True), epochs=20)
    ev = net.evaluate(ArrayDataSetIterator(xs, ys, batch=64))
    assert ev.accuracy() > 0.95


def test_parallel_wrapper_pads_ragged_batch(rng, devices):
    xs, ys = _blobs(rng, n=30)  # not divisible by 8
    net = _net()
    pw = ParallelWrapper(net, mesh=TrainingMesh(data=8))
    pw.fit(ArrayDataSetIterator(xs, ys, batch=30), epochs=1)
    assert np.isfinite(net.get_score())


def test_parallel_inference_matches_local(rng, devices):
    xs, ys = _blobs(rng, n=37)  # ragged on purpose
    net = _net(updater=Adam(0.01))
    net.fit(ArrayDataSetIterator(xs, ys, batch=37), epochs=5)
    local = np.asarray(net.output(xs))
    pi = ParallelInference(net, mesh=TrainingMesh(data=8))
    dist = pi.output(xs)
    np.testing.assert_allclose(local, dist, rtol=1e-5, atol=1e-6)


def test_gradients_allreduced_not_per_shard(rng, devices):
    """The sharded step must produce the GLOBAL-batch gradient: train one step
    on a batch whose halves are different; result must equal single-device."""
    xs, ys = _blobs(rng, n=16)
    a, b = _net(seed=9), _net(seed=9)
    a.fit(xs, ys)
    pw = ParallelWrapper(b, mesh=TrainingMesh(data=8))
    pw.fit(ArrayDataSetIterator(xs, ys, batch=16), epochs=1)
    np.testing.assert_allclose(
        np.asarray(a.params[1]["W"]), np.asarray(b.params[1]["W"]),
        rtol=2e-5, atol=1e-6,
    )


def test_tensor_parallel_dense_sharding(devices):
    """TP: shard a big dense layer's W over the 'model' axis; forward must be
    numerically identical to replicated execution (GSPMD all-gathers as
    needed). This is the mesh-axis TP the reference lacks (SURVEY §2.3)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = TrainingMesh(data=4, model=2)
    key = jax.random.PRNGKey(0)
    W = jax.random.normal(key, (64, 128))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 64))

    def f(x, W):
        return jnp.tanh(x @ W).sum(axis=-1)

    ref = f(x, W)
    Ws = jax.device_put(W, NamedSharding(m.mesh, P(None, "model")))
    xs = jax.device_put(x, NamedSharding(m.mesh, P("data", None)))
    out = jax.jit(f)(xs, Ws)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-5)


def test_ragged_batch_gradient_exact(rng, devices):
    """Padded rows carry zero loss weight: a ragged global batch must produce
    the same update as single-device training on the same examples."""
    xs, ys = _blobs(rng, n=13)  # 13 % 8 != 0
    a, b = _net(seed=5), _net(seed=5)
    a.fit(xs, ys)
    pw = ParallelWrapper(b, mesh=TrainingMesh(data=8))
    pw.fit(ArrayDataSetIterator(xs, ys, batch=13), epochs=1)
    np.testing.assert_allclose(
        np.asarray(a.params[0]["W"]), np.asarray(b.params[0]["W"]),
        rtol=2e-5, atol=1e-6,
    )


def test_fit_array_epochs_honored(rng):
    xs, ys = _blobs(rng, n=32)
    net = _net()
    net.fit(xs, ys, epochs=5)
    assert net.iteration == 5


def test_parallel_inference_dynamic_batching(rng):
    """output_async coalesces concurrent requests into shared device batches
    and routes each caller its own slice (ParallelInference queue parity)."""
    import threading

    from deeplearning4j_tpu.nn import (
        InputType, MultiLayerNetwork, NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import ParallelInference

    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, batch_timeout_ms=20.0)
    xs = [rng.standard_normal((n, 4)).astype(np.float32) for n in (1, 3, 2, 5)]
    expected = [np.asarray(net.output(x)) for x in xs]

    futs = [pi.output_async(x) for x in xs]
    for f, exp in zip(futs, expected):
        np.testing.assert_allclose(np.asarray(f.result(timeout=30)), exp,
                                   atol=1e-5)

    # concurrent submitters
    results = {}

    def submit(i):
        results[i] = pi.output_async(xs[i % len(xs)]).result(timeout=30)

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, r in results.items():
        np.testing.assert_allclose(np.asarray(r), expected[i % len(xs)],
                                   atol=1e-5)
    pi.shutdown()


def test_parallel_inference_bad_request_fails_batch_not_worker(rng):
    from deeplearning4j_tpu.nn import (
        InputType, MultiLayerNetwork, NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import ParallelInference

    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, batch_timeout_ms=1.0)
    bad = pi.output_async(rng.standard_normal((2, 7)).astype(np.float32))
    with pytest.raises(Exception):
        bad.result(timeout=30)
    # the worker survived: a good request still completes
    good = pi.output_async(rng.standard_normal((2, 4)).astype(np.float32))
    assert np.asarray(good.result(timeout=30)).shape == (2, 3)
    pi.shutdown()
    with pytest.raises(RuntimeError, match="shut down"):
        pi.output_async(rng.standard_normal((1, 4)).astype(np.float32))
