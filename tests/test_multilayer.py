"""MultiLayerNetwork end-to-end tests — MultiLayerTest / integration parity
(SURVEY.md §4: small-model training to target accuracy)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator, DataSet, MnistDataSetIterator
from deeplearning4j_tpu.nn import (
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    LossLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.listeners import CollectScoresListener
from deeplearning4j_tpu.nn.updaters import Adam, Sgd


def _mlp_conf(n_in=4, n_hidden=16, n_out=3, updater=None, **kw):
    return (
        NeuralNetConfiguration.builder()
        .seed(42)
        .updater(updater or Adam(0.01))
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=n_hidden, activation="relu"))
        .layer(OutputLayer(n_in=n_hidden, n_out=n_out, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.feed_forward(n_in))
        .build()
    )


def _blobs(rng, n=256, n_classes=3, dim=4, spread=3.0):
    centers = rng.standard_normal((n_classes, dim)) * spread
    ys = rng.integers(0, n_classes, n)
    xs = centers[ys] + rng.standard_normal((n, dim))
    return xs.astype(np.float32), np.eye(n_classes, dtype=np.float32)[ys]


def test_init_shapes_and_param_count():
    net = MultiLayerNetwork(_mlp_conf()).init()
    assert net.params[0]["W"].shape == (4, 16)
    assert net.params[0]["b"].shape == (16,)
    assert net.params[1]["W"].shape == (16, 3)
    assert net.num_params() == 4 * 16 + 16 + 16 * 3 + 3


def test_fit_reduces_score_and_learns_blobs(rng):
    xs, ys = _blobs(rng)
    net = MultiLayerNetwork(_mlp_conf()).init()
    initial = net.score(x=xs, y=ys)
    it = ArrayDataSetIterator(xs, ys, batch=32, shuffle=True)
    net.fit(it, epochs=30)
    final = net.score(x=xs, y=ys)
    assert final < initial * 0.3, f"{initial} -> {final}"
    preds = np.asarray(net.output(xs))
    acc = (preds.argmax(-1) == ys.argmax(-1)).mean()
    assert acc > 0.95, acc


def test_output_is_probabilities(rng):
    xs, ys = _blobs(rng, n=32)
    net = MultiLayerNetwork(_mlp_conf()).init()
    out = np.asarray(net.output(xs))
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)
    assert (out >= 0).all()


def test_evaluate_returns_evaluation(rng):
    xs, ys = _blobs(rng)
    net = MultiLayerNetwork(_mlp_conf()).init()
    it = ArrayDataSetIterator(xs, ys, batch=64)
    net.fit(it, epochs=20)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.9
    assert ev.confusion_matrix().sum() == len(xs)
    assert "Accuracy" in ev.stats()


def test_listeners_collect_scores(rng):
    xs, ys = _blobs(rng, n=64)
    net = MultiLayerNetwork(_mlp_conf()).init()
    collector = CollectScoresListener()
    net.set_listeners(collector)
    net.fit(ArrayDataSetIterator(xs, ys, batch=32), epochs=2)
    assert len(collector.scores) == 4  # 2 batches x 2 epochs
    assert all(np.isfinite(s) for _, s in collector.scores)


def test_feed_forward_exposes_activations(rng):
    xs, _ = _blobs(rng, n=8)
    net = MultiLayerNetwork(_mlp_conf()).init()
    acts = net.feed_forward(xs)
    assert len(acts) == 3  # input + 2 layers
    assert acts[1].shape == (8, 16)
    assert acts[2].shape == (8, 3)


def test_per_layer_updater_override(rng):
    conf = (
        NeuralNetConfiguration.builder()
        .updater(Adam(0.01))
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="tanh", updater=Sgd(0.0)))
        .layer(OutputLayer(n_in=8, n_out=3))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    frozen_before = np.asarray(net.params[0]["W"]).copy()
    head_before = np.asarray(net.params[1]["W"]).copy()
    xs, ys = _blobs(rng, n=64)
    net.fit(ArrayDataSetIterator(xs, ys, batch=32), epochs=2)
    np.testing.assert_array_equal(np.asarray(net.params[0]["W"]), frozen_before)
    assert not np.allclose(np.asarray(net.params[1]["W"]), head_before)


def test_l2_regularization_shrinks_weights(rng):
    xs, ys = _blobs(rng, n=128)

    def train(l2):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Sgd(0.05))
            .l2(l2)
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        net.fit(ArrayDataSetIterator(xs, ys, batch=64), epochs=30)
        return float(jnp.sum(net.params[0]["W"] ** 2))

    assert train(0.5) < train(0.0) * 0.8


def test_json_roundtrip_reproduces_network(rng):
    conf = _mlp_conf()
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    net1 = MultiLayerNetwork(conf).init()
    net2 = MultiLayerNetwork(conf2).init()
    xs, _ = _blobs(rng, n=8)
    np.testing.assert_allclose(
        np.asarray(net1.output(xs)), np.asarray(net2.output(xs)), rtol=1e-6
    )


def test_dropout_changes_training_but_not_inference(rng):
    conf = (
        NeuralNetConfiguration.builder()
        .updater(Sgd(0.1))
        .list()
        .layer(DenseLayer(n_in=4, n_out=64, activation="relu", dropout=0.5))
        .layer(OutputLayer(n_in=64, n_out=3))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    xs, _ = _blobs(rng, n=16)
    a = np.asarray(net.output(xs))
    b = np.asarray(net.output(xs))
    np.testing.assert_array_equal(a, b)  # inference is deterministic


def test_batchnorm_network_trains_and_infers(rng):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater(Adam(0.01))
        .list()
        .layer(DenseLayer(n_in=4, n_out=16))
        .layer(BatchNormalization())
        .layer(ActivationLayer(activation="relu"))
        .layer(OutputLayer(n_in=16, n_out=3))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    xs, ys = _blobs(rng)
    net.fit(ArrayDataSetIterator(xs, ys, batch=64, shuffle=True), epochs=20)
    # running stats must have moved off their init values
    assert not np.allclose(np.asarray(net.states[1]["mean"]), 0.0)
    ev = net.evaluate(ArrayDataSetIterator(xs, ys, batch=64))
    assert ev.accuracy() > 0.9


def test_regression_network(rng):
    xs = rng.standard_normal((256, 3)).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5]], dtype=np.float32)
    ys = xs @ w_true + 0.01 * rng.standard_normal((256, 1)).astype(np.float32)
    conf = (
        NeuralNetConfiguration.builder()
        .updater(Adam(0.05))
        .list()
        .layer(DenseLayer(n_in=3, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=1, loss="mse", activation="identity"))
        .set_input_type(InputType.feed_forward(3))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(ArrayDataSetIterator(xs, ys, batch=64, shuffle=True), epochs=50)
    ev = net.evaluate_regression(ArrayDataSetIterator(xs, ys, batch=64))
    assert ev.r_squared() > 0.95, ev.stats()


# ---------------------------------------------------------------- LeNet MNIST


def _lenet_conf(compute_dtype="float32"):
    """LeNet-5 (BASELINE config #1; reference: dl4j-examples LeNet MNIST)."""
    return (
        NeuralNetConfiguration.builder()
        .seed(123)
        .updater(Adam(1e-3))
        .compute_dtype(compute_dtype)
        .list()
        .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), padding="VALID", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), padding="VALID", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu", n_in=4 * 4 * 50))
        .layer(OutputLayer(n_in=500, n_out=10, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )


@pytest.mark.slow
def test_lenet_mnist_trains_to_high_accuracy():
    train_it = MnistDataSetIterator(batch=64, train=True, n_examples=2048)
    test_it = MnistDataSetIterator(batch=256, train=False, n_examples=512)
    net = MultiLayerNetwork(_lenet_conf()).init()
    net.fit(train_it, epochs=6)
    ev = net.evaluate(test_it)
    assert ev.accuracy() > 0.97, f"LeNet accuracy {ev.accuracy():.4f}\n{ev.stats()}"


def test_lenet_shapes_one_step():
    net = MultiLayerNetwork(_lenet_conf()).init()
    x = np.zeros((2, 28, 28, 1), dtype=np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)
    ds = DataSet(x, np.eye(10, dtype=np.float32)[[0, 1]])
    net.fit(ds.features, ds.labels)
    assert np.isfinite(net.get_score())
