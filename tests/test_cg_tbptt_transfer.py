"""ComputationGraph truncated BPTT + transfer-learning surgery.

Reference parity (VERDICT r1 missing #4): ComputationGraph.java's
doTruncatedBPTT/rnnTimeStep fields and TransferLearning.GraphBuilder —
path-cite, mount empty this round.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    ComputationGraph,
    ComputationGraphConfiguration,
    InputType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.transfer import (
    FineTuneConfiguration,
    FrozenLayer,
    TransferLearning,
)
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.vertices import MergeVertex


def _recurrent_graph(tbptt=0, hidden=12):
    gb = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
          .graph_builder()
          .add_inputs("in")
          .add_layer("lstm", LSTM(n_in=4, n_out=hidden), "in")
          .add_layer("out", RnnOutputLayer(n_in=hidden, n_out=4,
                                           loss="mcxent",
                                           activation="softmax"), "lstm")
          .set_outputs("out")
          .set_input_types(InputType.recurrent(4, 20)))
    if tbptt:
        gb.tbptt_length(tbptt)
    return gb.build()


def _shift_task(rng, n=48, T=20):
    """Predict the previous token (one-step memory)."""
    ids = rng.integers(0, 4, size=(n, T))
    x = np.eye(4, dtype=np.float32)[ids]
    shifted = np.roll(ids, 1, axis=1)
    shifted[:, 0] = ids[:, 0]
    y = np.eye(4, dtype=np.float32)[shifted]
    return x, y


class TestCGTbptt:
    def test_tbptt_trains_and_counts_segments(self, rng):
        x, y = _shift_task(rng)
        net = ComputationGraph(_recurrent_graph(tbptt=5)).init()
        s0 = net.score(x=x, y=y)
        it0 = net.iteration
        net.fit(x, y, epochs=1)
        assert net.iteration - it0 == 4  # T=20 / k=5 segments, one update each
        net.fit(x, y, epochs=30)
        assert net.score(x=x, y=y) < s0 * 0.55, (s0, net.score(x=x, y=y))

    def test_tbptt_matches_full_bptt_quality(self, rng):
        """Carries flow across segments: TBPTT must still learn the one-step
        memory task (which needs cross-segment state)."""
        x, y = _shift_task(rng)
        net = ComputationGraph(_recurrent_graph(tbptt=5)).init()
        net.fit(x, y, epochs=40)
        pred = np.argmax(np.asarray(net.output(x)), axis=-1)
        target = np.argmax(y, axis=-1)
        acc = (pred[:, 1:] == target[:, 1:]).mean()  # skip t=0 (no history)
        assert acc > 0.9, acc

    def test_rnn_time_step_matches_whole_sequence(self, rng):
        x, y = _shift_task(rng, n=8)
        net = ComputationGraph(_recurrent_graph()).init()
        net.fit(x, y, epochs=3)
        whole = np.asarray(net.output(x))            # (B,T,4)
        net.rnn_clear_previous_state()
        steps = []
        for t in range(x.shape[1]):
            steps.append(np.asarray(net.rnn_time_step(x[:, t])))
        np.testing.assert_allclose(np.stack(steps, axis=1), whole,
                                   atol=1e-5, rtol=1e-4)

    def test_tbptt_json_roundtrip(self):
        conf = _recurrent_graph(tbptt=5)
        back = ComputationGraphConfiguration.from_json(conf.to_json())
        assert back.tbptt_length == 5


def _two_input_recurrent_graph(tbptt=0, hidden=12):
    gb = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
          .graph_builder()
          .add_inputs("ina", "inb")
          .add_layer("la", LSTM(n_in=4, n_out=hidden), "ina")
          .add_layer("lb", LSTM(n_in=4, n_out=hidden), "inb")
          .add_vertex("m", MergeVertex(), "la", "lb")
          .add_layer("out", RnnOutputLayer(n_in=2 * hidden, n_out=4,
                                           loss="mcxent",
                                           activation="softmax"), "m")
          .set_outputs("out")
          .set_input_types(InputType.recurrent(4, 20),
                           InputType.recurrent(4, 20)))
    if tbptt:
        gb.tbptt_length(tbptt)
    return gb.build()


class TestCGTbpttMultiInputMasks:
    """Per-input masks on a multi-input recurrent CG (VERDICT r2 #3): each
    input stream carries its OWN (B,T) mask through both full BPTT and the
    TBPTT segment loop (MultiDataSet.features_masks → dict masks)."""

    def _task(self, rng, n=48, T=20):
        from deeplearning4j_tpu.data import MultiDataSet

        xa, y = _shift_task(rng, n=n, T=T)          # signal stream
        xb = rng.normal(size=(n, T, 4)).astype(np.float32)  # noise stream
        mask_a = np.ones((n, T), np.float32)
        mask_b = np.zeros((n, T), np.float32)       # noise fully masked out
        mask_b[:, 0] = 1.0                          # (all-zero would be degenerate)
        mds = MultiDataSet(features=[xa, xb], labels=[y],
                           features_masks=[mask_a, mask_b])
        return mds, xa, xb, y

    def test_per_input_masks_tbptt_matches_full_bptt(self, rng):
        mds, xa, xb, y = self._task(rng)
        target = np.argmax(y, axis=-1)

        accs = {}
        # equal UPDATE counts: full BPTT does 1 update/epoch, TBPTT k=5 does
        # T/k = 4 — so 160 vs 40 epochs both yield 160 updater steps
        for name, tbptt, epochs in (("full", 0, 160), ("tbptt", 5, 40)):
            net = ComputationGraph(_two_input_recurrent_graph(tbptt)).init()
            it0 = net.iteration
            net.fit([mds], epochs=epochs)
            assert net.iteration - it0 == 160
            pred = np.argmax(np.asarray(net.output(xa, xb)), axis=-1)
            accs[name] = (pred[:, 1:] == target[:, 1:]).mean()
        assert accs["full"] > 0.85, accs
        assert accs["tbptt"] > 0.85, accs  # carries + masks survive segmenting

    def test_mask_dict_changes_loss(self, rng):
        """The per-input mask must actually gate its own stream: masking the
        noise stream differently changes the compiled loss."""
        from deeplearning4j_tpu.data import MultiDataSet

        mds, xa, xb, y = self._task(rng, n=8)
        net = ComputationGraph(_two_input_recurrent_graph()).init()
        net.fit([mds], epochs=1)
        s_masked = float(net.score_value)
        net2 = ComputationGraph(_two_input_recurrent_graph()).init()
        mds_open = MultiDataSet(features=[xa, xb], labels=[y],
                                features_masks=[np.ones_like(xa[..., 0]),
                                                np.ones_like(xb[..., 0])])
        net2.fit([mds_open], epochs=1)
        assert not np.isclose(s_masked, float(net2.score_value)), s_masked


def _backbone_graph():
    return (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .graph_builder()
            .add_inputs("in")
            .add_layer("f1", DenseLayer(n_in=4, n_out=16, activation="relu"),
                       "in")
            .add_layer("f2", DenseLayer(n_in=16, n_out=8, activation="relu"),
                       "f1")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "f2")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())


def _blob_data(rng, classes=3, n=192):
    centers = rng.standard_normal((classes, 4)) * 3.0
    ys = rng.integers(0, classes, n)
    xs = (centers[ys] + rng.standard_normal((n, 4))).astype(np.float32)
    return xs, np.eye(classes, dtype=np.float32)[ys], ys


class TestCGTransfer:
    def test_frozen_backbone_finetunes(self, rng):
        xs, yoh, ys = _blob_data(rng)
        base = ComputationGraph(_backbone_graph()).init()
        base.fit(xs, yoh, epochs=60)
        new = (TransferLearning.GraphBuilder(base)
               .fine_tune_configuration(FineTuneConfiguration(updater=Adam(0.005)))
               .set_feature_extractor("f2")
               .build())
        assert isinstance(new.conf.nodes[0].node, FrozenLayer)  # f1 (upstream)
        assert isinstance(new.conf.nodes[1].node, FrozenLayer)  # f2 (named)
        assert not isinstance(new.conf.nodes[2].node, FrozenLayer)  # head
        f1_before = np.asarray(new.params["f1"]["W"]).copy()
        head_before = np.asarray(new.params["out"]["W"]).copy()
        new.fit(xs, yoh, epochs=40)
        np.testing.assert_array_equal(np.asarray(new.params["f1"]["W"]),
                                      f1_before)
        assert not np.allclose(np.asarray(new.params["out"]["W"]), head_before)
        acc = (np.argmax(np.asarray(new.output(xs)), 1) == ys).mean()
        assert acc > 0.85, acc

    def test_replace_head_new_classes(self, rng):
        xs, yoh, ys = _blob_data(rng)
        base = ComputationGraph(_backbone_graph()).init()
        base.fit(xs, yoh, epochs=60)
        f1_trained = np.asarray(base.params["f1"]["W"]).copy()
        new = (TransferLearning.GraphBuilder(base)
               .set_feature_extractor("f2")
               .remove_vertex_and_connections("out")
               .add_layer("new_out", OutputLayer(n_in=8, n_out=5,
                                                 loss="mcxent",
                                                 activation="softmax"), "f2")
               .set_outputs("new_out")
               .build())
        assert new.conf.outputs == ["new_out"]
        assert new.params["new_out"]["W"].shape == (8, 5)
        # backbone params carried over (then frozen)
        np.testing.assert_array_equal(
            np.asarray(new.params["f1"]["W"]), f1_trained)
        xs5, yoh5, ys5 = _blob_data(rng, classes=5)
        # 5-class blobs live in a different input space scale — just check
        # training the new head works end to end
        new.fit(xs5, yoh5, epochs=5)
        assert np.isfinite(float(new.score_value))

    def test_n_out_replace_ripples(self, rng):
        base = ComputationGraph(_backbone_graph()).init()
        new = (TransferLearning.GraphBuilder(base)
               .n_out_replace("f2", 12)
               .build())
        assert new.params["f2"]["W"].shape == (16, 12)
        assert new.params["out"]["W"].shape == (12, 3)

    def test_remove_downstream_closure(self, rng):
        base = ComputationGraph(_backbone_graph()).init()
        new = (TransferLearning.GraphBuilder(base)
               .remove_vertex_and_connections("f2")
               .add_layer("new_out", OutputLayer(n_in=16, n_out=3), "f1")
               .set_outputs("new_out")
               .build())
        names = {n.name for n in new.conf.nodes}
        assert names == {"f1", "new_out"}
