"""Unified telemetry + training-health monitors (ISSUE 4,
docs/OBSERVABILITY.md): registry semantics, span attribution + cross-process
merge, subsystem instrumentation, /metrics + /healthz endpoints, health
anomaly detection, and the telemetry-aware crash dump."""

import json
import os
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.health import TrainingHealthMonitor


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test sees a fresh, enabled registry and leaves it enabled.

    Collector wiring is saved/cleared/restored too: collectors survive
    ``reset()`` by design, so any EARLIER test file that installed the
    default collectors (e.g. the elastic suite asserting /metrics gauges)
    would otherwise leak scrape-time series into this file's snapshot
    assertions. Tests here that need the defaults re-install them (the
    module flag is reset alongside)."""
    tele = tm.get_telemetry()
    tele.reset()
    was = tele.enabled
    saved_collectors = list(tele._collectors)
    saved_flag = tm._defaults_installed
    tele._collectors.clear()
    tm._defaults_installed = False
    tele.enabled = True
    yield tele
    tele.enabled = was
    tele._collectors[:] = saved_collectors
    tm._defaults_installed = saved_flag
    tele.reset()


def _tiny_net(sync_every=1, seed=0):
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
            .sync_every(sync_every).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _batch(rng, n=16):
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return x, y


class TestRegistry:
    def test_counters_gauges_histograms(self, _clean_registry):
        tele = _clean_registry
        tm.counter("a.total", 2)
        tm.counter("a.total", 3)
        tm.counter("a.total", 1, worker="0")
        tm.gauge("g.depth", 7)
        tm.observe("d.seconds", 0.02)
        tm.observe("d.seconds", 0.04)
        snap = tele.snapshot()
        assert snap["counters"]["a.total"] == 5
        assert snap["counters"]["a.total{worker=0}"] == 1
        assert snap["gauges"]["g.depth"] == 7
        h = snap["histograms"]["d.seconds"]
        assert h["count"] == 2 and abs(h["sum"] - 0.06) < 1e-9
        assert h["min"] == 0.02 and h["max"] == 0.04

    def test_disabled_records_nothing(self, _clean_registry):
        tele = _clean_registry
        tele.enabled = False
        tm.counter("x.total")
        tm.gauge("g", 1)
        tm.observe("h", 1.0)
        with tm.span("s"):
            pass
        tm.instant("i")
        tele.enabled = True
        snap = tele.snapshot()
        assert not snap["counters"] and not snap["gauges"]
        assert not tele.drain_events()

    def test_span_nesting_and_attribution(self, _clean_registry):
        tele = _clean_registry
        with tm.span("outer", kind="t"):
            with tm.span("inner"):
                pass
        events = tele.drain_events()
        by_name = {e["name"]: e for e in events}
        assert by_name["inner"]["args"]["parent"] == "outer"
        assert by_name["outer"]["pid"] == os.getpid()
        assert by_name["outer"]["tname"] == "MainThread"
        # inner completed first and sits inside outer's window
        assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]

    def test_merge_events_keeps_foreign_pids(self, _clean_registry):
        tele = _clean_registry
        fake = [{"name": "etl.transform_chunk", "ph": "X", "pid": 99999,
                 "tid": 1, "tname": "MainThread", "ts": 123, "dur": 45}]
        assert tele.merge_events(fake) == 1
        trace = tele.chrome_trace()
        assert any(e["pid"] == 99999 and e["ph"] == "X"
                   for e in trace["traceEvents"])

    def test_chrome_trace_schema_and_metadata(self, _clean_registry):
        tele = _clean_registry
        with tm.span("work", n=1):
            pass
        tm.instant("marker")
        trace = tele.chrome_trace()
        events = trace["traceEvents"]
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in events)
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in events)
        for e in events:
            assert isinstance(e["name"], str) and e["ph"] in ("X", "i", "M")
            if e["ph"] == "X":
                assert e["dur"] >= 0 and e["ts"] >= 0
        # round-trips through JSON (Perfetto-loadable)
        assert json.loads(json.dumps(trace))["traceEvents"]

    def test_event_ring_bounds_memory(self, _clean_registry):
        tele = _clean_registry
        tele.max_events = 10
        for i in range(25):
            tele.event(f"e{i}", 0, 1)
        assert len(tele.drain_events()) == 10
        assert tele.snapshot()["counters"][
            "telemetry.events_dropped_total"] == 15

    def test_prometheus_text_format(self, _clean_registry):
        tm.counter("c.total", 3, model="mln")
        tm.gauge("g.val", 1.5)
        tm.observe("h.seconds", 0.2)
        tm.set_health("training.finite", True)
        text = _clean_registry.prometheus_text()
        assert "# TYPE dl4j_c_total counter" in text
        assert 'dl4j_c_total{model="mln"} 3' in text
        assert "dl4j_g_val 1.5" in text
        assert "# TYPE dl4j_h_seconds histogram" in text
        assert 'dl4j_h_seconds_bucket{le="+Inf"} 1' in text
        assert "dl4j_h_seconds_count 1" in text
        assert 'dl4j_health_check{check="training.finite"} 1' in text

    def test_prometheus_label_values_escaped(self, _clean_registry):
        """ISSUE 5 satellite: label values escape backslash, double quote,
        and newline per the exposition format — a raw newline in a value
        (e.g. a model description) would split the sample line and make
        the whole scrape unparsable."""
        tm.counter("esc.total", 1, path="C:\\tmp", note='say "hi"',
                   multi="line one\nline two")
        text = _clean_registry.prometheus_text()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("dl4j_esc_total"))
        assert 'path="C:\\\\tmp"' in line
        assert 'note="say \\"hi\\""' in line
        assert 'multi="line one\\nline two"' in line
        # the sample stayed ONE line ending in its value
        assert line.endswith(" 1")
        assert "line two" not in [ln.strip() for ln in text.splitlines()]

    def test_collectors_feed_scrapes(self, _clean_registry):
        tele = _clean_registry
        tele.register_collector(lambda: [("my.metric", {"k": "v"}, 42)])
        assert 'dl4j_my_metric{k="v"} 42' in tele.prometheus_text()
        assert tele.snapshot()["gauges"]["my.metric{k=v}"] == 42

    def test_broken_collector_never_breaks_scrape(self, _clean_registry):
        tele = _clean_registry

        def broken():
            raise RuntimeError("boom")

        tele.register_collector(broken)
        tm.counter("ok.total")
        assert "dl4j_ok_total" in tele.prometheus_text()


class TestInstrumentation:
    def test_fit_records_step_spans_and_counters(self, rng, _clean_registry):
        net = _tiny_net()
        x, y = _batch(rng)
        for _ in range(3):
            net._fit_batch(x, y)
        snap = _clean_registry.snapshot()
        assert snap["counters"]["train.steps_total{model=mln}"] == 3
        names = [e["name"] for e in _clean_registry.drain_events()]
        assert names.count("mln.train_step") == 3
        # first step retraced -> compile attribution sub-spans
        assert "xla.jaxpr_trace" in names
        assert snap["counters"]["xla.step_retraces_total"] >= 1
        assert snap["histograms"]["train.step_seconds{model=mln}"][
            "count"] == 2  # N-1 cadence intervals

    def test_cg_fit_records_spans(self, rng, _clean_registry):
        from deeplearning4j_tpu.nn import (InputType,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=8,
                                           activation="relu"), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=2), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4)).build())
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

        net = ComputationGraph(conf).init()
        x, y = _batch(rng)
        net._fit_batch([x], [y])
        snap = _clean_registry.snapshot()
        assert snap["counters"]["train.steps_total{model=cg}"] == 1
        assert any(e["name"] == "cg.train_step"
                   for e in _clean_registry.drain_events())

    def test_disabled_fit_records_nothing(self, rng, _clean_registry):
        net = _tiny_net()
        x, y = _batch(rng)
        _clean_registry.enabled = False
        net._fit_batch(x, y)
        _clean_registry.enabled = True
        assert not _clean_registry.drain_events()
        assert not _clean_registry.snapshot()["counters"]

    def test_prefetch_gauges_and_thread_spans(self, rng, _clean_registry):
        from deeplearning4j_tpu.data import (ArrayDataSetIterator,
                                             AsyncDataSetIterator)

        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        it = AsyncDataSetIterator(
            ArrayDataSetIterator(x, y, batch=8), buffer_size=2)
        assert sum(1 for _ in it) == 4
        snap = _clean_registry.snapshot()
        assert snap["counters"]["prefetch.batches_total"] == 4
        assert "prefetch.queue_depth" in snap["gauges"]
        events = _clean_registry.drain_events()
        etl = [e for e in events if e["name"] == "prefetch.etl_wait"]
        assert etl and all(
            e["tname"] == "dl4j-tpu-prefetch" for e in etl)
        # prefetch thread rows are distinct from the main thread's
        main_tid = [e["tid"] for e in events
                    if e["tname"] == "MainThread"]
        assert all(e["tid"] not in main_tid for e in etl)

    @pytest.mark.skipif(not hasattr(os, "fork"), reason="needs fork")
    def test_mp_etl_worker_spans_merge_with_child_pids(self, _clean_registry):
        from deeplearning4j_tpu.datavec import (MultiProcessTransformExecutor,
                                                Schema, TransformProcess)

        sb = Schema.builder()
        sb.add_column_double("v")
        tp = (TransformProcess.builder(sb.build())
              .double_math_op("v", "multiply", 3.0).build())
        records = [[float(i)] for i in range(64)]
        ex = MultiProcessTransformExecutor(tp, num_workers=2,
                                           min_records_per_worker=8)
        out = ex.execute(records)
        assert out == [[i * 3.0] for i in range(64)]
        events = _clean_registry.drain_events()
        chunk_pids = {e["pid"] for e in events
                      if e["name"] == "etl.transform_chunk"}
        assert len(chunk_pids) == 2  # one per worker process
        assert os.getpid() not in chunk_pids
        assert any(e["name"] == "etl.execute"
                   and e["pid"] == os.getpid() for e in events)
        snap = _clean_registry.snapshot()
        assert snap["counters"]["etl.records_total"] == 64

    def test_parallel_wrapper_skew_probe(self, rng, _clean_registry):
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh

        net = _tiny_net()
        x = rng.standard_normal((16, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        mesh = TrainingMesh(data=4, devices=jax.devices()[:4])
        pw = ParallelWrapper(net, mesh=mesh, skew_every=2)
        pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=2)
        snap = _clean_registry.snapshot()
        assert "parallel.straggler_skew_seconds" in snap["gauges"]
        assert snap["gauges"]["parallel.replicas"] == 4
        events = _clean_registry.drain_events()
        replica_rows = {e["tid"] for e in events
                        if e["name"] == "parallel.replica_step"}
        assert len(replica_rows) == 4
        assert any(e["name"] == "parallel.step" for e in events)

    def test_coalesced_flush_span_carries_window(self, rng, _clean_registry):
        net = _tiny_net(sync_every=4)
        net.set_listeners(_CountingListener())
        x, y = _batch(rng)
        for _ in range(4):
            net._fit_batch(x, y)
        events = _clean_registry.drain_events()
        flushes = [e for e in events if e["name"] == "listeners.flush"]
        assert len(flushes) == 1
        assert flushes[0]["args"]["window"] == 4
        assert any(e["name"] == "listeners.loss_fetch" for e in events)


class _CountingListener:
    def __init__(self):
        self.n = 0

    def iteration_done(self, model, iteration, epoch):
        self.n += 1


class TestEndpoints:
    def _server(self, storage=None):
        from deeplearning4j_tpu.util.ui_server import UIServer

        ui = UIServer(port=0)
        if storage is not None:
            ui.attach(storage)
        else:
            ui._start()
        return ui

    def test_metrics_endpoint_prometheus(self, _clean_registry):
        tm.counter("train.steps_total", 5, model="mln")
        tm.gauge("prefetch.queue_depth", 2)
        ui = self._server()
        try:
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/metrics")
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
            assert 'dl4j_train_steps_total{model="mln"} 5' in text
            assert "dl4j_prefetch_queue_depth 2" in text
            # default collectors: compile counters always exported
            assert "dl4j_xla_backend_compiles_total" in text
        finally:
            ui.stop()

    def test_healthz_ok_and_unhealthy(self, _clean_registry):
        ui = self._server()
        try:
            base = f"http://127.0.0.1:{ui.port}"
            r = urllib.request.urlopen(base + "/healthz")
            assert r.status == 200
            doc = json.loads(r.read().decode())
            assert doc["status"] == "ok"
            assert doc["checks"]["devices"]["ok"]
            tm.set_health("training.finite", False, "nan at iteration 7")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz")
            assert exc.value.code == 503
            doc = json.loads(exc.value.read().decode())
            assert doc["status"] == "unhealthy"
            assert doc["checks"]["training.finite"]["detail"] \
                == "nan at iteration 7"
        finally:
            ui.stop()


class TestHealthMonitor:
    def test_healthy_run_sets_gauges_and_checks(self, rng, _clean_registry):
        net = _tiny_net(sync_every=4)
        mon = TrainingHealthMonitor(window=4, log_fn=None)
        net.set_listeners(mon)
        x, y = _batch(rng)
        for _ in range(8):
            net._fit_batch(x, y)
        net._dispatcher.flush()
        snap = _clean_registry.snapshot()
        assert snap["gauges"]["health.params_finite"] == 1
        assert snap["gauges"]["health.update_ratio"] > 0
        assert snap["health"]["training.finite"]["ok"]
        assert not mon.anomalies
        st = mon.state()
        assert st["iterations_seen"] == 8
        assert st["last_probe"][0] is True

    def test_non_finite_loss_flags_anomaly(self, _clean_registry):
        mon = TrainingHealthMonitor(window=100, log_fn=None)
        model = _FakeModel(float("nan"))
        mon.iteration_done(model, 1, 0)
        assert mon.anomalies and mon.anomalies[0][1] == "loss_non_finite"
        ok, checks = _clean_registry.health_report()
        assert not ok and not checks["training.finite"]["ok"]
        assert _clean_registry.snapshot()["counters"][
            "health.anomalies_total{type=loss_non_finite}"] == 1

    def test_panic_escalates(self, _clean_registry):
        from deeplearning4j_tpu.util.profiler import NaNPanicError

        mon = TrainingHealthMonitor(window=100, panic=True, log_fn=None)
        with pytest.raises(NaNPanicError, match="loss_non_finite"):
            mon.iteration_done(_FakeModel(float("inf")), 1, 0)

    def test_divergence_detection(self, _clean_registry):
        mon = TrainingHealthMonitor(window=10_000, warmup=5,
                                    divergence_factor=10.0,
                                    band_sigma=1e9,  # isolate divergence
                                    log_fn=None)
        model = _FakeModel(0.1)
        for i in range(1, 20):
            mon.iteration_done(model, i, 0)
        model.score_value = 1e6
        for i in range(20, 60):
            mon.iteration_done(model, i, 0)
        kinds = {k for _, k, _ in mon.anomalies}
        assert "divergence" in kinds
        ok, checks = _clean_registry.health_report()
        assert not checks["training.converging"]["ok"]

    def test_loss_band_anomaly(self, _clean_registry):
        mon = TrainingHealthMonitor(window=10_000, warmup=5, band_sigma=6.0,
                                    log_fn=None)
        model = _FakeModel(1.0)
        rng = np.random.default_rng(0)
        for i in range(1, 40):
            model.score_value = 1.0 + 0.01 * rng.standard_normal()
            mon.iteration_done(model, i, 0)
        assert not mon.anomalies
        model.score_value = 50.0  # far outside 6 sigma of the ~0.01 band
        mon.iteration_done(model, 40, 0)
        assert any(k == "loss_anomaly" for _, k, _ in mon.anomalies)

    def test_nan_params_sentinel(self, rng, _clean_registry):
        net = _tiny_net()
        mon = TrainingHealthMonitor(window=2, log_fn=None)
        net.set_listeners(mon)
        x, y = _batch(rng)
        net._fit_batch(x, y)
        net._fit_batch(x, y)  # window probe at iteration 2: healthy
        assert _clean_registry.snapshot()["gauges"][
            "health.params_finite"] == 1
        # poison one weight on device, then hit the next window boundary
        import jax.numpy as jnp

        net.params[0]["W"] = net.params[0]["W"].at[0, 0].set(jnp.nan)
        net._fit_batch(x, y)
        net._fit_batch(x, y)
        assert any(k == "params_non_finite"
                   for _, k, _ in mon.anomalies)
        assert _clean_registry.snapshot()["gauges"][
            "health.params_finite"] == 0

    def test_probe_survives_structure_change(self, rng, _clean_registry):
        net = _tiny_net()
        mon = TrainingHealthMonitor(window=1, log_fn=None)
        net.set_listeners(mon)
        x, y = _batch(rng)
        net._fit_batch(x, y)
        net2 = _tiny_net(seed=1)
        mon.iteration_done(net2, 1, 0)  # different params tree: no crash


class _FakeModel:
    """Listener-facing model stub (score + empty params)."""

    def __init__(self, score):
        self.score_value = score
        self.params = None
        self.conf = None


class TestEnvKnob:
    def test_env_disables_telemetry(self):
        import subprocess
        import sys

        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from deeplearning4j_tpu.util import telemetry as tm\n"
            "assert not tm.enabled()\n"
            "tm.counter('x')\n"
            "with tm.span('s'): pass\n"
            "t = tm.get_telemetry()\n"
            "assert not t.snapshot()['counters'] and not t.drain_events()\n"
            "print('disabled-ok')\n"
        )
        env = dict(os.environ, DL4J_TPU_TELEMETRY="0", JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=300,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        assert "disabled-ok" in out.stdout, out.stderr
