"""Fusion-boundary engineering tests (util/xla_tuning.py): selective-remat
policy registry, differentiable optimization barriers, config JSON round-trip
on both network types, and — the load-bearing invariant — policied train
steps being loss- AND gradient-equivalent to the unpolicied step (remat only
changes what XLA keeps live across fwd/bwd, never the arithmetic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.gradcheck import check_model_gradients
from deeplearning4j_tpu.nn import (
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.computation_graph import (
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.util import xla_tuning
from deeplearning4j_tpu.zoo import ResNet50


# ---------------------------------------------------------------- registry
def test_policy_registry():
    assert xla_tuning.resolve_policy(None) == (False, None)
    assert xla_tuning.resolve_policy("none") == (False, None)
    wrap, pol = xla_tuning.resolve_policy("full")
    assert wrap and pol is None  # jax.checkpoint default: recompute all
    for name in ("save_conv", "save_conv_dots", "save_dots", "save_all"):
        wrap, pol = xla_tuning.resolve_policy(name)
        assert wrap and pol is not None
    with pytest.raises(ValueError, match="unknown remat policy"):
        xla_tuning.resolve_policy("nope")


def test_builder_rejects_unknown_policy():
    with pytest.raises(ValueError, match="unknown remat policy"):
        NeuralNetConfiguration.builder().remat_policy("typo_policy")


def test_env_default_remat_policy(monkeypatch):
    from deeplearning4j_tpu import config as cfg

    monkeypatch.setenv("DL4J_TPU_REMAT_POLICY", "save_conv")
    monkeypatch.setattr(cfg.Environment, "_instance", None)
    try:
        assert (NeuralNetConfiguration.builder()._remat_policy
                == "save_conv")
    finally:
        monkeypatch.setattr(cfg.Environment, "_instance", None)


# ---------------------------------------------------------------- barrier
def test_barrier_identity_and_gradient():
    tree = {"a": jnp.arange(4.0), "b": jnp.ones((2, 3))}
    out = xla_tuning.barrier(tree)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(tree[k]))

    def f(x, barrier):
        h = x * x
        if barrier:
            h = xla_tuning.barrier(h)
        return jnp.sum(jnp.sin(h))

    x = jnp.linspace(0.1, 2.0, 7)
    g_plain = jax.grad(f)(x, False)
    g_fenced = jax.grad(f)(x, True)
    np.testing.assert_allclose(np.asarray(g_fenced), np.asarray(g_plain),
                               rtol=1e-6)


# ----------------------------------------------------- MLN config round-trip
def _mln_conv_conf(policy=None, barriers=False, activation="relu"):
    b = NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05))
    if policy is not None:
        b.remat_policy(policy)
    if barriers:
        b.stage_barriers(True)
    return (
        b.list()
        .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                activation=activation))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .stage_boundary()
        .layer(ConvolutionLayer(n_out=6, kernel_size=(3, 3),
                                activation=activation))
        .stage_boundary()
        .layer(DenseLayer(n_out=16, activation=activation))
        .layer(OutputLayer(n_in=16, n_out=3))
        .set_input_type(InputType.convolutional(8, 8, 1))
        .build()
    )


def test_mln_remat_config_json_roundtrip():
    conf = _mln_conv_conf(policy="save_conv", barriers=True)
    assert conf.remat_policy == "save_conv"
    assert conf.remat_stages == (2, 3)
    assert conf.stage_barriers is True
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.remat_policy == "save_conv"
    assert conf2.remat_stages == (2, 3)
    assert conf2.stage_barriers is True
    assert conf2.to_json() == s
    # absent knobs stay off after a round-trip (old JSON keeps loading)
    plain = MultiLayerConfiguration.from_json(_mln_conv_conf().to_json())
    assert plain.remat_policy is None and plain.stage_barriers is False


def test_cg_remat_config_json_roundtrip():
    conf = ResNet50(num_classes=8, input_shape=(32, 32, 3),
                    remat_policy="save_conv", stage_barriers=True).conf()
    assert conf.remat_policy == "save_conv"
    assert conf.remat_stages == ("stem_pool", "res2c_out", "res3d_out",
                                 "res4f_out", "res5c_out")
    assert conf.stage_barriers is True
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.remat_policy == conf.remat_policy
    assert conf2.remat_stages == conf.remat_stages
    assert conf2.stage_barriers is True
    assert conf2.to_json() == s


def test_env_typo_remat_policy_fails_fast(monkeypatch):
    """A typo'd DL4J_TPU_REMAT_POLICY must fail at builder construction,
    not deep inside jit tracing of the first train step."""
    from deeplearning4j_tpu import config as cfg

    monkeypatch.setenv("DL4J_TPU_REMAT_POLICY", "save_convs")
    monkeypatch.setattr(cfg.Environment, "_instance", None)
    try:
        with pytest.raises(ValueError,
                           match="DL4J_TPU_REMAT_POLICY.*unknown"):
            NeuralNetConfiguration.builder()
    finally:
        monkeypatch.setattr(cfg.Environment, "_instance", None)


def test_cg_aux_output_inside_stage_rejected():
    """An output node that topologically precedes a stage boundary would be
    swallowed into the checkpointed stage — run as plain .apply() instead of
    compute_loss(), silently dropping its loss from training. Must refuse."""
    from deeplearning4j_tpu.nn import ComputationGraph, ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.computation_graph import GraphBuilder

    gb = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
          .remat_policy("full").graph_builder()
          .add_inputs("input")
          .add_layer("h1", DenseLayer(n_in=4, n_out=8), "input")
          .add_layer("aux", OutputLayer(n_in=8, n_out=2), "h1")
          .add_layer("h2", DenseLayer(n_in=8, n_out=8), "h1")
          .stage_boundary("h2")
          .add_layer("main", OutputLayer(n_in=8, n_out=2), "h2")
          .set_outputs("aux", "main"))
    with pytest.raises(ValueError, match="aux.*inside remat stage"):
        ComputationGraph(gb.build())


def test_ops_tags_match_policy_names():
    """ops/nn.py conv/dot tags and the xla_tuning policy targets are one
    source — drift would silently degrade 'save_conv' to full recompute."""
    from deeplearning4j_tpu.ops import nn as ops_nn

    assert ops_nn._CONV_OUT is xla_tuning.CONV_OUT
    assert ops_nn._DOT_OUT is xla_tuning.DOT_OUT


def test_cg_bad_stage_boundary_rejected():
    from deeplearning4j_tpu.nn import ComputationGraph

    conf = ResNet50(num_classes=8, input_shape=(32, 32, 3)).conf()
    conf.remat_policy = "save_conv"
    conf.remat_stages = ("not_a_node",)
    with pytest.raises(ValueError, match="not a node"):
        ComputationGraph(conf)
    conf.remat_stages = ("output",)
    with pytest.raises(ValueError, match="output layer"):
        ComputationGraph(conf)


# ------------------------------------------------- MLN step equivalence
def _mln_loss_and_grad(conf, x, y):
    net = MultiLayerNetwork(conf).init()
    keys = list(jax.random.split(jax.random.PRNGKey(0), len(net.layers)))

    def loss_fn(params):
        # follow the params' dtype so the x64 gradcheck feeds fp64 activations
        dt = jax.tree_util.tree_leaves(params)[0].dtype
        loss, _ = net._loss(params, net.states, jnp.asarray(x, dt),
                            jnp.asarray(y, dt), keys)
        return loss

    return net, loss_fn, float(loss_fn(net.params)), jax.grad(loss_fn)(
        net.params)


@pytest.mark.parametrize("policy,barriers", [
    ("full", False),
    ("save_conv", False),
    ("save_conv_dots", False),
    ("save_all", False),
    (None, True),
    ("save_conv", True),
])
def test_mln_policied_step_matches_plain(rng, policy, barriers):
    """Same seed → same params; the policied loss and every parameter
    gradient must match the unpolicied step (remat/barriers change the
    schedule, not the math)."""
    x = rng.normal(size=(4, 8, 8, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
    _, _, base_loss, base_grad = _mln_loss_and_grad(_mln_conv_conf(), x, y)
    net, _, pol_loss, pol_grad = _mln_loss_and_grad(
        _mln_conv_conf(policy=policy, barriers=barriers), x, y)
    assert net._segments is not None  # the fusion-boundary path actually ran
    np.testing.assert_allclose(pol_loss, base_loss, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        pol_grad, base_grad)


def test_mln_policied_step_gradcheck(rng):
    """Finite-difference gradcheck THROUGH the remat path — the policied
    train step is gradcheck-equivalent, not just jax.grad-consistent."""
    x = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 2)]
    # tanh (the whole-network gradcheck idiom): relu kinks break the central
    # difference, and _loss returns the scalar astype(float32) (train-step
    # contract) so eps must also clear the fp32 loss-rounding floor
    net, loss_fn, _, _ = _mln_loss_and_grad(
        _mln_conv_conf(policy="save_conv", barriers=True, activation="tanh"),
        x, y)
    res = check_model_gradients(loss_fn, net.params, eps=1e-3,
                                max_rel_error=1e-2, min_abs_error=1e-4)
    assert res.passed, repr(res)


def test_mln_bad_stage_boundary_rejected():
    conf = _mln_conv_conf(policy="save_conv")
    conf = MultiLayerConfiguration.from_json(conf.to_json())
    conf.remat_stages = (99,)
    with pytest.raises(ValueError, match="out of range"):
        MultiLayerNetwork(conf)


# -------------------------------------------------- flagship equivalence
def _flagship_loss(policy, barriers, x, y):
    net = ResNet50(num_classes=8, input_shape=(32, 32, 3),
                   remat_policy=policy, stage_barriers=barriers).init()
    keys = {n.name: k for n, k in zip(
        [n for n in net.topo if n.is_layer],
        jax.random.split(jax.random.PRNGKey(0),
                         sum(n.is_layer for n in net.topo)))}

    def loss_fn(params):
        loss, _ = net._loss(params, net.states, {"input": jnp.asarray(x)},
                            {"output": jnp.asarray(y)}, keys)
        return loss

    return net, loss_fn


# tier-1 runtime guard (ISSUE 11 satellite): ~21s — ResNet-50 flagship
# build under every policy; the small-net policied-step equivalence +
# gradcheck tests above keep the remat-policy seam in tier-1, the
# full-suite CI leg still runs the flagship
@pytest.mark.slow
def test_flagship_policied_loss_matches_plain(rng):
    """Tiny-config ResNet-50 (the flagship graph shape, stage boundaries at
    stem/res2–res5): every registered policy and the barrier variant produce
    the unpolicied loss exactly."""
    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rng.integers(0, 8, 2)]
    base_net, base_fn = _flagship_loss(None, False, x, y)
    base = float(base_fn(base_net.params))
    for policy, barriers in [("full", False), ("save_conv", False),
                             ("save_conv", True), (None, True)]:
        net, fn = _flagship_loss(policy, barriers, x, y)
        assert net._segments is not None
        np.testing.assert_allclose(float(fn(net.params)), base, rtol=1e-5)


@pytest.mark.slow
def test_flagship_policied_grad_matches_plain(rng):
    """Full jax.grad through the segmented flagship graph equals the plain
    gradient for the r6 sweep's leading candidate."""
    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rng.integers(0, 8, 2)]
    base_net, base_fn = _flagship_loss(None, False, x, y)
    pol_net, pol_fn = _flagship_loss("save_conv", True, x, y)
    g_base = jax.grad(base_fn)(base_net.params)
    g_pol = jax.grad(pol_fn)(pol_net.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        g_pol, g_base)
