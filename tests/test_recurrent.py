"""Recurrent layer tests — LSTMGradientCheckTests / GravesLSTMTest /
MaskingTests parity (SURVEY.md §4: every layer type has a gradcheck; masks
for variable-length sequences)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import (
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer, OutputLayer
from deeplearning4j_tpu.nn.recurrent import (
    GRU,
    LSTM,
    Bidirectional,
    GravesLSTM,
    LastTimeStep,
    RnnOutputLayer,
    SimpleRnn,
)
from deeplearning4j_tpu.nn.updaters import Adam


B, T, F, H = 2, 5, 3, 4


# tier-1 budget discipline (the r16 convention, extended r19 on a slow
# host): GravesLSTM/GRU share the recurrent-gradcheck seam with the LSTM
# and SimpleRnn variants that stay fast — the slow-marked pair still runs
# in every full-CI pass
@pytest.mark.parametrize("layer_cls", [
    LSTM,
    pytest.param(GravesLSTM, marks=pytest.mark.slow),
    pytest.param(GRU, marks=pytest.mark.slow),
    SimpleRnn,
])
def test_recurrent_gradcheck(layer_cls, rng):
    lyr = layer_cls(n_in=F, n_out=H)
    params, state = lyr.initialize(jax.random.PRNGKey(0), (T, F))
    x = jnp.asarray(rng.standard_normal((B, T, F)))

    def loss(p):
        y, _ = lyr.apply(p, state, x.astype(jax.tree_util.tree_leaves(p)[0].dtype),
                         training=True)
        return jnp.sum(y ** 2)

    res = gradcheck.check_model_gradients(loss, params)
    assert res.passed, res


# tier-1 runtime guard (ISSUE 11 satellite): heaviest test in the suite
# (~33s — fp64 gradcheck through a double-LSTM scan); the per-cell
# gradchecks above and the cheap bidirectional wrapper tests below
# (test_bidirectional_l2_in_network, test_graves_bidirectional_lstm_layer)
# keep both seams in tier-1; the full-suite CI leg still runs this
@pytest.mark.slow
def test_bidirectional_gradcheck_and_shape(rng):
    lyr = Bidirectional(layer=LSTM(n_in=F, n_out=H))
    params, state = lyr.initialize(jax.random.PRNGKey(0), (T, F))
    x = jnp.asarray(rng.standard_normal((B, T, F)))
    y, _ = lyr.apply(params, state, x)
    assert y.shape == (B, T, 2 * H)

    def loss(p):
        out, _ = lyr.apply(p, state, x.astype(jax.tree_util.tree_leaves(p)[0].dtype),
                           training=True)
        return jnp.sum(out ** 2)

    res = gradcheck.check_model_gradients(loss, params)
    assert res.passed, res


def test_mask_state_passthrough(rng):
    """Masked steps must not advance the hidden state: the output at the last
    valid step equals the run on the trimmed sequence."""
    lyr = LSTM(n_in=F, n_out=H)
    params, _ = lyr.initialize(jax.random.PRNGKey(1), (T, F))
    x = jnp.asarray(rng.standard_normal((1, T, F)).astype(np.float32))
    n_valid = 3
    mask = jnp.asarray((np.arange(T) < n_valid)[None].astype(np.float32))
    full, _ = lyr.apply(params, {}, x, mask=mask)
    trimmed, _ = lyr.apply(params, {}, x[:, :n_valid])
    np.testing.assert_allclose(full[:, n_valid - 1], trimmed[:, -1], rtol=2e-5, atol=1e-6)
    # masked tail emits zeros (DL4J zeroes masked activations); the carried
    # state is held, so a later valid step would resume from step n_valid-1
    np.testing.assert_allclose(full[:, n_valid:], np.zeros_like(full[:, n_valid:]))


def test_last_time_step_masked(rng):
    lyr = LastTimeStep()
    x = jnp.asarray(rng.standard_normal((2, 4, 3)).astype(np.float32))
    mask = jnp.asarray(np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32))
    y, _ = lyr.apply({}, {}, x, mask=mask)
    np.testing.assert_allclose(y[0], x[0, 1])
    np.testing.assert_allclose(y[1], x[1, 3])


def _seq_net(last=None):
    return (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(Adam(0.02))
        .list()
        .layer(LSTM(n_in=F, n_out=8))
        .layer(last or LastTimeStep())
        .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.recurrent(F, T))
        .build()
    )


def test_masked_fit_and_output(rng):
    """End-to-end variable-length sequence classification with feature masks
    through MultiLayerNetwork.fit/output (setLayerMaskArrays parity)."""
    n = 64
    lengths = rng.integers(2, T + 1, n)
    xs = rng.standard_normal((n, T, F)).astype(np.float32)
    mask = (np.arange(T)[None] < lengths[:, None]).astype(np.float32)
    xs = xs * mask[:, :, None]
    # label: sign of mean of first feature over valid steps
    means = (xs[:, :, 0] * mask).sum(1) / mask.sum(1)
    labels = (means > 0).astype(int)
    ys = np.eye(2, dtype=np.float32)[labels]

    net = MultiLayerNetwork(_seq_net()).init()
    ds = DataSet(xs, ys, features_mask=mask)
    for _ in range(60):
        net._fit_batch(jnp.asarray(xs), jnp.asarray(ys), mask=jnp.asarray(mask))
    out = np.asarray(net.output(xs, mask=mask))
    acc = (out.argmax(1) == labels).mean()
    assert acc > 0.9, acc

    # masked output must be independent of padding values
    xs2 = xs + (1 - mask[:, :, None]) * 100.0
    out2 = np.asarray(net.output(xs2, mask=mask))
    np.testing.assert_allclose(out, out2, rtol=2e-4, atol=1e-5)


def test_fit_from_dataset_with_masks(rng):
    xs = rng.standard_normal((8, T, F)).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    mask = np.ones((8, T), np.float32)
    mask[:, -2:] = 0
    net = MultiLayerNetwork(_seq_net()).init()
    net.fit([DataSet(xs, ys, features_mask=mask)], epochs=2)
    assert np.isfinite(float(net.score_))


def test_bidirectional_l2_in_network(rng):
    """Bidirectional's nested fwd/bwd params must not break regularization."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater(Adam(0.01))
        .l2(1e-3)
        .list()
        .layer(Bidirectional(layer=LSTM(n_in=F, n_out=4)))
        .layer(GlobalPoolingLayer(pooling_type="avg"))
        .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.recurrent(F, T))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    xs = rng.standard_normal((8, T, F)).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    net.fit(xs, ys, epochs=2)
    assert np.isfinite(float(net.score_))


@pytest.mark.parametrize("pt,expect_fn", [
    ("sum", lambda x: x.sum(1)),
    ("pnorm", lambda x: (np.abs(x) ** 2).sum(1) ** 0.5),
])
def test_global_pooling_sum_pnorm_rnn(rng, pt, expect_fn):
    x = rng.standard_normal((2, 4, 3)).astype(np.float32)
    y, _ = GlobalPoolingLayer(pooling_type=pt).apply({}, {}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), expect_fn(x), rtol=1e-5)


def test_global_pooling_unknown_type_raises():
    with pytest.raises(ValueError, match="pooling_type"):
        GlobalPoolingLayer(pooling_type="median").apply({}, {}, jnp.ones((2, 3, 4)))


def test_rnn_output_layer_sequence_loss(rng):
    """Per-timestep outputs + masked sequence loss (RnnOutputLayer parity)."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .updater(Adam(0.05))
        .list()
        .layer(SimpleRnn(n_in=F, n_out=8))
        .layer(RnnOutputLayer(n_in=8, n_out=2, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.recurrent(F, T))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    xs = rng.standard_normal((16, T, F)).astype(np.float32)
    labels = (xs[:, :, 0] > 0).astype(int)
    ys = np.eye(2, dtype=np.float32)[labels]
    for _ in range(80):
        net._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    out = np.asarray(net.output(xs))
    assert out.shape == (16, T, 2)
    acc = (out.argmax(-1) == labels).mean()
    assert acc > 0.9, acc


def test_stateful_time_stepping(rng):
    """rnnTimeStep parity: feeding a sequence step-by-step through apply_seq
    carries state identically to one full-sequence call."""
    lyr = GRU(n_in=F, n_out=H)
    params, _ = lyr.initialize(jax.random.PRNGKey(2), (T, F))
    x = jnp.asarray(rng.standard_normal((B, T, F)).astype(np.float32))
    full, _ = lyr.apply(params, {}, x)
    carry = lyr.init_carry(B)
    steps = []
    for t in range(T):
        out, carry = lyr.apply_seq(params, x[:, t : t + 1], carry)
        steps.append(out)
    stepped = jnp.concatenate(steps, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(stepped), rtol=2e-5, atol=1e-6)


def test_tbptt_learns_long_sequence(rng):
    # task: output at t mirrors input at t (identity through time) — learnable
    # within any segment; TBPTT must train without materializing full-T BPTT
    from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.02))
            .tbptt_length(8)
            .list()
            .layer(LSTM(n_in=4, n_out=16))
            .layer(RnnOutputLayer(n_in=16, n_out=4, loss="mcxent",
                                  activation="softmax"))
            .set_input_type(InputType.recurrent(4, 32)).build())
    net = MultiLayerNetwork(conf).init()
    assert net.conf.tbptt_length == 8
    ids = rng.integers(0, 4, size=(8, 32))
    x = np.eye(4, dtype=np.float32)[ids]
    y = x.copy()
    losses = []
    for _ in range(30):
        net._fit_batch(jnp.asarray(x), jnp.asarray(y))
        losses.append(float(net.score_value))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_tbptt_carries_state_across_segments(rng):
    # task solvable ONLY with memory across segment boundaries: label at
    # every t is the input token at t=0 (long-range copy). With carries
    # flowing across segments the net can solve it; verify loss gets near 0.
    from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.02))
            .tbptt_length(4)
            .list()
            .layer(LSTM(n_in=2, n_out=16))
            .layer(RnnOutputLayer(n_in=16, n_out=2, loss="mcxent",
                                  activation="softmax"))
            .set_input_type(InputType.recurrent(2, 16)).build())
    net = MultiLayerNetwork(conf).init()
    ids = rng.integers(0, 2, size=(16, 16))
    x = np.eye(2, dtype=np.float32)[ids]
    y = np.repeat(x[:, :1], 16, axis=1)  # label = first token, everywhere
    for _ in range(60):
        net._fit_batch(jnp.asarray(x), jnp.asarray(y))
    assert float(net.score_value) < 0.25, float(net.score_value)


def test_rnn_time_step_matches_full_forward(rng):
    from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder().seed(2)
            .list()
            .layer(LSTM(n_in=3, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=3, loss="mcxent",
                                  activation="softmax"))
            .set_input_type(InputType.recurrent(3, 6)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((2, 6, 3)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    steps = [np.asarray(net.rnn_time_step(x[:, t])) for t in range(6)]
    np.testing.assert_allclose(np.stack(steps, 1), full, atol=1e-5)
    # clearing state restarts the recurrence
    net.rnn_clear_previous_state()
    again = np.asarray(net.rnn_time_step(x[:, 0]))
    np.testing.assert_allclose(again, steps[0], atol=1e-6)


def test_graves_bidirectional_lstm_layer(rng):
    from deeplearning4j_tpu.nn.recurrent import (
        Bidirectional, GravesBidirectionalLSTM, GravesLSTM)

    layer = GravesBidirectionalLSTM(n_in=4, n_out=6)
    params, state = layer.initialize(jax.random.PRNGKey(0), (5, 4))
    x = jnp.asarray(rng.standard_normal((2, 5, 4)), jnp.float32)
    y, _ = layer.apply(params, state, x)
    assert y.shape == (2, 5, 12)  # concat of both directions
    # equals the explicit Bidirectional(GravesLSTM) with the same key
    ref = Bidirectional(layer=GravesLSTM(n_in=4, n_out=6), mode="concat")
    rp, rs = ref.initialize(jax.random.PRNGKey(0), (5, 4))
    ry, _ = ref.apply(rp, rs, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ry), atol=1e-6)


class TestLSTMBlockOps:
    """Fused lstmBlock family (VERDICT r3 registry-tail item): TF
    BlockLSTM/LSTMBlockCell contract, golden-matched against tf.raw_ops —
    including peepholes, cell clipping, and the seq_len_max semantics
    (outputs zero past the limit, state carried through)."""

    def _data(self, rng, T=5, B=3, I=4, H=6):
        mk = lambda *s: rng.standard_normal(s).astype(np.float32)
        return (mk(T, B, I), mk(B, H) * 0.3, mk(B, H) * 0.3,
                mk(I + H, 4 * H) * 0.2, mk(H) * 0.1, mk(H) * 0.1,
                mk(H) * 0.1, mk(4 * H) * 0.1)

    def test_block_lstm_matches_tf(self, rng):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.ops import registry

        x, cs0, h0, W, wci, wcf, wco, b = self._data(rng)
        golden = tf.raw_ops.BlockLSTM(
            seq_len_max=np.int64(4), x=x, cs_prev=cs0, h_prev=h0, w=W,
            wci=wci, wcf=wcf, wco=wco, b=b, forget_bias=1.0, cell_clip=3.0,
            use_peephole=True)
        ours = registry.exec_op(
            "lstm_block", np.int32(4), x, cs0, h0, W, wci, wcf, wco, b,
            forget_bias=1.0, cell_clip=3.0, use_peephole=True)
        # TF leaves rows at/past seq_len_max UNINITIALIZED (observed
        # garbage) — compare active steps only; our own semantics zero them
        for a, g in zip(ours, golden):
            np.testing.assert_allclose(np.asarray(a)[:4], g.numpy()[:4],
                                       atol=1e-5)
            assert np.all(np.asarray(a)[4:] == 0.0)

    def test_block_cell_matches_tf(self, rng):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.ops import registry

        x, cs0, h0, W, wci, wcf, wco, b = self._data(rng, T=1)
        golden = tf.raw_ops.LSTMBlockCell(
            x=x[0], cs_prev=cs0, h_prev=h0, w=W, wci=wci, wcf=wcf, wco=wco,
            b=b, forget_bias=1.0, cell_clip=-1.0, use_peephole=False)
        ours = registry.exec_op(
            "lstm_block_cell", x[0], cs0, h0, W, wci, wcf, wco, b,
            forget_bias=1.0, cell_clip=-1.0, use_peephole=False)
        for a, g in zip(ours, golden):
            np.testing.assert_allclose(np.asarray(a), g.numpy(), atol=1e-5)

    def test_block_lstm_imports_from_tf_graph(self, rng):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.imports import import_graph_def

        x, cs0, h0, W, wci, wcf, wco, b = self._data(rng)

        def fn(xv):
            out = tf.raw_ops.BlockLSTM(
                seq_len_max=np.int64(5), x=xv, cs_prev=cs0, h_prev=h0, w=W,
                wci=wci, wcf=wcf, wco=wco, b=b, forget_bias=1.0,
                cell_clip=-1.0, use_peephole=False)
            return out.h

        conc = tf.function(fn).get_concrete_function(
            tf.TensorSpec(x.shape, tf.float32))
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        frozen = convert_variables_to_constants_v2(conc)
        golden = frozen(tf.constant(x))
        if isinstance(golden, (list, tuple)):
            golden = golden[0]
        golden = np.asarray(golden)
        sd = import_graph_def(frozen.graph.as_graph_def())
        key = sd.tf_name_map[frozen.outputs[0].name]
        in_name = frozen.inputs[0].name.split(":")[0]
        res = np.asarray(sd.output({in_name: x}, [key])[key])
        np.testing.assert_allclose(res, golden, atol=1e-5)
