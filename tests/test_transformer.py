"""Transformer layers + BERT model (BASELINE config #4 family).

Reference test parity: the reference covers BERT through the TF-import
regression corpus (SURVEY.md §4) — here the encoder is native, so it gets
the layer-gradcheck treatment plus an end-to-end fine-tune-loss-decreases
test through MultiLayerNetwork.fit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.nn.transformer import (
    BertEmbeddingLayer,
    TimeStepLayer,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.zoo import Bert


class TestTransformerLayers:
    def test_encoder_block_gradcheck(self, rng):
        layer = TransformerEncoderBlock(hidden_size=8, n_heads=2, ffn_size=16)
        params, state = layer.initialize(jax.random.PRNGKey(0), (5, 8))
        x = jnp.asarray(rng.standard_normal((2, 5, 8)))

        def loss(p):
            y, _ = layer.apply(p, state, x.astype(jax.tree_util.tree_leaves(p)[0].dtype))
            return jnp.sum(y ** 2)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    @pytest.mark.parametrize("pre_norm", [False, True])
    def test_encoder_block_shapes_and_mask(self, rng, pre_norm):
        layer = TransformerEncoderBlock(hidden_size=16, n_heads=4, pre_norm=pre_norm)
        params, state = layer.initialize(jax.random.PRNGKey(0), (6, 16))
        x = jnp.asarray(rng.standard_normal((3, 6, 16)), jnp.float32)
        mask = jnp.ones((3, 6)).at[0, 4:].set(0)
        y, _ = layer.apply(params, state, x, mask=mask)
        assert y.shape == (3, 6, 16)
        # masked positions don't leak into valid ones
        x2 = x.at[0, 4:].add(30.0)
        y2, _ = layer.apply(params, state, x2, mask=mask)
        np.testing.assert_allclose(y[0, :4], y2[0, :4], atol=1e-4)
        np.testing.assert_allclose(y[0, 4:], 0.0, atol=1e-6)

    def test_bert_embedding_segments(self, rng):
        layer = BertEmbeddingLayer(vocab_size=20, hidden_size=8, max_position=10)
        params, state = layer.initialize(jax.random.PRNGKey(0), (6, 2))
        toks = rng.integers(0, 20, size=(2, 6))
        feats = np.stack([toks, np.zeros_like(toks)], axis=-1).astype(np.float32)
        y, _ = layer.apply(params, state, jnp.asarray(feats))
        assert y.shape == (2, 6, 8)
        # 2D input (no segments) == 3D input with all-zero segment ids
        y2, _ = layer.apply(params, state, jnp.asarray(toks, jnp.float32))
        np.testing.assert_allclose(y, y2, atol=1e-6)
        # different segment ids change the embedding
        feats1 = np.stack([toks, np.ones_like(toks)], axis=-1).astype(np.float32)
        y3, _ = layer.apply(params, state, jnp.asarray(feats1))
        assert float(jnp.max(jnp.abs(y3 - y))) > 1e-3

    def test_timestep_layer(self, rng):
        layer = TimeStepLayer(index=0)
        x = jnp.asarray(rng.standard_normal((2, 5, 3)), jnp.float32)
        y, _ = layer.apply({}, {}, x)
        np.testing.assert_array_equal(y, x[:, 0])
        assert layer.output_shape((5, 3)) == (3,)


class TestBertModel:
    def test_tiny_classification_finetune(self, rng):
        net = Bert.tiny(vocab_size=50, max_length=12, num_classes=2,
                        hidden_dropout=0.0).init()
        B, T = 8, 12
        toks = rng.integers(4, 50, size=(B, T))
        feats = np.stack([toks, np.zeros_like(toks)], -1).astype(np.float32)
        mask = np.ones((B, T), np.float32)
        mask[:, 9:] = 0
        # learnable signal: class = does token 7 appear in the sequence
        y = np.zeros((B, 2), np.float32)
        toks[:4, 3] = 7
        feats[:, :, 0] = toks
        y[:4, 1] = 1.0
        y[4:, 0] = 1.0
        from deeplearning4j_tpu.data.dataset import DataSet

        ds = DataSet(feats, y, features_mask=mask)
        s0 = net.score(ds)
        for _ in range(40):
            net.fit(ds)
        assert net.score(ds) < s0 * 0.5, (s0, net.score(ds))

    def test_mlm_batch_shapes(self, rng):
        net = Bert.tiny(vocab_size=30, max_length=8, task="mlm",
                        hidden_dropout=0.0).init()
        B, T = 4, 8
        toks = rng.integers(4, 30, size=(B, T))
        feats = np.stack([toks, np.zeros_like(toks)], -1).astype(np.float32)
        y = np.eye(30, dtype=np.float32)[toks]
        lmask = np.zeros((B, T), np.float32)
        lmask[:, 2] = 1.0
        from deeplearning4j_tpu.data.dataset import DataSet

        ds = DataSet(feats, y, features_mask=np.ones((B, T), np.float32),
                     labels_mask=lmask)
        s0 = net.score(ds)
        for _ in range(10):
            net.fit(ds)
        assert net.score(ds) < s0
        out = net.output(feats)
        assert out.shape == (B, T, 30)
