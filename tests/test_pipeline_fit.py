"""Pipeline-parallel fit(): PipelinedTrainer on the (data, model, pipe) mesh.

ISSUE 14 acceptance: a model partitioned at its stage_boundary() markers
trains across data x tensor x pipe with param+optimizer bytes/device
≈ 1/pipe_stages, trajectory-equivalent to the unpipelined fit (bit-identical
where the deterministic-lane contract allows — a data-fold change with the
pipe placement FIXED is bitwise; changing the pipe placement itself is the
pinned ~1ulp XLA:CPU fusion boundary, docs/DISTRIBUTED.md), composed with
ZeRO + grad_compression + the fused optimizer engine."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import (PipelinedTrainer, TrainingMesh,
                                         stage_partition)
from deeplearning4j_tpu.parallel.pipeline import (bubble_fraction,
                                                  pipeline_forward,
                                                  sequential_reference,
                                                  stack_stage_params)

H = 16


def _builder(pipe=True, fused=False, comp=None, thresh=1e-3, updater=None):
    b = (NeuralNetConfiguration.builder().seed(7)
         .updater(updater or Adam(1e-2)))
    if pipe:
        b = b.pipe_stages(2).n_micro(2)
    if fused:
        b = b.fused_update(True)
    if comp:
        b = b.grad_compression(comp, threshold=thresh)
    return b


def _net(pipe=True, **kw):
    lb = (_builder(pipe=pipe, **kw).list()
          .layer(DenseLayer(n_in=8, n_out=H, activation="relu"))
          .stage_boundary()
          .layer(DenseLayer(n_in=H, n_out=H, activation="tanh"))
          .layer(DenseLayer(n_in=H, n_out=H, activation="relu"))
          .stage_boundary()
          .layer(DenseLayer(n_in=H, n_out=H, activation="tanh"))
          .layer(DenseLayer(n_in=H, n_out=H, activation="relu"))
          .stage_boundary()
          .layer(OutputLayer(n_in=H, n_out=4, loss="mcxent",
                             activation="softmax"))
          .set_input_type(InputType.feed_forward(8)))
    return MultiLayerNetwork(lb.build()).init()


@pytest.fixture
def data(rng):
    xs = rng.standard_normal((16, 8)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    return xs, ys


def _leaves(t):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(t)]


def _fit(pt, ds, steps):
    for _ in range(steps):
        pt.step_batch(ds)
    pt.sync_model()
    return pt


# ---------------------------------------------------------------------------
# partition + conf plumbing (no device mesh needed)
# ---------------------------------------------------------------------------


class TestPartition:
    def test_markers_partition_with_preamble(self):
        net = _net()
        part = stage_partition(net, 2)
        assert [k for k, _ in part.pre] == [0]
        assert [[k for k, _ in c] for c in part.stages] == [[1, 2], [3, 4]]
        assert part.post == [] and part.head[0] == 5
        assert part.per_stage == 2

    def test_config_drift_between_stages_rejected(self):
        # identical shapes/updaters but DIFFERENT activation: the stage
        # vmap would silently run stage 0's activation for both — must
        # raise instead (regression: caught computing the wrong model)
        lb = (_builder().list()
              .layer(DenseLayer(n_in=8, n_out=H, activation="relu"))
              .stage_boundary()
              .layer(DenseLayer(n_in=H, n_out=H, activation="tanh"))
              .stage_boundary()
              .layer(DenseLayer(n_in=H, n_out=H, activation="relu"))
              .stage_boundary()
              .layer(OutputLayer(n_in=H, n_out=4, loss="mcxent",
                                 activation="softmax"))
              .set_input_type(InputType.feed_forward(8)))
        net = MultiLayerNetwork(lb.build()).init()
        with pytest.raises(ValueError, match="layer configs differ"):
            stage_partition(net, 2)

    def test_shape_mismatch_rejected(self):
        lb = (_builder().list()
              .layer(DenseLayer(n_in=8, n_out=H, activation="tanh"))
              .stage_boundary()
              .layer(DenseLayer(n_in=H, n_out=2 * H, activation="tanh"))
              .stage_boundary()
              .layer(OutputLayer(n_in=2 * H, n_out=4, loss="mcxent",
                                 activation="softmax"))
              .set_input_type(InputType.feed_forward(8)))
        net = MultiLayerNetwork(lb.build()).init()
        with pytest.raises(ValueError, match="differ"):
            stage_partition(net, 2)

    def test_too_few_chunks_rejected(self):
        net = _net()
        with pytest.raises(ValueError, match="pipe_stages=4 needs"):
            stage_partition(net, 4)

    def test_conf_roundtrip_json_mln_and_cg(self):
        from deeplearning4j_tpu.nn.computation_graph import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

        conf = _net().conf
        assert conf.pipe_stages == 2 and conf.n_micro == 2
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.pipe_stages == 2 and back.n_micro == 2
        g = (_builder().graph_builder()
             .add_inputs("in")
             .add_layer("d0", DenseLayer(n_in=8, n_out=4,
                                         activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                           activation="softmax"), "d0")
             .set_outputs("out").set_input_types((8,)).build())
        assert g.pipe_stages == 2 and g.n_micro == 2
        gback = ComputationGraphConfiguration.from_json(g.to_json())
        assert gback.pipe_stages == 2 and gback.n_micro == 2

    def test_env_default(self, monkeypatch):
        from deeplearning4j_tpu import config as cfg

        monkeypatch.setenv("DL4J_TPU_PIPE_STAGES", "4")
        monkeypatch.setattr(cfg.Environment, "_instance", None)
        try:
            conf = (NeuralNetConfiguration.builder().list()
                    .layer(DenseLayer(n_in=4, n_out=4))
                    .layer(OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                       activation="softmax"))
                    .set_input_type(InputType.feed_forward(4)).build())
            assert conf.pipe_stages == 4
        finally:
            monkeypatch.setattr(cfg.Environment, "_instance", None)

    def test_bubble_fraction_schedule_math(self):
        assert bubble_fraction(2, 2) == pytest.approx(1 / 3)
        assert bubble_fraction(4, 12) == pytest.approx(3 / 15)
        assert bubble_fraction(1, 8) == 0.0
        with pytest.raises(ValueError):
            bubble_fraction(2, 0)

    def test_tbptt_rejected(self):
        conf = _net().conf
        conf.tbptt_length = 5
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(NotImplementedError, match="TBPTT"):
            PipelinedTrainer(net, mesh=TrainingMesh(
                data=1, devices=jax.devices()[:1]))

    def test_pipe_axis_must_divide_stages(self, devices):
        net = _net()
        with pytest.raises(ValueError, match="must divide pipe_stages"):
            PipelinedTrainer(net, pipe_stages=2, mesh=TrainingMesh(
                data=1, pipe=4, devices=jax.devices()[:4]))


# ---------------------------------------------------------------------------
# pipeline_forward ragged support (ISSUE 14 satellite)
# ---------------------------------------------------------------------------


@pytest.mark.multichip
class TestRaggedPipelineForward:
    def test_pads_instead_of_raising(self, rng):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))

        def stage_fn(p, x):
            return jnp.tanh(x @ p["W"] + p["b"])

        params = [
            {"W": jnp.asarray(rng.standard_normal((8, 8)) * 0.4,
                              jnp.float32),
             "b": jnp.asarray(rng.standard_normal(8) * 0.1, jnp.float32)}
            for _ in range(4)
        ]
        # 10 % n_micro(4) != 0: pre-r19 this raised; now the last
        # microbatch pads (repeated rows, sliced off the result)
        x = jnp.asarray(rng.standard_normal((10, 8)), jnp.float32)
        out = pipeline_forward(stage_fn, stack_stage_params(params), x,
                               n_micro=4, mesh=mesh)
        ref = sequential_reference(stage_fn, params, x)
        assert out.shape == (10, 8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_ragged_trainer_loss_exact_weight_machinery(self, rng):
        """The satellite's exactness claim, split into its two honest
        halves: (a) a ragged batch's auto-padding is BIT-identical to
        manually padding the batch and threading explicit 0/1 weights
        through the SAME pipelined program (the padding machinery adds
        nothing beyond the r8 weights — exact gradients), and (b) the
        loss matches the weighted unpipelined loss on the same padded
        batch to ~1 ulp (the per-microbatch gemm shapes re-block on
        XLA:CPU — the pinned r12 boundary; bit-identity between the two
        PROGRAMS is shape-dependent luck, not a contract)."""
        xs = rng.standard_normal((13, 8)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 13)]
        net = _net(updater=Sgd(0.05))
        pt = PipelinedTrainer(
            net, mesh=TrainingMesh(data=1, devices=jax.devices()[:1]),
            replicas=1, skew_every=0)
        loss_pipe = float(pt.step_batch(DataSet(xs, ys)))
        pad = lambda a: np.concatenate([a, a[-1:]], axis=0)  # noqa: E731
        # (a) same program, manual pad to 14 rows: the auto-pad rows carry
        # weight 0, so a 14-row batch (its pad row weighted 1 but identical
        # data... ) — instead compare the LANE LOSS bodies directly: run
        # the padded batch through a fresh trainer; row 14 duplicates row
        # 13, so the weighted mean differs — what must be bit-equal is the
        # TRAJECTORY: one step on the ragged batch == one step on the
        # manually padded batch with the duplicate row's weight zeroed.
        net_m = _net(updater=Sgd(0.05))
        pt_m = PipelinedTrainer(
            net_m, mesh=TrainingMesh(data=1, devices=jax.devices()[:1]),
            replicas=1, skew_every=0)
        pt_m._build()
        xp, yp = pad(xs), pad(ys)
        xs_l, ys_l, w_l = pt_m.mesh.pad_lane_batch(xp, yp, 1, micro=2)
        w_l = jnp.asarray(np.array([[1.0] * 13 + [0.0]], np.float32))
        net_m._rng_key, sub = jax.random.split(net_m._rng_key)
        keys = pt_m._lane_keys(sub)
        pp = pt_m._pp
        new_p, _, _, loss_m = pt_m._sharded_step(
            pp["params"], pp["states"], pp["opts"],
            jnp.asarray(0), xs_l, ys_l, keys, w_l)
        assert np.float32(loss_pipe) == np.float32(float(loss_m))
        pt.sync_model()
        manual = pt_m._unstack_tree(new_p, net_m.params)
        for a, b in zip(_leaves(net.params), _leaves(manual)):
            assert np.array_equal(a, b)
        # (b) vs the weighted UNPIPELINED loss: ~1 ulp
        ref = _net(updater=Sgd(0.05))
        w = np.ones(14, np.float32)
        w[13:] = 0.0
        loss_ref, _ = ref._loss(
            ref.params, ref.states, jnp.asarray(xp), jnp.asarray(yp),
            [jax.random.PRNGKey(0)] * len(ref.layers), jnp.asarray(w))
        np.testing.assert_allclose(loss_pipe, float(loss_ref),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# the trainer: trajectory, bit-identity, memory, 3D composition
# ---------------------------------------------------------------------------


@pytest.mark.multichip
class TestPipelinedFit:
    def test_trajectory_and_data_fold_bit_identity(self, data, devices):
        """(data=4, pipe=2) 8-device fit: allclose to the plain unpipelined
        fit AND bit-identical (params, Adam moments, RNG key) to the same
        pipelined program on (data=1, pipe=2) — the r12 lane contract with
        the pipe placement fixed."""
        xs, ys = data
        ds = DataSet(xs, ys)
        ref = _net()
        for _ in range(4):
            ref._fit_batch(xs, ys)
        n8 = _net()
        pt8 = _fit(PipelinedTrainer(n8, mesh=TrainingMesh(data=4, pipe=2),
                                    replicas=4, skew_every=0), ds, 4)
        for a, b in zip(_leaves(n8.params), _leaves(ref.params)):
            np.testing.assert_allclose(a, b, atol=2e-6, rtol=2e-6)
        n1 = _net()
        _fit(PipelinedTrainer(
            n1, mesh=TrainingMesh(data=1, pipe=2,
                                  devices=jax.devices()[:2]),
            replicas=4, skew_every=0), ds, 4)
        for a, b in zip(_leaves(n8.params), _leaves(n1.params)):
            assert np.array_equal(a, b)
        for a, b in zip(_leaves(n8.opt_states), _leaves(n1.opt_states)):
            assert np.array_equal(a, b)
        assert np.array_equal(np.asarray(n8._rng_key),
                              np.asarray(n1._rng_key))
        # layout surface
        lay = pt8.layout["pipeline"]
        assert lay["stages"] == 2 and lay["n_micro"] == 2
        assert lay["bubble_fraction"] == pytest.approx(1 / 3)

    def test_memory_bytes_per_device_ratio(self, devices):
        """Stage params pipe-shard: param+opt bytes ONE device holds on the
        (2, 1, 2) placement land near 1/pipe_stages of the replicated
        footprint (preamble/head replicate — the small remainder)."""
        from deeplearning4j_tpu.parallel import gspmd

        W = 64  # stage leaves 64x64 = 4096 elements >= ZeRO's 1024 floor
        lb = (_builder().list()
              .layer(DenseLayer(n_in=8, n_out=W, activation="relu"))
              .stage_boundary()
              .layer(DenseLayer(n_in=W, n_out=W, activation="tanh"))
              .stage_boundary()
              .layer(DenseLayer(n_in=W, n_out=W, activation="tanh"))
              .stage_boundary()
              .layer(OutputLayer(n_in=W, n_out=4, loss="mcxent",
                                 activation="softmax"))
              .set_input_type(InputType.feed_forward(8)))
        net = MultiLayerNetwork(lb.build()).init()
        pt = PipelinedTrainer(net, mesh=TrainingMesh(data=2, pipe=2,
                                                     devices=jax.devices()[:4]),
                              replicas=2, skew_every=0)
        pt._build()
        per_dev = pt.train_state_bytes_per_device()
        replicated = (gspmd.tree_bytes(net.params)
                      + gspmd.tree_bytes(net.opt_states))
        ratio = per_dev / replicated
        # stage-dominated net: 1/pipe_stages plus the replicated pre/head
        # remainder; ZeRO-data sharding on the moments keeps the total under
        assert ratio < 0.62, (per_dev, replicated, ratio)
        assert pt.param_bytes_per_device() < gspmd.tree_bytes(net.params)

    def test_full_3d_mesh_with_tp_rules(self, data, devices):
        xs, ys = data
        ds = DataSet(xs, ys)
        net = _net()
        pt = _fit(PipelinedTrainer(
            net, mesh=TrainingMesh(data=2, model=2, pipe=2),
            replicas=2, skew_every=0,
            tp_rules=[(r"\['W'\]$", P(None, "model"))]), ds, 4)
        tp_leaves = [v for v in jax.tree_util.tree_leaves(pt._pp["params"])
                     if hasattr(v, "sharding")
                     and "model" in str(v.sharding.spec)]
        assert tp_leaves, "no tensor-parallel sharded leaves"
        ref = _net()
        for _ in range(4):
            ref._fit_batch(xs, ys)
        for a, b in zip(_leaves(net.params), _leaves(ref.params)):
            np.testing.assert_allclose(a, b, atol=5e-6, rtol=5e-6)

    def test_masks_rejected(self, data, devices):
        xs, ys = data
        net = _net()
        pt = PipelinedTrainer(net, mesh=TrainingMesh(data=4, pipe=2),
                              replicas=4, skew_every=0)
        ds = DataSet(xs, ys)
        ds.features_mask = np.ones((16, 1), np.float32)
        with pytest.raises(NotImplementedError, match="masks"):
            pt.step_batch(ds)

    def test_cost_report_per_stage_rows(self, data, devices):
        xs, ys = data
        net = _net()
        pt = _fit(PipelinedTrainer(net, mesh=TrainingMesh(data=4, pipe=2),
                                   replicas=4, skew_every=0),
                  DataSet(xs, ys), 1)
        rep = pt.cost_report(batch_size=16, publish=False)
        names = [r.layer for r in rep.rows]
        assert "pipe:stage0" in names and "pipe:stage1" in names
        assert "(optimizer)" in names
        s0 = next(r for r in rep.rows if r.layer == "pipe:stage0")
        s1 = next(r for r in rep.rows if r.layer == "pipe:stage1")
        assert s0.flops == s1.flops > 0  # identical stages, equal split
        assert rep.devices == 8


@pytest.mark.multichip
class TestCompositions:
    @pytest.mark.slow
    def test_compression_t0_identity_and_checkpoint(self, data, tmp_path,
                                                    devices):
        # slow-marked (tier-1 budget discipline): the t->0 bit-identity
        # contract also runs in every CI pass via
        # benchmarks/pipeline_smoke.py; this test adds the checkpointed
        # residual + resume legs on top
        """threshold→0 compression is the exact identity encode: the
        pipelined compressed fit is BIT-identical to the uncompressed
        pipelined fit. An active threshold ships encoded wire bytes and a
        resident residual that rides ShardedCheckpointer restores
        bit-exactly, with the resumed trajectory bit-identical."""
        from deeplearning4j_tpu.util.checkpoint import ShardedCheckpointer

        xs, ys = data
        ds = DataSet(xs, ys)
        mesh = lambda: TrainingMesh(data=4, pipe=2)  # noqa: E731
        nc = _net(comp="threshold", thresh=0.0)
        _fit(PipelinedTrainer(nc, mesh=mesh(), replicas=4, skew_every=0),
             ds, 3)
        nu = _net()
        _fit(PipelinedTrainer(nu, mesh=mesh(), replicas=4, skew_every=0),
             ds, 3)
        for a, b in zip(_leaves(nc.params), _leaves(nu.params)):
            assert np.array_equal(a, b)
        # active compression: wire accounting + checkpointed residual
        na = _net(comp="threshold", thresh=1e-3)
        pa = _fit(PipelinedTrainer(na, mesh=mesh(), replicas=4,
                                   skew_every=0), ds, 3)
        stats = pa.compression_stats()
        assert stats["wire_bytes"] > 0
        ck = ShardedCheckpointer(str(tmp_path / "ck"), log_fn=None)
        ck.save(na.iteration, na, block=True)
        nb = _net(comp="threshold", thresh=1e-3)
        ck.restore(nb)
        pb = PipelinedTrainer(nb, mesh=mesh(), replicas=4, skew_every=0)
        for _ in range(2):
            pa.step_batch(ds)
            pb.step_batch(ds)
        pa.sync_model()
        pb.sync_model()
        for a, b in zip(_leaves(na.params), _leaves(nb.params)):
            assert np.array_equal(a, b)
        for a, b in zip(_leaves(na._grad_comp_state),
                        _leaves(nb._grad_comp_state)):
            assert np.array_equal(a, b)

    @pytest.mark.slow
    def test_fused_engine_composition(self, data, devices):
        """FusedUpdateEngine composition: the pipeline-layout engine's
        trajectory tracks the unpipelined fused fit (the pipe-placement
        fusion boundary — docs/DISTRIBUTED.md — bounds it away from
        bitwise), re-runs deterministically bit-exact, and threshold→0
        compression over the flat buffers is bit-identical to the
        uncompressed fused fit. sync_model converts the resident masters
        to the net's model-layout engine state bit-exactly (the resync
        invariant): a restore + re-stack round trip reproduces the
        trajectory."""
        xs, ys = data
        ds = DataSet(xs, ys)
        mesh = lambda: TrainingMesh(data=4, pipe=2)  # noqa: E731
        nf = _net(fused=True)
        _fit(PipelinedTrainer(nf, mesh=mesh(), replicas=4, skew_every=0),
             ds, 4)
        ref = _net(fused=True)
        for _ in range(4):
            ref._fit_batch(xs, ys)
        for a, b in zip(_leaves(nf.params), _leaves(ref.params)):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-3)
        # deterministic re-run: same program, same mesh -> bitwise
        nf2 = _net(fused=True)
        _fit(PipelinedTrainer(nf2, mesh=mesh(), replicas=4, skew_every=0),
             ds, 4)
        for a, b in zip(_leaves(nf.params), _leaves(nf2.params)):
            assert np.array_equal(a, b)
        # t->0 over the flat buffers == uncompressed fused, bitwise
        nfc = _net(fused=True, comp="threshold", thresh=0.0)
        _fit(PipelinedTrainer(nfc, mesh=mesh(), replicas=4, skew_every=0),
             ds, 4)
        for a, b in zip(_leaves(nfc.params), _leaves(nf.params)):
            assert np.array_equal(a, b)
        # masters ride sync_model: restore into a fresh net + trainer and
        # continue — bit-identical continuation proves params/masters moved
        # together through both layout conversions
        from deeplearning4j_tpu.util.checkpoint import ShardedCheckpointer
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            ck = ShardedCheckpointer(d, log_fn=None)
            ck.save(nf.iteration, nf, block=True)
            nr = _net(fused=True)
            ck.restore(nr)
            pr = PipelinedTrainer(nr, mesh=mesh(), replicas=4, skew_every=0)
            pf = PipelinedTrainer(nf, mesh=mesh(), replicas=4, skew_every=0)
            for _ in range(2):
                pf.step_batch(ds)
                pr.step_batch(ds)
            pf.sync_model()
            pr.sync_model()
            for a, b in zip(_leaves(nf.params), _leaves(nr.params)):
                assert np.array_equal(a, b)

    @pytest.mark.slow
    def test_remat_policy_through_stages(self, data, devices):
        """Activation checkpointing (the r6 remat machinery) wraps each
        stage body: same values/gradients, only XLA's fwd/bwd liveness
        changes — the pipelined fit under remat_policy='full' tracks the
        un-remat pipelined fit."""
        xs, ys = data
        ds = DataSet(xs, ys)

        def build(policy):
            b = _builder()
            if policy:
                b = b.remat_policy(policy)
            lb = (b.list()
                  .layer(DenseLayer(n_in=8, n_out=H, activation="relu"))
                  .stage_boundary()
                  .layer(DenseLayer(n_in=H, n_out=H, activation="tanh"))
                  .stage_boundary()
                  .layer(DenseLayer(n_in=H, n_out=H, activation="tanh"))
                  .stage_boundary()
                  .layer(OutputLayer(n_in=H, n_out=4, loss="mcxent",
                                     activation="softmax"))
                  .set_input_type(InputType.feed_forward(8)))
            return MultiLayerNetwork(lb.build()).init()

        n_plain = build(None)
        _fit(PipelinedTrainer(n_plain, mesh=TrainingMesh(data=4, pipe=2),
                              replicas=4, skew_every=0), ds, 3)
        n_remat = build("full")
        _fit(PipelinedTrainer(n_remat, mesh=TrainingMesh(data=4, pipe=2),
                              replicas=4, skew_every=0), ds, 3)
        for a, b in zip(_leaves(n_plain.params), _leaves(n_remat.params)):
            np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)

    def test_reshard_and_adopt_after_restore(self, data, devices):
        xs, ys = data
        ds = DataSet(xs, ys)
        net = _net()
        pt = _fit(PipelinedTrainer(net, mesh=TrainingMesh(data=4, pipe=2),
                                   replicas=4, skew_every=0), ds, 2)
        before = _leaves(net.params)
        pt.reshard(TrainingMesh(data=2, pipe=2, devices=jax.devices()[:4]))
        pt.sync_model()
        after = _leaves(net.params)
        for a, b in zip(before, after):
            assert np.array_equal(a, b)  # reshard migrates state bit-exactly
        pt.step_batch(ds)  # and the re-placed step runs
        # external write (a restore): the next step adopts it
        net.params = jax.tree_util.tree_map(np.asarray, net.params)
        pt.step_batch(ds)
        assert np.isfinite(float(net.score_value))

    def test_in_place_external_write_adopted(self, data, devices):
        """Regression (review finding): transfer ``copy_back`` / the Keras
        importer write INTO the existing params list (``net.params[i] =
        ...``), leaving the container id unchanged — the leaf-id
        fingerprint must still detect it, or the trainer keeps training
        the stale stacked state and sync_model() silently overwrites the
        external write."""
        xs, ys = data
        ds = DataSet(xs, ys)
        net = _net()
        pt = _fit(PipelinedTrainer(net, mesh=TrainingMesh(data=4, pipe=2),
                                   replicas=4, skew_every=0), ds, 2)
        # in-place entry write: zero layer 0's weights (container id kept)
        net.params[0] = dict(net.params[0],
                             W=jnp.zeros_like(net.params[0]["W"]))
        pt.step_batch(ds)
        pt.sync_model()
        w = np.abs(np.asarray(net.params[0]["W"])).max()
        # adopted: one Adam step from zeros is lr-scale (~1e-2), not the
        # stale trained magnitude (~0.5)
        assert w < 0.1, f"in-place write ignored (|W|max={w})"

    def test_deterministic_wrapper_rejects_pipe_mesh(self, devices):
        """Regression (review finding): the deterministic lane mode's
        data-only-mesh guard must cover the new 'pipe' axis."""
        from deeplearning4j_tpu.parallel import ParallelWrapper

        net = _net(pipe=False)
        with pytest.raises(ValueError, match="data-only mesh"):
            ParallelWrapper(net, mesh=TrainingMesh(data=2, pipe=2,
                                                   devices=jax.devices()[:4]),
                            deterministic=True)


@pytest.mark.multichip
class TestLinearChainCG:
    def _graph(self):
        g = (_builder().graph_builder()
             .add_inputs("in")
             .add_layer("embed", DenseLayer(n_in=8, n_out=H,
                                            activation="relu"), "in")
             .add_layer("b0", DenseLayer(n_in=H, n_out=H,
                                         activation="tanh"), "embed")
             .add_layer("b1", DenseLayer(n_in=H, n_out=H,
                                         activation="tanh"), "b0")
             .add_layer("out", OutputLayer(n_in=H, n_out=4, loss="mcxent",
                                           activation="softmax"), "b1")
             .set_outputs("out").set_input_types((8,))
             .stage_boundary("embed", "b0", "b1"))
        return ComputationGraph(g.build()).init()

    def test_cg_chain_trains_and_tracks_unpipelined(self, data, devices):
        xs, ys = data
        net = self._graph()
        part = stage_partition(net, 2)
        assert [k for k, _ in part.pre] == ["embed"]
        assert [[k for k, _ in c] for c in part.stages] == [["b0"], ["b1"]]
        pt = _fit(PipelinedTrainer(net, mesh=TrainingMesh(data=4, pipe=2),
                                   replicas=4, skew_every=0),
                  DataSet(xs, ys), 3)
        ref = self._graph()
        for _ in range(3):
            ref._fit_batch([xs], [ys])
        for a, b in zip(_leaves(net.params), _leaves(ref.params)):
            np.testing.assert_allclose(a, b, atol=2e-6, rtol=2e-6)
        assert pt.layout["pipeline"]["stages"] == 2

    def test_non_chain_graph_rejected(self):
        g = (_builder().graph_builder()
             .add_inputs("a", "b")
             .add_layer("d", DenseLayer(n_in=8, n_out=4,
                                        activation="tanh"), "a")
             .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                           activation="softmax"), "d")
             .set_outputs("out").set_input_types((8,), (8,)))
        net = ComputationGraph(g.build()).init()
        with pytest.raises(ValueError, match="single-input"):
            stage_partition(net, 2)


@pytest.mark.multichip
def test_partitioner_slice_hazard_documented(devices):
    """Pins the jaxlib SPMD bug the fused path engineers around: slicing a
    pipe-sharded stacked array inside jit on a multi-axis mesh corrupts
    data (strided reads), while the reshape-based flatten the
    pipeline-layout engine uses is exact. If this test ever FAILS on the
    corrupt branch, the workaround can be retired (docs/DISTRIBUTED.md)."""
    from jax import lax
    from jax.sharding import Mesh, NamedSharding

    devs = np.array(jax.devices()[:4]).reshape(2, 1, 1, 2)
    mesh = Mesh(devs, ("data", "model", "seq", "pipe"))
    pipe_spec = NamedSharding(mesh, P("pipe"))
    S, n = 2, 16
    x = np.arange(S * n * n, dtype=np.float32).reshape(S, n, n)
    xs = jax.device_put(x, pipe_spec)

    @jax.jit
    def reshape_roundtrip(stacked):
        stacked = lax.with_sharding_constraint(stacked, pipe_spec)
        flat = stacked.reshape(-1)
        return lax.with_sharding_constraint(flat.reshape(S, n, n),
                                            pipe_spec)

    assert np.array_equal(np.asarray(reshape_roundtrip(xs)), x)

    @jax.jit
    def slice_roundtrip(stacked):
        stacked = lax.with_sharding_constraint(stacked, pipe_spec)
        return lax.with_sharding_constraint(
            jnp.stack([stacked[i] for i in range(S)]), pipe_spec)

    sliced = np.asarray(slice_roundtrip(jax.device_put(x, pipe_spec)))
    if np.array_equal(sliced, x):
        pytest.fail(
            "jaxlib's partitioner now slices pipe-sharded stage axes "
            "correctly — the reshape-only constraint in "
            "parallel/pipelined.py (module docstring) can be retired")
