"""1-D/3-D layer families, locally-connected, misc layers + new vertices:
forward shapes + gradchecks (CNNGradientCheckTest-style rows — VERDICT r1
missing #5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.nn.layers_spatial import (
    Convolution1D,
    Convolution3D,
    Cropping1D,
    Cropping3D,
    DepthwiseConvolution2D,
    ElementWiseMultiplicationLayer,
    LocallyConnected1D,
    LocallyConnected2D,
    MaskLayer,
    MaskZeroLayer,
    PReLULayer,
    Subsampling1DLayer,
    Subsampling3DLayer,
    Upsampling1D,
    Upsampling3D,
    ZeroPadding1DLayer,
    ZeroPadding3DLayer,
)
from deeplearning4j_tpu.nn.recurrent import SimpleRnn
from deeplearning4j_tpu.nn.vertices import (
    DuplicateToTimeSeriesVertex,
    FrozenVertex,
    L2Vertex,
    LastTimeStepVertex,
    PreprocessorVertex,
    ScaleVertex,
    vertex_from_dict,
)


def _cast_like(p, x):
    leaves = jax.tree_util.tree_leaves(p)
    return x.astype(leaves[0].dtype) if leaves else x


PARAM_LAYERS = [
    (Convolution1D(n_in=3, n_out=4, kernel_size=3, padding="VALID",
                   activation="tanh"), (7, 3)),
    (Convolution3D(n_in=2, n_out=3, kernel_size=(2, 2, 2), padding="VALID",
                   activation="sigmoid"), (4, 4, 4, 2)),
    (DepthwiseConvolution2D(n_in=3, depth_multiplier=2, kernel_size=(2, 2),
                            padding="VALID", activation="tanh"), (5, 5, 3)),
    (LocallyConnected2D(n_in=2, n_out=3, kernel_size=(2, 2),
                        input_size=(4, 4), activation="tanh"), (4, 4, 2)),
    (LocallyConnected1D(n_in=2, n_out=3, kernel_size=2, input_size=6,
                        activation="tanh"), (6, 2)),
    (PReLULayer(n_in=5), (5,)),
    (ElementWiseMultiplicationLayer(n_in=5), (5,)),
    (MaskZeroLayer(underlying=SimpleRnn(n_in=3, n_out=4)), (5, 3)),
]


@pytest.mark.parametrize("layer,shape", PARAM_LAYERS,
                         ids=[type(l).__name__ for l, _ in PARAM_LAYERS])
def test_param_layer_gradients(layer, shape, rng):
    params, state = layer.initialize(jax.random.PRNGKey(0), shape)
    x = jnp.asarray(rng.standard_normal((2,) + tuple(shape)))

    def loss(p):
        y, _ = layer.apply(p, state, _cast_like(p, x), training=True)
        return jnp.sum(y.astype(jax.tree_util.tree_leaves(p)[0].dtype) ** 2)

    res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
    assert res.passed, f"{type(layer).__name__}: {res}"


SHAPE_CASES = [
    (Convolution1D(n_in=3, n_out=4, kernel_size=3, padding="VALID"), (7, 3)),
    (Subsampling1DLayer(kernel_size=2), (8, 3)),
    (Subsampling1DLayer(kernel_size=2, pooling_type="avg"), (8, 3)),
    (Cropping1D(cropping=(1, 2)), (8, 3)),
    (ZeroPadding1DLayer(padding=(2, 1)), (5, 3)),
    (Upsampling1D(size=3), (4, 2)),
    (Convolution3D(n_in=2, n_out=3, kernel_size=(2, 2, 2), padding="VALID"),
     (4, 4, 4, 2)),
    (Subsampling3DLayer(kernel_size=(2, 2, 2)), (4, 4, 4, 2)),
    (Subsampling3DLayer(kernel_size=(2, 2, 2), pooling_type="avg"),
     (4, 4, 4, 2)),
    (Cropping3D(cropping=((1, 1), (0, 1), (1, 0))), (4, 5, 6, 2)),
    (ZeroPadding3DLayer(padding=((1, 1), (2, 0), (0, 2))), (3, 3, 3, 2)),
    (Upsampling3D(size=2), (2, 3, 4, 2)),
    (DepthwiseConvolution2D(n_in=3, depth_multiplier=2, kernel_size=(2, 2),
                            padding="VALID"), (5, 5, 3)),
    (LocallyConnected2D(n_in=2, n_out=3, kernel_size=(2, 2),
                        input_size=(4, 4)), (4, 4, 2)),
    (LocallyConnected1D(n_in=2, n_out=3, kernel_size=2, input_size=6), (6, 2)),
]


@pytest.mark.parametrize("layer,shape", SHAPE_CASES, ids=[
    f"{type(l).__name__}-{i}" for i, (l, _) in enumerate(SHAPE_CASES)])
def test_forward_shape_matches_output_shape(layer, shape, rng):
    params, state = layer.initialize(jax.random.PRNGKey(0), shape)
    x = jnp.asarray(rng.standard_normal((2,) + tuple(shape)), jnp.float32)
    y, _ = layer.apply(params, state, x)
    assert y.shape[1:] == tuple(layer.output_shape(shape)), (
        y.shape, layer.output_shape(shape))


def test_mask_layer_zeroes_masked_steps(rng):
    lyr = MaskLayer()
    x = jnp.asarray(rng.standard_normal((2, 4, 3)), jnp.float32)
    mask = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    y, _ = lyr.apply({}, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y[0, 2:]), 0.0)
    np.testing.assert_allclose(np.asarray(y[1]), np.asarray(x[1]))


def test_mask_zero_layer_ignores_padded_steps(rng):
    inner = SimpleRnn(n_in=3, n_out=4)
    lyr = MaskZeroLayer(underlying=inner)
    params, state = lyr.initialize(jax.random.PRNGKey(0), (5, 3))
    x = jnp.asarray(rng.standard_normal((2, 5, 3)), jnp.float32)
    x = x.at[:, 3:].set(0.0)  # padding steps
    y, _ = lyr.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y[:, 3:]), 0.0)


class TestNewVertices:
    def test_l2_vertex(self, rng):
        a = jnp.asarray(rng.standard_normal((3, 4)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((3, 4)), jnp.float32)
        y = L2Vertex().apply(a, b)
        assert y.shape == (3, 1)
        np.testing.assert_allclose(
            np.asarray(y[:, 0]), np.linalg.norm(np.asarray(a - b), axis=1),
            rtol=1e-4, atol=1e-4)

    def test_last_time_step_vertex(self, rng):
        x = jnp.asarray(rng.standard_normal((2, 5, 3)), jnp.float32)
        y = LastTimeStepVertex().apply(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x[:, -1]))
        assert LastTimeStepVertex().output_shape((5, 3)) == (3,)

    def test_duplicate_to_time_series(self, rng):
        x = jnp.asarray(rng.standard_normal((2, 3)), jnp.float32)
        seq = jnp.zeros((2, 7, 5))
        y = DuplicateToTimeSeriesVertex().apply(x, seq)
        assert y.shape == (2, 7, 3)
        np.testing.assert_array_equal(np.asarray(y[:, 4]), np.asarray(x))

    @pytest.mark.parametrize("mode,shape,in_shape,out_shape", [
        ("cnn_to_ff", (), (4, 4, 2), (32,)),
        ("ff_to_cnn", (4, 4, 2), (32,), (4, 4, 2)),
        ("rnn_to_ff", (), (5, 3), (3,)),
        ("ff_to_rnn", (5,), (3,), (5, 3)),
    ])
    def test_preprocessor_vertex(self, rng, mode, shape, in_shape, out_shape):
        v = PreprocessorVertex(mode=mode, shape=shape)
        assert v.output_shape(in_shape) == out_shape
        if mode in ("cnn_to_ff", "ff_to_cnn"):
            x = jnp.ones((2,) + in_shape)
            assert v.apply(x).shape == (2,) + out_shape

    def test_frozen_vertex_blocks_gradients(self, rng):
        v = FrozenVertex(inner=ScaleVertex(scale=2.0))
        x = jnp.asarray(rng.standard_normal((3, 4)), jnp.float32)
        g = jax.grad(lambda x: jnp.sum(v.apply(x)))(x)
        np.testing.assert_allclose(np.asarray(g), 0.0)
        # serialization round-trip with nested inner
        back = vertex_from_dict(v.to_dict())
        assert isinstance(back, FrozenVertex)
        np.testing.assert_allclose(np.asarray(back.apply(x)),
                                   np.asarray(v.apply(x)))
