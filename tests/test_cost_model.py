"""Cost attribution (util/cost_model.py, ISSUE 5): per-layer FLOPs / bytes /
device-time accounting extracted from the compiled executable, analytic
fallbacks, MFU reporting, and the reporting surfaces (/costs route,
StatsListener cost group, utilization gauges).

The load-bearing invariant: the per-layer table's FLOPs column (and, under
profiling, its device-time column) sums back to the whole-step compiled
totals within 5% — attribution must account for everything, with optimizer
and metadata-stripped ops in explicit (optimizer)/(untagged) rows rather
than silently dropped. And ``source: analytic`` rows appear EXACTLY when
XLA cost analysis is unavailable."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import GraphBuilder
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          OutputLayer, SharedLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.util import cost_model as cm


def _conv_net():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5),
                                    padding="VALID", activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_in=32, n_out=10))
            .set_input_type(InputType.convolutional(28, 28, 1)).build())
    return MultiLayerNetwork(conf).init()


def _lstm_net(T=12):
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(LSTM(n_in=16, n_out=32))
            .layer(LSTM(n_in=32, n_out=32))
            .layer(RnnOutputLayer(n_in=32, n_out=16))
            .set_input_type(InputType.recurrent(16, T)).build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture
def _clean_published():
    cm.clear_published()
    yield
    cm.clear_published()


class TestHloParser:
    def test_micro_program_reconciles_and_tags(self):
        """The per-instruction cost model reproduces the executable's own
        cost_analysis() total, and named scopes recover (layer, fwd|bwd)."""

        def loss(params, x):
            with cm.layer_scope("dense0"):
                h = jnp.tanh(x @ params["w0"])
            with cm.layer_scope("dense1"):
                h = h @ params["w1"]
            return (h ** 2).sum()

        params = {"w0": jnp.ones((16, 32)), "w1": jnp.ones((32, 4))}
        compiled = jax.jit(jax.value_and_grad(loss)).lower(
            params, jnp.ones((8, 16))).compile()
        totals = cm.compiled_totals(compiled)
        attrib = cm.attribute_hlo(cm.compiled_text(compiled))
        assert attrib.flops_total == pytest.approx(totals["flops"],
                                                   rel=0.05)
        # fwd dot of dense0: 2*8*16*32; its bwd row exists separately
        assert attrib.by_layer[("dense0", "fwd")]["flops"] >= 2 * 8 * 16 * 32
        assert ("dense0", "bwd") in attrib.by_layer
        assert ("dense1", "bwd") in attrib.by_layer
        # transcendentals (tanh) tracked separately, on the right layer
        assert attrib.by_layer[("dense0", "fwd")]["transcendentals"] > 0
        # instruction map exists for runtime grouping
        assert any(tag == "dense0" for tag, _ in attrib.inst_map.values())

    def test_memory_analysis_totals(self):
        compiled = jax.jit(lambda x: (x @ x).sum()).lower(
            jnp.ones((16, 16))).compile()
        totals = cm.compiled_totals(compiled)
        assert totals["argument_size_in_bytes"] >= 16 * 16 * 4
        assert "peak_bytes" in totals

    def test_sanitize_tag(self):
        assert cm.sanitize_tag("res2a/branch 1") == "res2a_branch_1"


class TestMlnCostReport:
    def test_conv_net_flops_sum_to_compiled_total(self):
        net = _conv_net()
        rep = net.cost_report(batch_size=8, publish=False)
        assert rep.source == "xla"
        attributed = sum(r.flops for r in rep.rows)
        assert attributed == pytest.approx(rep.totals["flops"], rel=0.05)
        # the conv forward dominates and is attributed to its own row
        conv = next(r for r in rep.rows if "ConvolutionLayer" in r.layer)
        assert conv.flops_fwd >= 2 * 8 * 24 * 24 * 25 * 8  # 2*B*OH*OW*K*Cout
        assert conv.params == 5 * 5 * 1 * 8 + 8
        # optimizer work is explicit, not hidden in a layer row
        assert any(r.layer == cm.OPTIMIZER_ROW and r.flops > 0
                   for r in rep.rows)
        assert all(r.source == "xla" for r in rep.rows)

    def test_lstm_net_flops_sum_to_compiled_total(self):
        """Acceptance: LSTM model (scan -> while loop in HLO) — the
        attribution still accounts for the whole step within 5%."""
        net = _lstm_net()
        rep = net.cost_report(batch_size=8, publish=False)
        assert rep.source == "xla"
        attributed = sum(r.flops for r in rep.rows)
        assert attributed == pytest.approx(rep.totals["flops"], rel=0.05)
        for tag in ("0_LSTM", "1_LSTM", "2_RnnOutputLayer"):
            row = next(r for r in rep.rows if r.layer == tag)
            assert row.flops > 0, tag

    def test_profile_device_time_columns_sum_to_total(self):
        """Acceptance: per-layer device-time columns reconcile against the
        whole-step device total (same XPlane grouping, independent sums)."""
        net = _conv_net()
        rep = net.cost_report(batch_size=8, profile=True, steps=2,
                              publish=False)
        assert rep.step_time_s and rep.step_time_s > 0
        assert rep.device_time_s and rep.device_time_s > 0
        row_sum = sum(r.device_time_s or 0.0 for r in rep.rows)
        assert row_sum == pytest.approx(rep.device_time_s, rel=0.05)
        # the model rows (not just (untagged)) actually got device time
        tagged = sum((r.device_time_s or 0.0) for r in rep.rows
                     if r.layer not in (cm.UNTAGGED_ROW, cm.OPTIMIZER_ROW))
        assert tagged > 0
        assert rep.examples_per_sec and rep.examples_per_sec > 0

    def test_profile_does_not_advance_model(self):
        """profile=True runs the compiled step on copies: iteration count,
        params, and RNG key of the live model must be untouched."""
        net = _conv_net()
        w_before = np.asarray(net.params[0]["W"]).copy()
        it_before = net.iteration
        key_before = np.asarray(net._rng_key).copy()
        net.cost_report(batch_size=4, profile=True, steps=1, publish=False)
        assert net.iteration == it_before
        assert np.array_equal(np.asarray(net.params[0]["W"]), w_before)
        assert np.array_equal(np.asarray(net._rng_key), key_before)

    def test_mfu_reported_exactly_when_peak_known(self, monkeypatch):
        net = _conv_net()
        rep = net.cost_report(batch_size=8, profile=True, steps=1,
                              peak_flops=1e12, publish=False)
        assert rep.mfu is not None and 0 < rep.mfu < 1
        assert rep.achieved_flops_per_sec == pytest.approx(
            rep.flops_per_step / rep.step_time_s)
        # no peak configured -> no MFU (no silent hardware guesses)
        monkeypatch.delenv("DL4J_TPU_PEAK_FLOPS", raising=False)
        rep2 = net.cost_report(batch_size=8, profile=True, steps=1,
                               publish=False)
        assert rep2.mfu is None
        # env knob path
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "2.5e13")
        rep3 = net.cost_report(batch_size=8, publish=False)
        assert rep3.peak_flops == 2.5e13

    def test_analytic_rows_exactly_when_xla_unavailable(self, monkeypatch):
        """source=analytic appears on EVERY row when cost analysis is
        absent, and on NO row when it is present."""
        net = _conv_net()
        rep = net.cost_report(batch_size=8, publish=False)
        assert rep.source == "xla"
        assert not any(r.source == "analytic" for r in rep.rows)

        def unavailable(compiled):
            raise cm.CostAnalysisUnavailable("backend without cost model")

        monkeypatch.setattr(cm, "compiled_totals", unavailable)
        rep2 = net.cost_report(batch_size=8, publish=False)
        assert rep2.source == "analytic"
        assert rep2.rows and all(r.source == "analytic" for r in rep2.rows)
        # analytic conv formula: 2*B*OH*OW*KH*KW*Cin*Cout forward
        conv = next(r for r in rep2.rows if "ConvolutionLayer" in r.layer)
        assert conv.flops_fwd == pytest.approx(
            2 * 8 * 24 * 24 * 5 * 5 * 1 * 8)
        assert conv.flops_bwd == pytest.approx(2 * conv.flops_fwd)
        # the estimate lands in the right ballpark of the true total
        assert rep2.flops_per_step == pytest.approx(
            rep.totals["flops"], rel=0.5)

    def test_summary_and_json_round_trip(self):
        net = _conv_net()
        rep = net.cost_report(batch_size=4, publish=False)
        s = rep.summary()
        assert "MFLOP" in s or "GFLOP" in s or "KFLOP" in s
        assert "0_ConvolutionLayer" in s
        d = json.loads(rep.to_json())
        assert d["batch"] == 4
        assert d["layers"][0]["flops"] >= 0
        assert d["source"] == "xla"


class TestCgCostReport:
    def _graph(self, shared=False):
        # square dense so a SharedLayer can re-apply fc1's weights
        b = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
             .graph_builder()
             .add_inputs("in"))
        b.add_layer("fc1", DenseLayer(n_in=32, n_out=32, activation="relu"),
                    "in")
        if shared:
            b.add_layer("fc_shared",
                        SharedLayer(source="fc1",
                                    layer=DenseLayer(n_in=32, n_out=32,
                                                     activation="relu")),
                        "fc1")
            last = "fc_shared"
        else:
            b.add_layer("fc2",
                        DenseLayer(n_in=32, n_out=32, activation="relu"),
                        "fc1")
            last = "fc2"
        b.add_layer("out", OutputLayer(n_in=32, n_out=10), last)
        b.set_outputs("out").set_input_types((32,))
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

        return ComputationGraph(b.build()).init()

    def test_graph_flops_sum_to_compiled_total(self):
        net = self._graph()
        rep = net.cost_report(batch_size=8, publish=False)
        assert rep.source == "xla"
        attributed = sum(r.flops for r in rep.rows)
        assert attributed == pytest.approx(rep.totals["flops"], rel=0.05)
        for tag in ("fc1", "fc2", "out"):
            assert next(r for r in rep.rows if r.layer == tag).flops > 0

    def test_shared_weights_layer_appears_in_two_scopes(self):
        """A SharedLayer node computes under its OWN scope with the source
        node's params: two rows, each with real FLOPs, params only on the
        owner — and the column sum still reconciles."""
        net = self._graph(shared=True)
        rep = net.cost_report(batch_size=8, publish=False)
        fc1 = next(r for r in rep.rows if r.layer == "fc1")
        shared = next(r for r in rep.rows if r.layer == "fc_shared")
        assert fc1.flops_fwd > 0 and shared.flops_fwd > 0
        assert fc1.params == 32 * 32 + 32
        assert shared.params == 0  # the source row owns the weights
        # both call sites' backward work exists (grads accumulate into fc1)
        assert fc1.flops_bwd > 0 and shared.flops_bwd > 0
        attributed = sum(r.flops for r in rep.rows)
        assert attributed == pytest.approx(rep.totals["flops"], rel=0.05)

    def test_graph_profile_reconciles(self):
        net = self._graph()
        rep = net.cost_report(batch_size=8, profile=True, steps=2,
                              publish=False)
        row_sum = sum(r.device_time_s or 0.0 for r in rep.rows)
        assert rep.device_time_s and row_sum == pytest.approx(
            rep.device_time_s, rel=0.05)


@pytest.mark.slow
class TestFlagshipResNet50:
    def test_resnet50_flops_and_time_reconcile(self):
        """Acceptance: flagship zoo ResNet-50 (CPU-sized 32px, same graph
        topology as 224px) — per-layer FLOPs AND device-time columns each
        sum to within 5% of the whole-step compiled totals."""
        from deeplearning4j_tpu.zoo import ResNet50

        net = ResNet50(num_classes=16, input_shape=(32, 32, 3)).init()
        rep = net.cost_report(batch_size=4, profile=True, steps=1,
                              publish=False)
        assert rep.source == "xla"
        attributed = sum(r.flops for r in rep.rows)
        assert attributed == pytest.approx(rep.totals["flops"], rel=0.05)
        row_sum = sum(r.device_time_s or 0.0 for r in rep.rows)
        assert rep.device_time_s and row_sum == pytest.approx(
            rep.device_time_s, rel=0.05)
        # every conv stage shows up as its own row with real work
        named = {r.layer for r in rep.rows if r.flops > 0}
        assert any(t.startswith("res2a") for t in named)
        assert any(t.startswith("res5a") for t in named)


class TestSurfaces:
    def test_publish_and_costs_route(self, _clean_published):
        from deeplearning4j_tpu.util.ui_server import UIServer

        net = _conv_net()
        net.cost_report(batch_size=4, name="test_mln", peak_flops=1e12)
        assert "test_mln" in cm.published_reports()

        import urllib.request

        server = UIServer(port=0)
        server._start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/costs") as r:
                body = json.loads(r.read())
            assert "test_mln" in body["reports"]
            rep = body["reports"]["test_mln"]
            assert rep["flops_per_step"] > 0
            assert len(rep["layers"]) >= 4
        finally:
            server.stop()

    def test_stats_listener_cost_group(self, _clean_published):
        from deeplearning4j_tpu.util.stats import (InMemoryStatsStorage,
                                                   StatsListener)

        net = _conv_net()
        net.cost_report(batch_size=4, name="cost_stats")
        storage = InMemoryStatsStorage()
        net.set_listeners(StatsListener(storage, collect_histograms=False))
        x = np.random.default_rng(0).normal(size=(4, 28, 28, 1)).astype(
            np.float32)
        y = np.eye(10, dtype=np.float32)[[0, 1, 2, 3]]
        net.fit(x, y)
        rec = storage.records[-1]
        assert "cost" in rec
        assert rec["cost"]["cost_stats"]["flops_per_step"] > 0
        assert rec["cost"]["cost_stats"]["source"] == "xla"

    def test_utilization_gauges_on_fit(self, _clean_published):
        from deeplearning4j_tpu.util import telemetry as tm

        tele = tm.get_telemetry()
        was = tele.enabled
        tele.enabled = True
        try:
            net = _conv_net()
            net.cost_report(batch_size=4, name="gauges",
                            peak_flops=1e12)
            x = np.random.default_rng(0).normal(
                size=(4, 28, 28, 1)).astype(np.float32)
            y = np.eye(10, dtype=np.float32)[[0, 1, 2, 3]]
            net.fit(x, y, epochs=3)  # >= 2 dispatches arm the cadence path
            gauges = tele.snapshot()["gauges"]
            eps = [v for k, v in gauges.items()
                   if k.startswith("train.examples_per_sec")
                   and "model=mln" in k]
            mfu = [v for k, v in gauges.items()
                   if k.startswith("train.model_flops_utilization")
                   and "model=mln" in k]
            assert eps and eps[0] > 0
            assert mfu and 0 < mfu[0] < 1
        finally:
            tele.enabled = was
