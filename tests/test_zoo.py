"""Model-zoo tests (TestInstantiation in deeplearning4j-zoo parity: build,
init, forward-shape, and a short fit for the flagship)."""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    AlexNet,
    Darknet19,
    LeNet,
    ResNet50,
    SimpleCNN,
    SqueezeNet,
    UNet,
    VGG16,
    Xception,
)


def _fwd(model, batch=2):
    net = model.init()
    h, w, c = model.input_shape
    x = np.random.default_rng(0).normal(size=(batch, h, w, c)).astype(np.float32)
    return net, net.output(x)


def test_lenet():
    net, out = _fwd(LeNet())
    assert out.shape == (2, 10)
    assert net.num_params() == 431080  # classic LeNet-5-ish param count


def test_simplecnn():
    _, out = _fwd(SimpleCNN(num_classes=7, input_shape=(32, 32, 3)))
    assert out.shape == (2, 7)


def test_alexnet():
    _, out = _fwd(AlexNet(num_classes=5, input_shape=(128, 128, 3)))
    assert out.shape == (2, 5)


def test_vgg16_small():
    _, out = _fwd(VGG16(num_classes=4, input_shape=(32, 32, 3)))
    assert out.shape == (2, 4)


def test_darknet19():
    _, out = _fwd(Darknet19(num_classes=6, input_shape=(64, 64, 3)))
    assert out.shape == (2, 6)


def test_squeezenet():
    _, out = _fwd(SqueezeNet(num_classes=9, input_shape=(64, 64, 3)))
    assert out.shape == (2, 9)


def test_unet():
    model = UNet(input_shape=(64, 64, 3), base_filters=4)
    net, out = _fwd(model)
    assert out.shape == (2, 64, 64, 1)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) <= 1))


def test_xception():
    _, out = _fwd(Xception(num_classes=3, input_shape=(64, 64, 3), middle_repeats=1))
    assert out.shape == (2, 3)


def test_resnet50_structure():
    model = ResNet50(num_classes=1000, input_shape=(64, 64, 3))
    net = model.init()
    # Keras ResNet50 (v1, fc1000) has 25,636,712 params; ours differs only in
    # not having the ZeroPadding edge handling -> identical count.
    assert abs(net.num_params() - 25_636_712) < 100_000, net.num_params()
    x = np.random.default_rng(0).normal(size=(2, 64, 64, 3)).astype(np.float32)
    out = net.output(x)
    assert out.shape == (2, 1000)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, atol=1e-3)


@pytest.mark.slow
def test_resnet50_learns():
    model = ResNet50(num_classes=4, input_shape=(32, 32, 3))
    net = model.init()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    s0 = net.score(x=x, y=y)
    for _ in range(15):
        net.fit(x, y)
    assert net.score(x=x, y=y) < s0


def test_facenet_nn4_small2():
    from deeplearning4j_tpu.zoo import FaceNetNN4Small2

    model = FaceNetNN4Small2(num_classes=11, input_shape=(96, 96, 3))
    net, out = _fwd(model)
    assert out.shape == (2, 11)
    # the embedding the model exists for: 128-d and L2-normalized
    h, w, c = model.input_shape
    x = np.random.default_rng(0).normal(size=(2, h, w, c)).astype(np.float32)
    acts = net.feed_forward(x)
    emb = np.asarray(acts["embed_norm"])
    assert emb.shape == (2, 128)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, atol=1e-5)
