"""Native runtime: arena, CSV parse, async pipeline.

Reference test parity: libnd4j gtest suites cover the native core
(SURVEY.md §4 row 1); here the native module is the ETL/memory runtime and
is validated against the pure-Python implementations. The pipeline's
concurrency is additionally stress-tested under TSan/ASan out-of-band (see
csrc comments)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu import native

pytestmark = pytest.mark.skipif(
    not native.is_available(),
    reason=f"native build unavailable: {native.build_error()}")


class TestArena:
    def test_alloc_views_and_reset(self):
        with native.HostArena(1 << 16) as ar:
            a = ar.alloc_array((8, 8))
            a[:] = 3.0
            b = ar.alloc_array((4,), np.int32)
            b[:] = 7
            assert float(a.sum()) == 192.0
            assert ar.used() >= a.nbytes + b.nbytes
            ar.reset()
            assert ar.used() == 0
            c = ar.alloc_array((8, 8))
            c[:] = 1.0  # reuses the same slab

    def test_alignment_and_exhaustion(self):
        with native.HostArena(4096) as ar:
            v = ar.alloc_array((4,), np.float32, align=256)
            assert v.ctypes.data % 256 == 0
            with pytest.raises(MemoryError):
                ar.alloc_array((100000,), np.float32)


class TestCSVParse:
    def test_matches_python_parse(self, rng):
        rows = rng.normal(size=(200, 7)).astype(np.float32)
        text = "\n".join(",".join(f"{v:.6f}" for v in r) for r in rows)
        out = native.parse_csv(text.encode(), 7)
        np.testing.assert_allclose(out, rows, atol=1e-5)

    def test_handles_blank_lines_and_crlf(self):
        out = native.parse_csv(b"1,2\r\n\r\n3,4\r\n", 2)
        np.testing.assert_array_equal(out, [[1, 2], [3, 4]])

    def test_non_numeric_becomes_nan(self):
        out = native.parse_csv(b"1,abc\n2,3\n", 2)
        assert np.isnan(out[0, 1]) and out[1, 1] == 3

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            native.parse_csv(b"1,2\n3,4,5\n", 2)


class TestAsyncPipeline:
    def _files(self, tmp_path, n=8, rows=50, cols=3):
        paths = []
        for i in range(n):
            p = tmp_path / f"part{i}.csv"
            p.write_text("\n".join(
                ",".join(f"{i}.0" if c == 0 else f"{r}.5" for c in range(cols))
                for r in range(rows)))
            paths.append(str(p))
        return paths

    def test_delivers_all_files_in_order(self, tmp_path):
        paths = self._files(tmp_path)
        pipe = native.AsyncCSVPipeline(paths, cols=3, n_threads=3, prefetch=2)
        seen = []
        for idx, arr in pipe:
            assert arr.shape == (50, 3)
            assert arr[0, 0] == float(idx)  # right file's data
            seen.append(idx)
        pipe.close()
        assert seen == list(range(8))

    def test_matches_single_threaded_reference(self, tmp_path, rng):
        paths = []
        ref = []
        for i in range(4):
            data = rng.normal(size=(20, 4)).astype(np.float32)
            p = tmp_path / f"r{i}.csv"
            p.write_text("\n".join(",".join(f"{v:.6f}" for v in r) for r in data))
            paths.append(str(p))
            ref.append(data)
        pipe = native.AsyncCSVPipeline(paths, cols=4, n_threads=4, prefetch=1)
        for idx, arr in pipe:
            np.testing.assert_allclose(arr, ref[idx], atol=1e-5)
        pipe.close()

    def test_unreadable_file_raises(self, tmp_path):
        paths = self._files(tmp_path, n=2)
        paths.insert(1, str(tmp_path / "missing.csv"))
        pipe = native.AsyncCSVPipeline(paths, cols=3)
        next(pipe)
        with pytest.raises(IOError):
            while True:
                next(pipe)
        pipe.close()

    def test_early_close_no_hang(self, tmp_path):
        paths = self._files(tmp_path, n=8)
        pipe = native.AsyncCSVPipeline(paths, cols=3, n_threads=2, prefetch=1)
        next(pipe)
        pipe.close()  # workers blocked on a full ring must exit


class TestNativeDataSetIterator:
    def test_trains_a_network(self, tmp_path, rng):
        from deeplearning4j_tpu.native.dataset import NativeCSVDataSetIterator
        from deeplearning4j_tpu.nn import (
            InputType, MultiLayerNetwork, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        centers = rng.standard_normal((3, 4)) * 3
        paths = []
        for i in range(4):
            ys = rng.integers(0, 3, 64)
            xs = centers[ys] + rng.standard_normal((64, 4))
            rows = np.concatenate([xs, ys[:, None]], 1)
            p = tmp_path / f"shard{i}.csv"
            p.write_text("\n".join(",".join(f"{v:.5f}" for v in r) for r in rows))
            paths.append(str(p))
        it = NativeCSVDataSetIterator(paths, batch_size=32, n_columns=5,
                                      label_index=-1, num_classes=3)
        batches = list(it)
        assert sum(len(b.features) for b in batches) == 256
        assert batches[0].features.shape == (32, 4)
        assert batches[0].labels.shape == (32, 3)

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=8)
        ev = net.evaluate(it)
        assert ev.accuracy() > 0.8, ev.accuracy()
