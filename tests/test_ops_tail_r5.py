"""Numeric tests for the round-5 op-registry tail (beyond the coverage gate).

Each section checks real semantics against an independent computation —
manual math, numpy, or brute force — per the repo's gradcheck-first standard.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import ops
from deeplearning4j_tpu.nn import updaters as U

R = np.random.default_rng(7)


# ---------------------------------------------------------------- updaters --
class TestUpdaterOps:
    def test_adam_matches_updater_class(self):
        g = jnp.asarray(R.normal(size=(5,)).astype(np.float32))
        m = jnp.asarray(R.normal(size=(5,)).astype(np.float32)) * 0.1
        v = jnp.abs(jnp.asarray(R.normal(size=(5,)).astype(np.float32))) * 0.1
        upd, m2, v2 = ops.exec_op("adam_updater", g, m, v, lr=0.01,
                                  iteration=3)
        ref_u, ref_s = U.Adam(learning_rate=0.01).apply(
            g, {"m": m, "v": v}, 3)
        np.testing.assert_allclose(upd, ref_u, rtol=1e-6)
        np.testing.assert_allclose(m2, ref_s["m"], rtol=1e-6)
        np.testing.assert_allclose(v2, ref_s["v"], rtol=1e-6)

    def test_sgd_and_apply_sgd(self):
        g = jnp.asarray([1.0, -2.0])
        np.testing.assert_allclose(ops.exec_op("sgd_updater", g, lr=0.5),
                                   [0.5, -1.0])
        p = jnp.asarray([10.0, 10.0])
        np.testing.assert_allclose(ops.exec_op("apply_sgd", p, g, lr=0.5),
                                   [9.5, 11.0])

    @pytest.mark.parametrize("name,cls,nstate", [
        ("nesterovs_updater", U.Nesterovs, 1),
        ("ada_grad_updater", U.AdaGrad, 1),
        ("rms_prop_updater", U.RmsProp, 1),
        ("nadam_updater", U.Nadam, 2),
        ("ada_max_updater", U.AdaMax, 2),
    ])
    def test_delegation_consistency(self, name, cls, nstate):
        """Every updater op must agree with the class the training loop uses
        — the invariant the module exists for."""
        g = jnp.asarray(R.normal(size=(4,)).astype(np.float32))
        states = [jnp.abs(jnp.asarray(
            R.normal(size=(4,)).astype(np.float32))) * 0.1
            for _ in range(nstate)]
        out = ops.exec_op(name, g, *states, iteration=2)
        upd = out[0]
        inst = cls()
        keys = list(inst.init_state(g).keys())
        ref_u, _ = inst.apply(g, dict(zip(keys, states)), 2)
        # op defaults must match class defaults for the shared hyperparams
        kw = {}
        if hasattr(inst, "learning_rate"):
            kw = {}
        np.testing.assert_allclose(upd, ref_u, rtol=1e-5, atol=1e-7)


# ------------------------------------------------------------ word2vec ops --
class TestSkipgramCbow:
    def test_skipgram_matches_manual_gradient(self):
        syn0 = jnp.asarray(R.normal(size=(6, 4)).astype(np.float32)) * 0.1
        syn1 = jnp.asarray(R.normal(size=(6, 4)).astype(np.float32)) * 0.1
        target, samples = 2, jnp.asarray([1, 4, 5])
        labels = jnp.asarray([1.0, 0.0, 0.0])
        lr = 0.1
        s0, s1, loss = ops.exec_op("skipgram", syn0, syn1, target, samples,
                                   labels, lr=lr)
        # manual: g_k = lr*(label - sigma(w_k . h))
        h = np.asarray(syn0)[2]
        w = np.asarray(syn1)[np.asarray(samples)]
        p = 1 / (1 + np.exp(-(w @ h)))
        gk = lr * (np.asarray(labels) - p)
        np.testing.assert_allclose(np.asarray(s0)[2], h + gk @ w, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s1)[1],
                                   w[0] + gk[0] * h, rtol=1e-5)
        assert float(loss) > 0

    def test_skipgram_training_reduces_loss(self):
        """Repeated updates on one (target, context) pair must drive the
        positive-sample probability up."""
        syn0 = jnp.asarray(R.normal(size=(8, 6)).astype(np.float32)) * 0.1
        syn1 = jnp.zeros((8, 6), jnp.float32)
        samples = jnp.asarray([3, 5, 6])
        labels = jnp.asarray([1.0, 0.0, 0.0])
        first = None
        for _ in range(50):
            syn0, syn1, loss = ops.exec_op("skipgram", syn0, syn1, 1,
                                           samples, labels, lr=0.5)
            first = first if first is not None else float(loss)
        assert float(loss) < first * 0.2

    def test_cbow_mask_and_mean(self):
        syn0 = jnp.ones((5, 3), jnp.float32) * jnp.asarray(
            [[1.0], [2.0], [3.0], [4.0], [0.0]])
        syn1 = jnp.asarray(R.normal(size=(5, 3)).astype(np.float32)) * 0.1
        ctx = jnp.asarray([0, 1, 4])
        mask = jnp.asarray([1.0, 1.0, 0.0])
        s0m, _, _ = ops.exec_op("cbow", syn0, syn1, ctx, jnp.asarray([2]),
                                jnp.asarray([1.0]), lr=0.1,
                                context_mask=mask)
        # masked slot 4 must be untouched
        np.testing.assert_allclose(np.asarray(s0m)[4], np.asarray(syn0)[4])
        assert not np.allclose(np.asarray(s0m)[0], np.asarray(syn0)[0])


# ----------------------------------------------------------- barnes / tsne --
class TestBarnesOps:
    def test_edge_forces_match_dense(self):
        n, e = 5, 8
        rows = jnp.asarray(R.integers(0, n, e))
        cols = jnp.asarray(R.integers(0, n, e))
        vals = jnp.asarray(R.random(e).astype(np.float32))
        y = jnp.asarray(R.normal(size=(n, 2)).astype(np.float32))
        out = ops.exec_op("barnes_edge_forces", rows, cols, vals, y)
        dense = np.zeros((n, 2), np.float32)
        for i, j, v in zip(np.asarray(rows), np.asarray(cols),
                           np.asarray(vals)):
            d = np.asarray(y)[i] - np.asarray(y)[j]
            dense[i] += v * d / (1 + d @ d)
        np.testing.assert_allclose(out, dense, rtol=1e-5, atol=1e-6)

    def test_symmetrize_equals_dense_symmetrization(self):
        rows = jnp.asarray([0, 1, 2])
        cols = jnp.asarray([1, 2, 0])
        vals = jnp.asarray([1.0, 2.0, 4.0])
        r2, c2, v2 = ops.exec_op("barnes_symmetrized", rows, cols, vals)
        dense = np.zeros((3, 3))
        for i, j, v in zip(np.asarray(r2), np.asarray(c2), np.asarray(v2)):
            dense[i, j] += v
        p = np.zeros((3, 3))
        for i, j, v in zip([0, 1, 2], [1, 2, 0], [1.0, 2.0, 4.0]):
            p[i, j] = v
        np.testing.assert_allclose(dense, (p + p.T) / 2)

    def test_gains_rule(self):
        gains = jnp.ones((2, 2))
        grad = jnp.asarray([[1.0, -1.0], [1.0, 1.0]])
        incs = jnp.asarray([[1.0, 1.0], [-1.0, 1.0]])
        out = np.asarray(ops.exec_op("barnes_gains", gains, grad, incs))
        np.testing.assert_allclose(out, [[0.8, 1.2], [1.2, 0.8]])

    def test_knn_mindistance(self):
        d = ops.exec_op("knn_mindistance", jnp.asarray([2.0, 0.0]),
                        jnp.asarray([-1.0, -1.0]), jnp.asarray([1.0, 1.0]))
        np.testing.assert_allclose(d, 1.0)
        inside = ops.exec_op("knn_mindistance", jnp.zeros(2),
                             -jnp.ones(2), jnp.ones(2))
        np.testing.assert_allclose(inside, 0.0)
        assert bool(ops.exec_op("cell_contains", jnp.zeros(2), jnp.ones(2),
                                jnp.asarray([0.5, -0.9])))


# -------------------------------------------------------------- conv tail --
class TestConvTail:
    def test_dilation2d_manual(self):
        x = jnp.asarray(R.normal(size=(1, 5, 5, 2)).astype(np.float32))
        f = jnp.asarray(R.normal(size=(2, 2, 2)).astype(np.float32)) * 0.1
        out = ops.exec_op("dilation2d", x, f, padding="VALID")
        xn, fn = np.asarray(x), np.asarray(f)
        man = np.zeros((1, 4, 4, 2), np.float32)
        for y in range(4):
            for xx in range(4):
                for c in range(2):
                    man[0, y, xx, c] = np.max(
                        xn[0, y:y + 2, xx:xx + 2, c] + fn[:, :, c])
        np.testing.assert_allclose(out, man, rtol=1e-5)

    def test_erosion_duality(self):
        x = jnp.asarray(R.normal(size=(1, 6, 6, 1)).astype(np.float32))
        f = jnp.asarray(R.normal(size=(3, 3, 1)).astype(np.float32)) * 0.1
        ero = ops.exec_op("erosion2d", x, f, padding="VALID")
        dil = ops.exec_op("dilation2d", -x, f[::-1, ::-1, :],
                          padding="VALID")
        np.testing.assert_allclose(ero, -np.asarray(dil), rtol=1e-5)

    def test_max_pool_with_argmax_flat_indices(self):
        x = jnp.arange(32.0).reshape(1, 4, 4, 2)
        vals, idx = ops.exec_op("max_pool_with_argmax", x)
        np.testing.assert_allclose(
            np.asarray(vals).ravel(),
            np.asarray(x).reshape(4, 4, 2)[1::2, 1::2, :].ravel())
        # TF flat index convention: value recoverable by flat lookup
        flat = np.asarray(x).ravel()
        np.testing.assert_allclose(flat[np.asarray(idx).ravel()],
                                   np.asarray(vals).ravel())

    def test_deconv3d_inverts_stride_shape(self):
        x = jnp.ones((2, 3, 3, 3, 4))
        w = jnp.ones((2, 2, 2, 4, 6)) * 0.1
        out = ops.exec_op("deconv3d", x, w, strides=(2, 2, 2))
        assert out.shape == (2, 6, 6, 6, 6)

    def test_deconv3d_int_stride(self):
        out = ops.exec_op("deconv3d", jnp.ones((1, 2, 2, 2, 3)),
                          jnp.ones((2, 2, 2, 3, 4)) * 0.1, strides=2)
        assert out.shape == (1, 4, 4, 4, 4)

    def test_upsampling3d(self):
        x = jnp.arange(8.0).reshape(1, 2, 2, 2, 1)
        out = ops.exec_op("upsampling3d", x, 2)
        assert out.shape == (1, 4, 4, 4, 1)
        np.testing.assert_allclose(np.asarray(out)[0, :2, :2, :2, 0],
                                   np.asarray(x)[0, 0, 0, 0, 0])

    def test_mean_pairwise_sq_err_vs_bruteforce(self):
        p = R.normal(size=(3, 5)).astype(np.float32)
        l = R.normal(size=(3, 5)).astype(np.float32)
        got = float(ops.exec_op("mean_pairwssqerr_loss", jnp.asarray(p),
                                jnp.asarray(l)))
        d = p - l
        per = []
        for b in range(3):
            acc, cnt = 0.0, 0
            for i in range(5):
                for j in range(5):
                    if i != j:
                        acc += (d[b, i] - d[b, j]) ** 2 / 2
                        cnt += 1
            per.append(acc / cnt)  # mean over ordered pairs of (d_i-d_j)^2/2
        # identity form: (n*sum_sq - sq_sum)/(n(n-1)) == mean over ordered
        # pairs of (d_i-d_j)^2 / 2 * 2 ... assert against the direct formula
        per2 = [(5 * (d[b] ** 2).sum() - d[b].sum() ** 2) / (5 * 4)
                for b in range(3)]
        np.testing.assert_allclose(got, np.mean(per2), rtol=1e-5)
        np.testing.assert_allclose(np.mean(per), np.mean(per2), rtol=1e-5)


# ------------------------------------------------------------ ctc decoder --
class TestCtcBeamSearch:
    def test_peaked_distribution_greedy_consistent(self):
        # classes: 0=blank; emit 1,1,blank,2 -> collapse to [1, 2]
        logits = np.full((1, 4, 3), -10.0, np.float32)
        for t, c in enumerate([1, 1, 0, 2]):
            logits[0, t, c] = 10.0
        lp = jax.nn.log_softmax(jnp.asarray(logits))
        paths, logp = ops.exec_op("ctc_beam_search_decoder", lp,
                                  beam_width=8)
        assert paths[0][0] == [1, 2]
        assert logp.shape == (1, 1)

    def test_merging_beats_greedy(self):
        """The canonical CTC case: many alignments of one short label can
        outweigh the single best alignment of the greedy label."""
        # T=2, classes 0=blank,1=a. P(blank)=0.6, P(a)=0.4 each step.
        # Greedy per-frame: [blank, blank] -> []. p([]) = 0.36 but
        # p([a]) = 0.4*0.4(a,a collapses) + 0.4*0.6 + 0.6*0.4 = 0.64.
        probs = np.asarray([[[0.6, 0.4], [0.6, 0.4]]], np.float32)
        lp = jnp.asarray(np.log(probs))
        paths, logp = ops.exec_op("ctc_beam_search_decoder", lp,
                                  beam_width=4, top_paths=2)
        assert paths[0][0] == [1]
        np.testing.assert_allclose(np.exp(logp[0][0]), 0.64, rtol=1e-5)
        np.testing.assert_allclose(np.exp(logp[0][1]), 0.36, rtol=1e-5)


# ------------------------------------------------------------- rnn tail ----
class TestRnnTail:
    def _params(self, i=3, h=4):
        wx = jnp.asarray(R.normal(size=(i, h)).astype(np.float32)) * 0.3
        wh = jnp.asarray(R.normal(size=(h, h)).astype(np.float32)) * 0.3
        b = jnp.asarray(R.normal(size=(h,)).astype(np.float32)) * 0.1
        return wx, wh, b

    def test_static_equals_dynamic(self):
        wx, wh, b = self._params()
        x = jnp.asarray(R.normal(size=(5, 2, 3)).astype(np.float32))
        ys1, h1 = ops.exec_op("static_rnn", x, wx, wh, b)
        ys2, h2 = ops.exec_op("dynamic_rnn", x, wx, wh, b)
        np.testing.assert_allclose(ys1, ys2, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(h1, h2, rtol=1e-5, atol=1e-6)

    def test_seq_lens_freeze_state_zero_output(self):
        wx, wh, b = self._params()
        x = jnp.asarray(R.normal(size=(4, 2, 3)).astype(np.float32))
        ys, h = ops.exec_op("dynamic_rnn", x, wx, wh, b,
                            seq_lens=jnp.asarray([2, 4]))
        np.testing.assert_allclose(np.asarray(ys)[2:, 0], 0.0)
        ys_short, h_short = ops.exec_op("dynamic_rnn", x[:2, :1], wx, wh, b)
        np.testing.assert_allclose(h[0], h_short[0], rtol=1e-5, atol=1e-6)

    def test_bidirectional_reverse_semantics(self):
        wx, wh, b = self._params()
        wx2, wh2, b2 = self._params()
        x = jnp.asarray(R.normal(size=(4, 1, 3)).astype(np.float32))
        ys, (hf, hb) = ops.exec_op("static_bidirectional_rnn", x, wx, wh, b,
                                   wx2, wh2, b2)
        assert ys.shape == (4, 1, 8)
        # backward half at t=0 equals forward pass over reversed input at end
        ys_rev, h_rev = ops.exec_op("static_rnn", x[::-1], wx2, wh2, b2)
        np.testing.assert_allclose(np.asarray(ys)[:, :, 4:],
                                   np.asarray(ys_rev)[::-1], rtol=1e-5)
        np.testing.assert_allclose(hb, h_rev, rtol=1e-5)

    def test_sru_bi_shapes_and_direction(self):
        x = jnp.asarray(R.normal(size=(5, 2, 8)).astype(np.float32))
        w = jnp.asarray(R.normal(size=(2, 12, 4)).astype(np.float32)) * 0.1
        b = jnp.zeros((2, 8))
        h, c = ops.exec_op("sru_bi", x, w, b)
        assert h.shape == (5, 2, 8) and c.shape == (2, 2, 4)
        hf, cf = ops.exec_op("sru", x[..., :4], w[0], b[0], layout=0)
        np.testing.assert_allclose(np.asarray(h)[..., :4], hf, rtol=1e-5)


# ---------------------------------------------------------- shape/bit tail --
class TestShapeBitTail:
    def test_scatter_nd_variants(self):
        ref = jnp.zeros((4, 2))
        idx = jnp.asarray([[1], [1]])
        upd = jnp.ones((2, 2))
        added = ops.exec_op("scatter_nd_add", ref, idx, upd)
        np.testing.assert_allclose(np.asarray(added)[1], [2.0, 2.0])
        sub = ops.exec_op("scatter_nd_sub", ref, idx, upd)
        np.testing.assert_allclose(np.asarray(sub)[1], [-2.0, -2.0])
        setv = ops.exec_op("scatter_nd_update", ref, idx, upd)
        np.testing.assert_allclose(np.asarray(setv)[1], [1.0, 1.0])

    def test_tear_and_bitcast(self):
        parts = ops.exec_op("tear", jnp.arange(12.0).reshape(3, 4), axis=1)
        assert len(parts) == 4 and parts[0].shape == (3,)
        np.testing.assert_allclose(parts[2], [2.0, 6.0, 10.0])
        x = jnp.asarray([1.5, -2.0], jnp.float32)
        round_trip = ops.exec_op("bitcast",
                                 ops.exec_op("bitcast", x, jnp.int32),
                                 jnp.float32)
        np.testing.assert_allclose(round_trip, x)
        # TF width-change semantics: narrow appends a ratio dim, widen
        # consumes it (NOT numpy's flat view)
        narrow = ops.exec_op("bitcast", x, jnp.uint8)
        assert narrow.shape == (2, 4)
        wide = ops.exec_op("bitcast", narrow, jnp.float32)
        assert wide.shape == (2,)
        np.testing.assert_allclose(wide, x)
        with pytest.raises(ValueError):
            ops.exec_op("bitcast", jnp.zeros((3,), jnp.uint8), jnp.float32)

    def test_broadcast_dynamic_shape(self):
        out = ops.exec_op("broadcast_dynamic_shape", jnp.asarray([2, 1, 3]),
                          jnp.asarray([4, 1]))
        np.testing.assert_array_equal(out, [2, 4, 3])

    def test_hamming_and_rotr(self):
        a = np.asarray([0b1010, 0b1111], np.int32)
        b = np.asarray([0b0101, 0b1111], np.int32)
        got = int(ops.exec_op("bits_hamming_distance", jnp.asarray(a),
                              jnp.asarray(b)))
        assert got == 4
        x = jnp.asarray([8], jnp.int32)
        np.testing.assert_array_equal(
            ops.exec_op("cyclic_rshift_bits", x, 3), [1])
        # rotr by 0 is identity; rotr(rotl(x)) round-trips
        np.testing.assert_array_equal(
            ops.exec_op("cyclic_rshift_bits",
                        ops.exec_op("cyclic_shift_bits", x, 7), 7), x)


# ------------------------------------------------------------- quant tail --
class TestQuantTail:
    def test_fake_quant_grid_and_clip(self):
        x = jnp.asarray([-10.0, 0.0, 2.5, 10.0])
        y = np.asarray(ops.exec_op("fake_quant_with_min_max_vars", x,
                                   min=0.0, max=6.0))
        scale = 6.0 / 255.0
        assert y[0] == 0.0 and abs(y[3] - 6.0) < 1e-6
        np.testing.assert_allclose(y[2] / scale, np.round(y[2] / scale),
                                   atol=1e-4)

    def test_fake_quant_straight_through_grad(self):
        f = lambda x: jnp.sum(ops.exec_op(
            "fake_quant_with_min_max_vars", x, min=0.0, max=6.0))
        g = jax.grad(f)(jnp.asarray([-1.0, 3.0, 7.0]))
        np.testing.assert_allclose(g, [0.0, 1.0, 0.0])

    def test_per_channel(self):
        x = jnp.asarray([[-2.0, 2.0], [0.5, 0.5]])
        y = ops.exec_op("fake_quant_with_min_max_vars_per_channel", x,
                        jnp.asarray([-1.0, 0.0]), jnp.asarray([1.0, 1.0]))
        assert float(y[0, 0]) >= -1.001 and float(y[0, 1]) <= 1.001

    def test_compare_and_bitpack(self):
        x = jnp.asarray(R.normal(size=(2, 16)).astype(np.float32))
        out = np.asarray(ops.exec_op("compare_and_bitpack", x, 0.0))
        ref = np.packbits((np.asarray(x) > 0).astype(np.uint8),
                          axis=-1)
        np.testing.assert_array_equal(out, ref)


# ------------------------------------------------------------ linalg tail --
class TestLinalgTail:
    def test_lup_reconstructs(self):
        a = jnp.asarray(R.normal(size=(4, 4)).astype(np.float32))
        l, u, p = ops.exec_op("lup", a)
        np.testing.assert_allclose(np.asarray(a)[np.asarray(p)],
                                   np.asarray(l) @ np.asarray(u),
                                   rtol=1e-4, atol=1e-5)
        assert np.allclose(np.triu(np.asarray(l), 1), 0)
        assert np.allclose(np.tril(np.asarray(u), -1), 0)

    def test_matrix_set_diag(self):
        x = jnp.ones((2, 3))
        out = ops.exec_op("matrix_set_diag", x, jnp.asarray([7.0, 8.0]))
        np.testing.assert_allclose(np.asarray(out),
                                   [[7, 1, 1], [1, 8, 1]])

    def test_solve_ls_matches_lstsq(self):
        a = jnp.asarray(R.normal(size=(6, 3)).astype(np.float32))
        b = jnp.asarray(R.normal(size=(6, 2)).astype(np.float32))
        fast = ops.exec_op("solve_ls", a, b)
        slow = ops.exec_op("solve_ls", a, b, fast=False)
        np.testing.assert_allclose(fast, slow, rtol=1e-3, atol=1e-4)
        # regularization shrinks the solution
        reg = ops.exec_op("solve_ls", a, b, l2_regularizer=10.0)
        assert np.linalg.norm(np.asarray(reg)) < np.linalg.norm(
            np.asarray(fast))

    def test_sufficient_statistics_compose_to_moments(self):
        x = jnp.asarray(R.normal(size=(8, 3)).astype(np.float32))
        count, m_ss, v_ss, shift = ops.exec_op("sufficient_statistics", x,
                                               (0,))
        mean, var = ops.exec_op("normalize_moments", count, m_ss, v_ss)
        np.testing.assert_allclose(mean, jnp.mean(x, axis=0), rtol=1e-5)
        np.testing.assert_allclose(var, jnp.var(x, axis=0), rtol=1e-4,
                                   atol=1e-5)

    def test_zero_fraction(self):
        np.testing.assert_allclose(
            ops.exec_op("zero_fraction", jnp.asarray([0.0, 1.0, 0.0, 2.0])),
            0.5)

    def test_check_numerics(self):
        x = jnp.asarray([1.0, 2.0])
        np.testing.assert_allclose(ops.exec_op("check_numerics", x), x)
        with pytest.raises(FloatingPointError):
            ops.exec_op("check_numerics", jnp.asarray([1.0, np.nan]))
