"""DataVec-parity ETL tests — mirrors the reference's CSVRecordReaderTest,
TransformProcessTest and RecordReaderDataSetIteratorTest coverage
(SURVEY.md §2.2 J12, §4)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import NormalizerStandardize
from deeplearning4j_tpu.datavec import (
    CollectionRecordReader,
    ColumnType,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReaderDataSetIterator,
    RegexLineRecordReader,
    Schema,
    SequenceRecordReaderDataSetIterator,
    SVMLightRecordReader,
    TransformProcess,
    TransformProcessRecordReader,
)


@pytest.fixture
def iris_csv(tmp_path):
    p = tmp_path / "iris.csv"
    rng = np.random.default_rng(0)
    lines = []
    for i in range(30):
        f = rng.uniform(0, 8, 4)
        lines.append(",".join(f"{v:.2f}" for v in f) + f",{i % 3}")
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_csv_record_reader(iris_csv):
    rr = CSVRecordReader(iris_csv)
    recs = list(rr)
    assert len(recs) == 30
    assert len(recs[0]) == 5
    assert recs[0][4] == "0"
    # reset semantics
    assert len(list(rr)) == 30


def test_line_and_regex_readers(tmp_path):
    p = tmp_path / "log.txt"
    p.write_text("2026-01-01 INFO start\n2026-01-02 WARN slow\n")
    assert list(LineRecordReader(str(p)))[1] == ["2026-01-02 WARN slow"]
    rr = RegexLineRecordReader(str(p), r"(\S+) (\S+) (\S+)")
    assert list(rr) == [
        ["2026-01-01", "INFO", "start"],
        ["2026-01-02", "WARN", "slow"],
    ]


def test_svmlight_reader(tmp_path):
    p = tmp_path / "data.svm"
    p.write_text("1 1:0.5 3:2.0\n0 2:1.5\n")
    recs = list(SVMLightRecordReader(str(p), num_features=3))
    assert recs[0] == [0.5, 0.0, 2.0, 1.0]
    assert recs[1] == [0.0, 1.5, 0.0, 0.0]


def test_csv_sequence_reader_and_iterator(tmp_path):
    for i, L in enumerate((3, 5)):
        rows = "\n".join(f"{t}.0,{t % 2}" for t in range(L))
        (tmp_path / f"seq_{i}.csv").write_text(rows + "\n")
    rr = CSVSequenceRecordReader(str(tmp_path))
    seqs = list(rr)
    assert [len(s) for s in seqs] == [3, 5]

    it = SequenceRecordReaderDataSetIterator(rr, batch_size=2, label_index=-1, num_classes=2)
    ds = next(iter(it))
    assert ds.features.shape == (2, 5, 1)
    assert ds.labels.shape == (2, 5, 2)
    np.testing.assert_array_equal(ds.features_mask.sum(1), [3, 5])


def test_image_record_reader(tmp_path):
    from PIL import Image

    for label in ("cat", "dog"):
        d = tmp_path / label
        d.mkdir()
        for i in range(2):
            Image.fromarray(
                (np.random.default_rng(i).uniform(0, 255, (20, 16, 3))).astype(np.uint8)
            ).save(d / f"{i}.png")
    rr = ImageRecordReader(height=8, width=10, channels=3, root=str(tmp_path))
    recs = list(rr)
    assert len(recs) == 4
    assert recs[0][0].shape == (8, 10, 3)  # HWC resize
    assert rr.labels == ["cat", "dog"]
    assert {r[1] for r in recs} == {0, 1}

    it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=1, num_classes=2)
    ds = next(iter(it))
    assert ds.features.shape == (4, 8, 10, 3)
    assert ds.labels.shape == (4, 2)


def test_record_reader_dataset_iterator_classification(iris_csv):
    rr = CSVRecordReader(iris_csv)
    it = RecordReaderDataSetIterator(rr, batch_size=8, label_index=4, num_classes=3)
    batches = list(it)
    assert batches[0].features.shape == (8, 4)
    assert batches[0].labels.shape == (8, 3)
    assert sum(b.num_examples() for b in batches) == 30
    np.testing.assert_allclose(batches[0].labels.sum(1), 1.0)
    # with normalizer attached as preprocessor
    norm = NormalizerStandardize().fit(
        RecordReaderDataSetIterator(CSVRecordReader(iris_csv), 30, label_index=4, num_classes=3)
    )
    it2 = RecordReaderDataSetIterator(
        CSVRecordReader(iris_csv), 30, label_index=4, num_classes=3, preprocessor=norm
    )
    ds = next(iter(it2))
    assert abs(float(ds.features.mean())) < 0.05


def test_transform_process_schema_and_records():
    schema = (
        Schema.builder()
        .add_column_string("name")
        .add_column_categorical("color", "red", "green", "blue")
        .add_column_double("size")
        .add_column_integer("count")
        .build()
    )
    tp = (
        TransformProcess.builder(schema)
        .remove_columns("name")
        .categorical_to_one_hot("color")
        .double_math_op("size", "multiply", 2.0)
        .filter(lambda r, s: r[s.column_index("count")] < 0)
        .build()
    )
    fs = tp.final_schema()
    assert fs.column_names() == ["color[red]", "color[green]", "color[blue]", "size", "count"]
    assert fs.column_type("size") == ColumnType.Double

    out = tp.execute([
        ["a", "green", 1.5, 3],
        ["b", "red", 2.0, -1],  # filtered
        ["c", "blue", 0.5, 7],
    ])
    assert out == [[0, 1, 0, 3.0, 3], [0, 0, 1, 1.0, 7]]


def test_transform_conditional_rename_reorder_time():
    schema = (
        Schema.builder()
        .add_column_double("x")
        .add_column_string("ts")
        .build()
    )
    tp = (
        TransformProcess.builder(schema)
        .conditional_replace_value_transform("x", 0.0, lambda v: float(v) < 0)
        .rename_column("x", "clipped")
        .string_to_time("ts", "%Y-%m-%d")
        .reorder_columns("ts")
        .build()
    )
    fs = tp.final_schema()
    assert fs.column_names() == ["ts", "clipped"]
    assert fs.column_type("ts") == ColumnType.Time
    out = tp.execute_record([-3.0, "2026-07-29"])
    assert out[1] == 0.0
    assert isinstance(out[0], int) and out[0] > 1_500_000_000_000


def test_transform_process_record_reader():
    rr = CollectionRecordReader([["1.0", "4"], ["2.0", "5"]])
    schema = Schema.builder().add_column_double("a").add_column_integer("b").build()
    tp = (
        TransformProcess.builder(schema)
        .convert_to_double("a")
        .double_math_op("a", "add", 10.0)
        .build()
    )
    out = list(TransformProcessRecordReader(rr, tp))
    assert out == [[11.0, "4"], [12.0, "5"]]


def test_sequence_iterator_align_end(tmp_path):
    for i, L in enumerate((3, 5)):
        rows = "\n".join(f"{t}.0,{t % 2}" for t in range(L))
        (tmp_path / f"seq_{i}.csv").write_text(rows + "\n")
    rr = CSVSequenceRecordReader(str(tmp_path))
    it = SequenceRecordReaderDataSetIterator(
        rr, batch_size=2, label_index=-1, num_classes=2, alignment_mode="align_end")
    ds = next(iter(it))
    # short sequence right-aligned: padding at the start, data at t=2..4
    np.testing.assert_array_equal(ds.features_mask[0], [0, 0, 1, 1, 1])
    np.testing.assert_array_equal(ds.features_mask[1], [1, 1, 1, 1, 1])
    np.testing.assert_array_equal(ds.features[0, :2, 0], [0.0, 0.0])
    np.testing.assert_array_equal(ds.features[0, 2:, 0], [0.0, 1.0, 2.0])


class TestRound2DataVec:
    """Audio reader, Arrow serde, joins (J12 gaps from VERDICT r1)."""

    def test_wav_record_reader(self, tmp_path):
        import wave

        from deeplearning4j_tpu.datavec.records import WavFileRecordReader

        path = str(tmp_path / "tone.wav")
        sr = 8000
        t = np.arange(sr // 4) / sr
        samples = (np.sin(2 * np.pi * 440 * t) * 32000).astype(np.int16)
        with wave.open(path, "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(sr)
            w.writeframes(samples.tobytes())
        rec = next(iter(WavFileRecordReader([path])))
        wavef, rate = rec
        assert rate == sr
        assert wavef.shape == (len(samples), 1)
        np.testing.assert_allclose(
            wavef[:, 0], samples.astype(np.float32) / 32768.0, atol=1e-6)

    def test_arrow_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.datavec.records import (
            ArrowRecordReader,
            write_arrow,
        )

        path = str(tmp_path / "t.feather")
        records = [[1, "a", 0.5], [2, "b", 1.5], [3, "c", 2.5]]
        write_arrow(path, records, ["id", "name", "x"])
        back = list(ArrowRecordReader(path))
        assert back == records

    def test_join_inner_and_outer(self):
        from deeplearning4j_tpu.datavec.transform import Join, Schema

        left = (Schema.Builder().add_column_integer("id")
                .add_column_string("name").build())
        right = (Schema.Builder().add_column_integer("id")
                 .add_column_string("city").build())
        L = [[1, "ann"], [2, "bob"], [3, "cyd"]]
        R = [[1, "oslo"], [1, "pune"], [4, "rome"]]
        inner = (Join.Builder("inner").set_join_columns("id")
                 .set_schemas(left, right).build())
        rows = inner.execute(L, R)
        assert rows == [[1, "ann", "oslo"], [1, "ann", "pune"]]
        assert inner.output_schema().column_names() == ["id", "name", "city"]
        louter = Join("LeftOuter", ["id"], left, right)
        rows = louter.execute(L, R)
        assert [1, "ann", "oslo"] in rows and [2, "bob", None] in rows
        fouter = Join("FullOuter", ["id"], left, right)
        rows = fouter.execute(L, R)
        assert [4, None, "rome"] in rows
        assert len(rows) == 5


class TestTransformBreadth:
    """Round-3 TransformProcess column-op breadth (round-2 deferred item):
    the DataVec transform families beyond the original core set."""

    def _schema(self):
        return (Schema.builder()
                .add_column_string("name")
                .add_column_integer("age")
                .add_column_double("score")
                .add_column_time("ts")
                .build())

    def test_fill_filter_const_dup(self):
        tp = (TransformProcess.builder(self._schema())
              .replace_missing_value_with("age", 0)
              .filter_invalid_values("score")
              .add_constant_column("source", ColumnType.String, "web")
              .duplicate_column("age", "age_copy")
              .build())
        recs = [["a", None, 1.5, 0], ["b", 3, None, 0], ["c", 7, 2.0, 0]]
        out = tp.execute(recs)
        assert out == [["a", 0, 1.5, 0, "web", 0],
                       ["c", 7, 2.0, 0, "web", 7]]
        assert tp.final_schema().column_names() == [
            "name", "age", "score", "ts", "source", "age_copy"]

    def test_int_math_and_categorical_roundtrip(self):
        tp = (TransformProcess.builder(self._schema())
              .integer_math_op("age", "Multiply", 2)
              .integer_math_op("age", "ScalarMin", 10)
              .integer_to_categorical("age", [str(i) for i in range(11)])
              .build())
        out = tp.execute([["a", 3, 0.0, 0], ["b", 9, 0.0, 0]])
        assert [r[1] for r in out] == ["6", "10"]
        assert tp.final_schema().column_type("age") == ColumnType.Categorical

    def test_string_transforms(self):
        tp = (TransformProcess.builder(self._schema())
              .change_case_string_transform("name", upper=True)
              .replace_string_transform("name", "OB", "o")
              .map_string("name", lambda v: v + "!")
              .build())
        out = tp.execute([["bob", 1, 0.0, 0]])
        assert out[0][0] == "Bo!"

    def test_normalize_and_standardize(self):
        tp = (TransformProcess.builder(self._schema())
              .normalize("score", 0.0, 10.0)
              .build())
        assert tp.execute([["a", 1, 5.0, 0]])[0][2] == 0.5
        tp2 = (TransformProcess.builder(self._schema())
               .standardize("score", mean=2.0, stdev=2.0)
               .build())
        assert tp2.execute([["a", 1, 6.0, 0]])[0][2] == 2.0

    def test_derive_time_fields(self):
        # 2021-06-15 13:45:00 UTC
        ms = 1623764700000
        tp = (TransformProcess.builder(self._schema())
              .derive_column_from_time("ts", "hour_of_day")
              .derive_column_from_time("ts", "day_of_week")
              .build())
        out = tp.execute([["a", 1, 0.0, ms]])[0]
        assert out[-2] == 13
        assert out[-1] == 2  # Tuesday (Joda/DataVec: Monday=1..Sunday=7)
        names = tp.final_schema().column_names()
        assert names[-2:] == ["ts_hour_of_day", "ts_day_of_week"]


class TestReducer:
    def test_group_by_aggregations(self):
        from deeplearning4j_tpu.datavec import Reducer

        schema = (Schema.builder()
                  .add_column_string("city")
                  .add_column_double("temp")
                  .add_column_integer("count")
                  .build())
        red = (Reducer.Builder(schema, "city")
               .mean_columns("temp")
               .sum_columns("count")
               .build())
        out = red.execute([
            ["nyc", 10.0, 1], ["sf", 20.0, 2],
            ["nyc", 30.0, 3], ["sf", 10.0, 4],
        ])
        assert out == [["nyc", 20.0, 4.0], ["sf", 15.0, 6.0]]
        names = red.output_schema().column_names()
        assert names == ["city", "mean(temp)", "sum(count)"]

    def test_default_and_stdev(self):
        from deeplearning4j_tpu.datavec import Reducer

        schema = (Schema.builder()
                  .add_column_string("k")
                  .add_column_double("v")
                  .build())
        red = Reducer(schema, ["k"], default_op="stdev")
        out = red.execute([["a", 1.0], ["a", 3.0]])
        np.testing.assert_allclose(out[0][1], np.std([1.0, 3.0], ddof=1))


def _int_schema():
    return (Schema.builder().add_column_string("name")
            .add_column_integer("age").build())


def test_int_math_java_semantics():
    """Divide truncates toward zero, Modulus keeps the dividend's sign
    (Java semantics — review fix)."""
    tp = (TransformProcess.builder(_int_schema())
          .integer_math_op("age", "Divide", 2).build())
    assert tp.execute([["a", -7]])[0][1] == -3
    tp2 = (TransformProcess.builder(_int_schema())
           .integer_math_op("age", "Modulus", 3).build())
    assert tp2.execute([["a", -7]])[0][1] == -1


def test_int_to_categorical_range_checked():
    tp = (TransformProcess.builder(_int_schema())
          .integer_to_categorical("age", ["a", "b"]).build())
    with pytest.raises(ValueError, match="out of range"):
        tp.execute([["x", -1]])


def test_int_math_exact_above_2_53():
    """No float64 detour: Long-range values divide exactly (review fix)."""
    big = 2**53 + 1
    tp = (TransformProcess.builder(_int_schema())
          .integer_math_op("age", "Divide", 1).build())
    assert tp.execute([["a", big]])[0][1] == big


def test_fillna_covers_nan():
    schema = (Schema.builder().add_column_string("n")
              .add_column_double("v").build())
    tp = (TransformProcess.builder(schema)
          .replace_missing_value_with("v", 0.0).build())
    assert tp.execute([["a", float("nan")]])[0][1] == 0.0
