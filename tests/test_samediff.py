"""SameDiff-parity graph API tests (SURVEY §4: SameDiffTests.java analog —
graph build/exec/grad, serialization round-trip, training)."""

import numpy as np
import pytest

from deeplearning4j_tpu.samediff import SameDiff, TrainingConfig, VariableType
from deeplearning4j_tpu.nn.updaters import Sgd, Adam


def test_basic_arithmetic_eval():
    sd = SameDiff()
    a = sd.var("a", np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    b = sd.constant(np.array([[10.0, 20.0], [30.0, 40.0]], np.float32), "b")
    c = (a + b) * 2.0 - 1.0
    out = c.eval()
    np.testing.assert_allclose(out, (np.array([[1, 2], [3, 4.0]]) + [[10, 20], [30, 40]]) * 2 - 1)


def test_placeholder_exec_and_shape():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3), dtype=np.float32)
    w = sd.var("w", np.ones((3, 4), np.float32))
    y = sd.nn.relu(x @ w)
    xv = np.array([[1.0, -2.0, 3.0]], np.float32)
    out = sd.output({"x": xv}, [y.name])[y.name]
    np.testing.assert_allclose(out, np.maximum(xv @ np.ones((3, 4)), 0))
    assert sd.get_variable("w").shape == (3, 4)


def test_missing_placeholder_raises():
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    y = sd.math.exp(x)
    with pytest.raises(ValueError, match="not fed"):
        sd.output({}, [y.name])


def test_namespaced_ops_and_multi_output():
    sd = SameDiff()
    x = sd.var("x", np.arange(12, dtype=np.float32).reshape(3, 4))
    mean, var = sd.math.moments(x, axes=(0,))
    m = mean.eval()
    v = var.eval()
    np.testing.assert_allclose(m.squeeze(), np.arange(12).reshape(3, 4).mean(0), rtol=1e-6)
    np.testing.assert_allclose(v.squeeze(), np.arange(12).reshape(3, 4).var(0), rtol=1e-6)


def test_getitem_and_reductions():
    sd = SameDiff()
    x = sd.var("x", np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    sl = x[0, 1:3]
    np.testing.assert_allclose(
        sl.eval(), np.arange(24).reshape(2, 3, 4)[0, 1:3])
    s = x.sum(1, 2)
    np.testing.assert_allclose(s.eval(), np.arange(24).reshape(2, 3, 4).sum((1, 2)))


def test_calculate_gradients_matches_analytic():
    # loss = sum((x@w - y)^2); dL/dw = 2 x^T (x@w - y)
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(5, 3)).astype(np.float32)
    wv = rng.normal(size=(3, 2)).astype(np.float32)
    yv = rng.normal(size=(5, 2)).astype(np.float32)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    y = sd.placeholder("y", shape=(-1, 2))
    w = sd.var("w", wv)
    diff = x @ w - y
    loss = sd.math.sum(diff * diff)
    sd.set_loss_variables(loss)
    grads = sd.calculate_gradients({"x": xv, "y": yv}, "w")
    expect = 2 * xv.T @ (xv @ wv - yv)
    np.testing.assert_allclose(grads["w"], expect, rtol=1e-4)
    # gradient wrt a placeholder also works (DL4J allows input grads)
    gx = sd.calculate_gradients({"x": xv, "y": yv}, "x")["x"]
    np.testing.assert_allclose(gx, 2 * (xv @ wv - yv) @ wv.T, rtol=1e-4)


def test_fit_linear_regression_converges():
    rng = np.random.default_rng(1)
    true_w = np.array([[2.0], [-3.0], [0.5]], np.float32)
    xv = rng.normal(size=(256, 3)).astype(np.float32)
    yv = xv @ true_w

    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    y = sd.placeholder("y", shape=(-1, 1))
    w = sd.var("w", np.zeros((3, 1), np.float32))
    pred = x @ w
    loss = sd.loss.meanSquaredError(pred, y)
    sd.set_loss_variables(loss)
    sd.set_training_config(TrainingConfig(
        updater=Sgd(learning_rate=0.1),
        data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
    hist = sd.fit((xv, yv), epochs=60)
    assert hist[-1] < 1e-3, hist[-5:]
    np.testing.assert_allclose(sd.get_variable("w").get_arr(), true_w, atol=0.05)


def test_fit_softmax_classifier():
    rng = np.random.default_rng(2)
    xv = rng.normal(size=(200, 4)).astype(np.float32)
    labels = (xv[:, 0] + xv[:, 1] > 0).astype(int)
    yv = np.eye(2, dtype=np.float32)[labels]

    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 4))
    y = sd.placeholder("y", shape=(-1, 2))
    w = sd.var("w", np.zeros((4, 2), np.float32))
    b = sd.var("b", np.zeros((2,), np.float32))
    logits = x @ w + b
    loss = sd.loss.softmaxCrossEntropy(logits, y)
    sd.set_loss_variables(loss)
    sd.set_training_config(TrainingConfig(
        updater=Adam(learning_rate=0.05),
        data_set_feature_mapping=["x"], data_set_label_mapping=["y"],
        l2=1e-4))
    sd.fit((xv, yv), epochs=40)
    out = sd.output({"x": xv}, [logits.name])[logits.name]
    acc = (out.argmax(1) == labels).mean()
    assert acc > 0.95, acc


def test_save_load_roundtrip(tmp_path):
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    w = sd.var("w", np.random.default_rng(3).normal(size=(3, 2)).astype(np.float32))
    out = sd.nn.softmax(x @ w)
    loss = sd.math.sum(out)
    sd.set_loss_variables(loss)
    sd.set_training_config(TrainingConfig(
        updater=Adam(learning_rate=0.01),
        data_set_feature_mapping=["x"], data_set_label_mapping=[]))
    path = str(tmp_path / "model.sdz")
    sd.save(path)

    sd2 = SameDiff.load(path)
    xv = np.random.default_rng(4).normal(size=(5, 3)).astype(np.float32)
    a = sd.output({"x": xv}, [out.name])[out.name]
    b = sd2.output({"x": xv}, [out.name])[out.name]
    np.testing.assert_allclose(a, b, rtol=1e-6)
    assert sd2.get_variable("w").vtype is VariableType.VARIABLE
    assert sd2.training_config is not None


def test_while_loop_control_flow():
    import jax.numpy as jnp
    sd = SameDiff()
    i0 = sd.constant(np.float32(0.0), "i0")
    acc0 = sd.constant(np.float32(1.0), "acc0")
    i_f, acc_f = sd.while_loop(
        lambda i, acc: i < 5,
        lambda i, acc: (i + 1, acc * 2),
        i0, acc0)
    assert float(acc_f.eval()) == 32.0


def test_if_cond():
    sd = SameDiff()
    p = sd.constant(np.bool_(True), "p")
    a = sd.constant(np.float32(3.0), "a")
    out = sd.if_cond(p, lambda v: v * 2, lambda v: v * 10, a)
    assert float(out.eval()) == 6.0


def test_custom_op_not_serializable(tmp_path):
    sd = SameDiff()
    a = sd.constant(np.float32(1.0), "a")
    sd.custom_op(lambda v: v + 1, a)
    with pytest.raises(ValueError, match="custom"):
        sd.save(str(tmp_path / "x.sdz"))


def test_variadic_multi_output_ops():
    """split/split_v/unstack/dynamic_partition arity handling (regression:
    the arity attr must match the registered lowering's signature)."""
    sd = SameDiff()
    x = sd.constant(np.arange(12, dtype=np.float32).reshape(6, 2), "x")
    parts = sd.math.split(x, num_or_sections=3, axis=0)
    assert len(parts) == 3
    np.testing.assert_allclose(parts[0].eval(), np.arange(4).reshape(2, 2))

    rows = sd.math.unstack(x, axis=1)
    assert len(rows) == 2
    np.testing.assert_allclose(rows[1].eval(), np.arange(12).reshape(6, 2)[:, 1])

    sv = sd.math.split_v(x, sizes=(2, 4), axis=0)
    assert len(sv) == 2 and sv[1].eval().shape == (4, 2)

    idx = sd.constant(np.array([0, 1, 0, 1, 0, 1]), "idx")
    dp = sd.math.dynamic_partition(x, idx, num_partitions=2)
    assert len(dp) == 2


def test_resume_preserves_updater_state_and_iteration(tmp_path):
    """Regression: fit after load() must not clobber restored Adam moments or
    restart the iteration counter (LR schedules / bias correction)."""
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(32, 3)).astype(np.float32)
    ys = (xs @ np.array([[1.0], [-2.0], [0.5]], dtype=np.float32)).astype(np.float32)

    def build():
        sd = SameDiff()
        x = sd.placeholder("x", shape=(-1, 3))
        y = sd.placeholder("y", shape=(-1, 1))
        w = sd.var("w", np.zeros((3, 1), dtype=np.float32))
        pred = x @ w
        loss = sd.loss.meanSquaredError(pred, y)
        sd.set_loss_variables(loss)
        sd.set_training_config(TrainingConfig(
            updater=Adam(learning_rate=0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
        return sd

    a = build()
    a.fit((xs, ys), epochs=3)
    path = str(tmp_path / "mid.sdz")
    a.save(path, save_updater_state=True)
    a.fit((xs, ys), epochs=3)  # uninterrupted

    b = SameDiff.load(path)
    assert b._it_count == 3
    assert b._opt_state is not None
    b.fit((xs, ys), epochs=3)  # resumed

    np.testing.assert_allclose(
        a.get_variable("w").get_arr(), b.get_variable("w").get_arr(),
        rtol=1e-5, atol=1e-6)


def test_fit_after_adding_trainable_keeps_moments():
    # regression: _opt_state must conform to the current trainables when the
    # graph gains a variable between fit() calls (stale-state crash)
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(64, 3)).astype(np.float32)
    yv = (xv @ np.array([[1.0], [2.0], [-1.0]], np.float32))

    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    y = sd.placeholder("y", shape=(-1, 1))
    w = sd.var("w", np.zeros((3, 1), np.float32))
    pred = x @ w
    loss = sd.loss.meanSquaredError(pred, y)
    sd.set_loss_variables(loss)
    sd.set_training_config(TrainingConfig(
        updater=Adam(learning_rate=0.05),
        data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
    sd.fit((xv, yv), epochs=3)

    b = sd.var("b", np.zeros((1,), np.float32))
    pred2 = pred + b
    loss2 = sd.loss.meanSquaredError(pred2, y)
    sd.set_loss_variables(loss2)
    hist = sd.fit((xv, yv), epochs=40)  # must not raise
    assert hist[-1] < hist[0] and hist[-1] < 0.2, hist[-5:]


class TestRound2Namespaces:
    """sd.rnn / sd.cnn / sd.image namespaces (SDRNN/SDCNN/SDImage parity)."""

    def test_rnn_namespace_lstm_layer(self, rng):
        from deeplearning4j_tpu.samediff import SameDiff

        sd = SameDiff()
        x = sd.placeholder("x", shape=(4, 2, 3))
        W = sd.constant((rng.standard_normal((1, 16, 3)) * 0.2)
                        .astype(np.float32), name="W")
        R = sd.constant((rng.standard_normal((1, 16, 4)) * 0.2)
                        .astype(np.float32), name="R")
        y, yh, yc = sd.rnn.lstmLayer(x, W, R, hidden_size=4)
        xs = rng.standard_normal((4, 2, 3)).astype(np.float32)
        res = sd.output({"x": xs}, [y.name, yh.name, yc.name])
        assert res[y.name].shape == (4, 1, 2, 4)
        assert res[yh.name].shape == (1, 2, 4)

    def test_cnn_namespace(self, rng):
        from deeplearning4j_tpu.samediff import SameDiff

        sd = SameDiff()
        x = sd.placeholder("x", shape=(2, 8, 8, 3))
        w = sd.constant((rng.standard_normal((3, 3, 3, 4)) * 0.2)
                        .astype(np.float32), name="w")
        y = sd.cnn.conv2d(x, w)
        p = sd.cnn.maxPooling2d(y, kernel=(2, 2))
        xs = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
        res = sd.output({"x": xs}, [p.name])
        assert res[p.name].shape == (2, 4, 4, 4)

    def test_image_namespace(self, rng):
        from deeplearning4j_tpu.samediff import SameDiff

        sd = SameDiff()
        x = sd.placeholder("x", shape=(2, 8, 8, 3))
        y = sd.image.resizeBiLinear(x, size=(4, 4))
        xs = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
        res = sd.output({"x": xs}, [y.name])
        assert res[y.name].shape == (2, 4, 4, 3)

    def test_image_nms(self):
        from deeplearning4j_tpu.samediff import SameDiff

        sd = SameDiff()
        boxes = sd.constant(np.asarray(
            [[0, 0, 1, 1], [0, 0, 0.95, 0.95], [0.6, 0.6, 1, 1]], np.float32),
            name="boxes")
        scores = sd.constant(np.asarray([0.9, 0.8, 0.7], np.float32),
                             name="scores")
        idx = sd.image.nonMaxSuppression(boxes, scores, 3, iou_threshold=0.5)
        res = sd.output({}, [idx.name])
        np.testing.assert_array_equal(res[idx.name], [0, 2, -1])


class TestSerializableWhileLoopAPI:
    def test_while_loop_graph_saves_and_matches(self, tmp_path, rng):
        """SameDiff.whileLoop parity (round 4): user-authored loops built
        from sub-SameDiff graphs serialize with the model — the
        closure-based while_loop cannot."""
        from deeplearning4j_tpu.samediff import SameDiff

        # cond: i < 5 ; body: (i+1, acc*2)
        cond_sd = SameDiff()
        ci = cond_sd.placeholder("i", shape=(), dtype=np.int32)
        ca = cond_sd.placeholder("acc", shape=(2,), dtype=np.float32)
        cout = cond_sd._op("less", [ci, cond_sd.constant(
            np.int32(5), name="limit")])
        body_sd = SameDiff()
        bi = body_sd.placeholder("i", shape=(), dtype=np.int32)
        ba = body_sd.placeholder("acc", shape=(2,), dtype=np.float32)
        i2 = body_sd._op("add", [bi, body_sd.constant(np.int32(1),
                                                      name="one")])
        a2 = body_sd._op("multiply", [ba, body_sd.constant(
            np.float32(2.0), name="two")])

        sd = SameDiff()
        x = sd.placeholder("x", shape=(2,), dtype=np.float32)
        i0 = sd.constant(np.int32(0), name="i0")
        fi, facc = sd.while_loop_graph(
            cond_sd, [ci, ca], cout, body_sd, [bi, ba], [i2, a2],
            i0, x, name="w")
        out_name = facc.name
        xv = rng.normal(size=(2,)).astype(np.float32)
        ref = np.asarray(sd.output({"x": xv}, [out_name])[out_name])
        np.testing.assert_allclose(ref, xv * 32.0, rtol=1e-6)  # 2^5

        p = str(tmp_path / "uwhile.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = np.asarray(sd2.output({"x": xv}, [out_name])[out_name])
        np.testing.assert_array_equal(out, ref)
