"""benchmarks/regression_gate.py (ISSUE 5): noise-aware best-known bands
over the committed BENCH trajectory, machine-checking every future run's
perf claims — and proving the gate actually fires on a regression."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import regression_gate as rg  # noqa: E402


def _record(n, metrics):
    """Committed-BENCH wrapper for {metric: (value, noise_str|None)}."""
    rows = [{"metric": m, "value": v, **({"noise": nz} if nz else {})}
            for m, (v, nz) in metrics.items()]
    head, extra = rows[0], rows[1:]
    head = dict(head)
    if extra:
        head["extra_metrics"] = extra
    return {"n": n, "parsed": head}


def _write_trajectory(tmp_path, records):
    for i, rec in enumerate(records, 1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(rec))
    return str(tmp_path / "BENCH_r*.json")


class TestNoiseParsing:
    def test_parse_noise(self):
        assert rg.parse_noise("±7.2% (3-sample spread/2)") == \
            pytest.approx(0.072)
        assert rg.parse_noise("±10.9% (x)") == pytest.approx(0.109)
        assert rg.parse_noise(None) is None
        assert rg.parse_noise("fast") is None


class TestBands:
    def test_within_band_passes(self):
        traj = [("r1", {"tput": (100.0, 0.05)}),
                ("r2", {"tput": (110.0, 0.05)})]
        res = rg.gate(traj, {"tput": (104.0, 0.05)})
        assert res[0]["status"] == "ok"

    def test_regression_beyond_band_fails(self):
        traj = [("r1", {"tput": (100.0, 0.02)}),
                ("r2", {"tput": (110.0, 0.02)})]
        res = rg.gate(traj, {"tput": (70.0, 0.02)})
        assert res[0]["status"] == "regressed"
        assert res[0]["best"] == 110.0

    def test_noise_widens_band(self):
        traj = [("r1", {"tput": (110.0, 0.30)})]
        # 25% below best but the best record itself is ±30% noisy
        res = rg.gate(traj, {"tput": (82.0, 0.05)})
        assert res[0]["status"] == "ok"

    def test_lower_is_better_direction(self):
        traj = [("r1", {"telemetry_overhead": (1.10, 0.02)}),
                ("r2", {"telemetry_overhead": (0.98, 0.02)})]
        assert rg.gate(traj, {"telemetry_overhead": (1.00, 0.02)})[0][
            "status"] == "ok"
        assert rg.gate(traj, {"telemetry_overhead": (1.50, 0.02)})[0][
            "status"] == "regressed"

    def test_new_and_missing_metrics(self):
        traj = [("r1", {"tput": (100.0, None)})]
        res = {r["metric"]: r["status"]
               for r in rg.gate(traj, {"brand_new": (5.0, None)})}
        assert res == {"tput": "missing", "brand_new": "new"}
        # missing is warn-only by default, fatal under strict
        results = rg.gate(traj, {"brand_new": (5.0, None)})
        assert rg._passed(results, strict=False)
        assert not rg._passed(results, strict=True)

    def test_critical_metric_missing_is_fatal_even_unstrict(self):
        metric = "dp_sharding_efficiency_8dev_virtual_cpu"
        assert metric in rg.CRITICAL
        traj = [("r1", {metric: (0.58, None), "tput": (100.0, None)})]
        results = rg.gate(traj, {"tput": (100.0, None)})
        # the scaling-efficiency contract may never silently disappear
        assert not rg._passed(results, strict=False)
        ok = rg.gate(traj, {metric: (0.9, None), "tput": (100.0, None)})
        assert rg._passed(ok, strict=False)

    def test_host_condition_metric_gates_against_floor(self):
        # dp_sharding efficiency tracks the shared host's scheduling
        # weather (committed trajectory spans 0.52-1.06 for the same
        # code), so it gates on an absolute floor, not the best band —
        # a value far below any committed record still passes as long
        # as it clears the floor; a collapse below the floor fails.
        metric = "dp_sharding_efficiency_8dev_virtual_cpu"
        assert metric in rg.HOST_CONDITION_FLOOR
        floor = rg.HOST_CONDITION_FLOOR[metric]
        traj = [("r1", {metric: (1.05, 0.02)})]
        above = rg.gate(traj, {metric: (floor + 0.05, 0.15)})[0]
        assert above["status"] == "ok" and above["direction"] == "floor"
        below = rg.gate(traj, {metric: (floor - 0.05, 0.01)})[0]
        assert below["status"] == "regressed"
        assert below["bound"] == pytest.approx(floor)

    def test_zero_memory_metric_is_lower_better(self):
        metric = "zero_optimizer_memory_bytes_per_device"
        assert metric in rg.LOWER_BETTER
        traj = [("r1", {metric: (25e6, 0.01)})]
        assert rg.gate(traj, {metric: (24e6, 0.01)})[0]["status"] == "ok"
        assert rg.gate(traj, {metric: (60e6, 0.01)})[0]["status"] == \
            "regressed"

    def test_default_noise_applies_to_legacy_records(self):
        traj = [("r1", {"tput": (100.0, None)})]  # pre-noise-field record
        # tol = 0.05 + 0.05 + 0.02 -> bound 88
        assert rg.gate(traj, {"tput": (89.0, None)})[0]["status"] == "ok"
        assert rg.gate(traj, {"tput": (87.0, None)})[0]["status"] == \
            "regressed"


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "benchmarks",
                                          "regression_gate.py"), *args],
            capture_output=True, text=True, timeout=120)

    def test_ci_mode_passes_on_committed_trajectory(self):
        """Acceptance: the gate passes against the repo's own BENCH files
        AND its self-test proves it fails on an injected regression."""
        out = self._run("--ci")
        assert out.returncode == 0, out.stdout + out.stderr
        assert "self-test" in out.stdout and "PASS" in out.stdout

    def test_check_mode_flags_fresh_regression(self, tmp_path):
        pattern = _write_trajectory(tmp_path, [
            _record(1, {"resnet": (2000.0, "±2%")}),
            _record(2, {"resnet": (2400.0, "±2%")}),
        ])
        fresh = tmp_path / "fresh.json"
        fresh.write_text(
            "some log line\n" + json.dumps(
                {"metric": "resnet", "value": 1000.0, "noise": "±2%"}))
        out = self._run("--bench-glob", pattern, "--check", str(fresh))
        assert out.returncode == 1
        assert "REGRESSED" in out.stdout

    def test_check_mode_passes_fresh_improvement(self, tmp_path):
        pattern = _write_trajectory(tmp_path, [
            _record(1, {"resnet": (2000.0, "±2%")}),
        ])
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(
            {"metric": "resnet", "value": 2600.0, "noise": "±2%"}))
        out = self._run("--bench-glob", pattern, "--check", str(fresh))
        assert out.returncode == 0, out.stdout + out.stderr

    def test_json_output(self, tmp_path):
        pattern = _write_trajectory(tmp_path, [
            _record(1, {"resnet": (2000.0, "±2%")}),
        ])
        out = self._run("--bench-glob", pattern, "--json")
        doc = json.loads(out.stdout)
        assert doc["results"][0]["metric"] == "resnet"
        assert doc["results"][0]["status"] == "ok"
