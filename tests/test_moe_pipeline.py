"""MoE expert parallelism + pipeline parallelism.

No reference counterpart (SURVEY.md §2.3: EP and PP absent upstream) —
validated against single-device execution on the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.nn.moe import MixtureOfExperts, expert_parallel
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_forward,
    sequential_reference,
    stack_stage_params,
)


class TestMoE:
    def test_forward_shapes_and_topk_sparsity(self, rng):
        layer = MixtureOfExperts(n_in=8, n_experts=4, top_k=2, ffn_size=16)
        params, state = layer.initialize(jax.random.PRNGKey(0), (5, 8))
        x = jnp.asarray(rng.standard_normal((2, 5, 8)), jnp.float32)
        y, _ = layer.apply(params, state, x)
        assert y.shape == (2, 5, 8)
        gates, _ = layer._gates(params, x.reshape(-1, 8), False, None)
        nz = (np.asarray(gates) > 1e-8).sum(axis=1)
        assert (nz <= 2).all() and (nz >= 1).all()

    def test_gradcheck(self, rng):
        layer = MixtureOfExperts(n_in=4, n_experts=2, top_k=2, ffn_size=8)
        params, state = layer.initialize(jax.random.PRNGKey(1), (3, 4))
        x = jnp.asarray(rng.standard_normal((2, 3, 4)))

        def loss(p):
            y, _ = layer.apply(p, state, x.astype(
                jax.tree_util.tree_leaves(p)[0].dtype))
            return jnp.sum(y ** 2)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    def test_topk_exact_under_ties(self):
        # zero-init router → all logits tied; exactly top_k must stay active
        layer = MixtureOfExperts(n_in=4, n_experts=8, top_k=2, ffn_size=8)
        params, state = layer.initialize(jax.random.PRNGKey(0), (3, 4))
        params = dict(params, router=jnp.zeros_like(params["router"]))
        x = jnp.ones((1, 3, 4), jnp.float32)
        gates, _ = layer._gates(params, x.reshape(-1, 4), False, None)
        nz = (np.asarray(gates) > 1e-8).sum(axis=1)
        assert (nz == 2).all(), nz

    def test_aux_loss_balances(self, rng):
        layer = MixtureOfExperts(n_in=4, n_experts=4, top_k=1)
        params, _ = layer.initialize(jax.random.PRNGKey(0), (3, 4))
        x = jnp.asarray(rng.standard_normal((8, 3, 4)), jnp.float32)
        al = float(layer.aux_loss(params, x))
        assert np.isfinite(al) and al > 0

    @pytest.mark.multichip
    def test_expert_parallel_matches_single_device(self, rng):
        from jax.sharding import Mesh

        layer = MixtureOfExperts(n_in=8, n_experts=8, top_k=2, ffn_size=16)
        params, state = layer.initialize(jax.random.PRNGKey(0), (5, 8))
        x = jnp.asarray(rng.standard_normal((4, 5, 8)), jnp.float32)
        ref, _ = layer.apply(params, state, x)
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("model",))
        out = expert_parallel(layer, params, x, mesh, axis_name="model")
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)


@pytest.mark.multichip
class TestPipeline:
    def _mesh(self, s):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:s]).reshape(s), ("model",))

    def _stages(self, rng, s, h):
        def stage_fn(p, x):
            return jnp.tanh(x @ p["W"] + p["b"])

        params = [
            {"W": jnp.asarray(rng.standard_normal((h, h)) * 0.4, jnp.float32),
             "b": jnp.asarray(rng.standard_normal(h) * 0.1, jnp.float32)}
            for _ in range(s)
        ]
        return stage_fn, params

    @pytest.mark.parametrize("s,n_micro", [(4, 4), (8, 2), (2, 8)])
    def test_matches_sequential(self, rng, s, n_micro):
        stage_fn, params = self._stages(rng, s, 16)
        x = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
        ref = sequential_reference(stage_fn, params, x)
        out = pipeline_forward(stage_fn, stack_stage_params(params), x,
                               n_micro=n_micro, mesh=self._mesh(s))
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)

    def test_differentiable(self, rng):
        stage_fn, params = self._stages(rng, 4, 8)
        x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
        stacked = stack_stage_params(params)
        mesh = self._mesh(4)

        def loss_pipe(stacked):
            return jnp.sum(pipeline_forward(stage_fn, stacked, x, 4, mesh) ** 2)

        def loss_ref(stacked):
            plist = [jax.tree_util.tree_map(lambda v: v[i], stacked)
                     for i in range(4)]
            return jnp.sum(sequential_reference(stage_fn, plist, x) ** 2)

        g1 = jax.grad(loss_pipe)(stacked)
        g2 = jax.grad(loss_ref)(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g1),
                        jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=1e-3)
