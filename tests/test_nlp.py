"""NLP: tokenizers, BertIterator, word vectors.

Reference test parity: deeplearning4j-nlp tests (BertWordPieceTokenizerTests,
BertIteratorTest, Word2VecTests/Glove tests on tiny corpora; SURVEY.md §2.2
J15)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BertIterator,
    BertWordPieceTokenizer,
    DefaultTokenizer,
    GloVe,
    ParagraphVectors,
    Vocab,
    Word2Vec,
)


class TestTokenizers:
    def test_default_tokenizer(self):
        t = DefaultTokenizer()
        assert t.tokenize("Hello, World!") == ["hello", ",", "world", "!"]

    def test_wordpiece_greedy_longest_match(self):
        v = Vocab(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
                   "un", "##aff", "##able", "##ord", "play", "##ing", "the"])
        tok = BertWordPieceTokenizer(v)
        assert tok.tokenize("unaffable") == ["un", "##aff", "##able"]
        assert tok.tokenize("playing the") == ["play", "##ing", "the"]
        assert tok.tokenize("xyzzy") == ["[UNK]"]

    def test_vocab_file_roundtrip(self, tmp_path):
        p = tmp_path / "vocab.txt"
        p.write_text("[PAD]\n[UNK]\nhello\nworld\n")
        v = Vocab.load(str(p))
        assert v.id("hello") == 2 and v.token(3) == "world"
        assert v.id("missing") == v.id("[UNK]")


class TestBertIterator:
    def _tok(self, texts):
        return BertWordPieceTokenizer(Vocab.build(texts))

    def test_classification_batches(self):
        texts = ["the cat sat", "a dog ran fast", "the cat ran"] * 4
        labels = [0, 1, 0] * 4
        it = BertIterator(self._tok(texts), task=BertIterator.SEQ_CLASSIFICATION,
                          sentences=texts, labels=labels, max_length=8,
                          batch_size=4, n_classes=2)
        batches = list(it)
        assert len(batches) == 3
        ds = batches[0]
        assert ds.features.shape == (4, 8, 2)
        assert ds.labels.shape == (4, 2)
        v = it.vocab
        # [CLS] first, [SEP] closes each sequence, mask covers the tokens
        assert ds.features[0, 0, 0] == v.id(v.CLS)
        L = int(ds.features_mask[0].sum())
        assert ds.features[0, L - 1, 0] == v.id(v.SEP)

    def test_sentence_pairs_segments(self):
        texts = ["the cat sat on the mat", "a dog ran"]
        pairs = [(texts[0], texts[1])]
        it = BertIterator(self._tok(texts), task=BertIterator.SEQ_CLASSIFICATION,
                          sentence_pairs=pairs, labels=[1], max_length=16,
                          batch_size=1, n_classes=2)
        ds = next(iter(it))
        segs = ds.features[0, :, 1]
        assert segs.max() == 1.0  # second sentence marked segment 1
        # segment 1 region ends where the mask ends
        L = int(ds.features_mask[0].sum())
        assert segs[L - 1] == 1.0 and segs[0] == 0.0

    def test_unsupervised_mlm_masking(self):
        texts = ["the quick brown fox jumps over the lazy dog again"] * 8
        it = BertIterator(self._tok(texts), task=BertIterator.UNSUPERVISED,
                          sentences=texts, max_length=12, batch_size=8,
                          mask_prob=0.5, seed=3)
        ds = next(iter(it))
        assert ds.labels.shape == (8, 12, len(it.vocab))
        assert ds.labels_mask.sum() > 0  # some positions masked
        v = it.vocab
        # masked-position labels hold the ORIGINAL token, not [MASK]
        b, t = np.argwhere(ds.labels_mask > 0)[0]
        orig = int(np.argmax(ds.labels[b, t]))
        assert orig not in (v.id(v.MASK), v.id(v.PAD))
        # [MASK] appears somewhere in the inputs
        assert (ds.features[..., 0] == v.id(v.MASK)).any()

    def test_reset_reproducible(self):
        texts = ["a b c d e f g"] * 4
        it = BertIterator(self._tok(texts), task=BertIterator.UNSUPERVISED,
                          sentences=texts, max_length=8, batch_size=4, seed=1)
        a = next(iter(it)).features.copy()
        it.reset()
        b = next(iter(it)).features.copy()
        np.testing.assert_array_equal(a, b)


@pytest.fixture(scope="module")
def toy_corpus():
    # two topic clusters; co-occurrence forces king/queen and cat/dog together
    rng = np.random.default_rng(0)
    royal = ["king queen royal palace crown throne"] * 40
    pets = ["cat dog pet tail fur paw"] * 40
    lines = royal + pets
    rng.shuffle(lines)
    return lines


class TestWordVectors:
    def test_word2vec_learns_topics(self, toy_corpus):
        w2v = Word2Vec(min_word_frequency=5, layer_size=16, window_size=3,
                       negative=4, epochs=10, subsample=0, seed=0).fit(toy_corpus)
        assert w2v.has_word("king") and w2v.has_word("cat")
        assert w2v.similarity("king", "queen") > w2v.similarity("king", "dog")
        near = w2v.words_nearest("cat", 3)
        assert "king" not in near

    def test_glove_learns_topics(self, toy_corpus):
        g = GloVe(min_word_frequency=5, layer_size=8, epochs=40, seed=0).fit(toy_corpus)
        assert g.similarity("king", "queen") > g.similarity("king", "dog")

    def test_paragraph_vectors_infer(self, toy_corpus):
        pv = ParagraphVectors(min_word_frequency=5, layer_size=16, window_size=3,
                              negative=4, epochs=6, subsample=0, seed=0).fit(toy_corpus)
        assert pv.doc_vectors.shape[0] == len(toy_corpus)
        v = pv.infer_vector("king queen royal")
        assert v.shape == (16,)
        assert np.isfinite(v).all()


def test_word_vector_serializer_roundtrip(tmp_path, toy_corpus):
    from deeplearning4j_tpu.nlp import WordVectorSerializer, Word2Vec

    w2v = Word2Vec(min_word_frequency=5, layer_size=8, epochs=2,
                   subsample=0, seed=0).fit(toy_corpus)
    p = str(tmp_path / "vectors.txt")
    WordVectorSerializer.write_word_vectors(w2v, p)
    loaded = WordVectorSerializer.read_word_vectors(p)
    assert loaded.vocab.words == w2v.vocab.words
    np.testing.assert_allclose(loaded.vectors, w2v.vectors, atol=1e-5)
    assert loaded.similarity("king", "queen") == pytest.approx(
        w2v.similarity("king", "queen"), abs=1e-4)
    # gz variant
    pz = str(tmp_path / "vectors.txt.gz")
    WordVectorSerializer.write_word_vectors(w2v, pz)
    assert WordVectorSerializer.read_word_vectors(pz).vocab.words == w2v.vocab.words


class TestHierarchicalSoftmax:
    """useHierarchicSoftmax parity (HierarchicSoftmax.java / word2vec.c HS
    mode — VERDICT r1 missing #9)."""

    def test_huffman_tree_is_prefix_code(self):
        from deeplearning4j_tpu.nlp.word2vec import _build_huffman

        counts = np.asarray([50, 30, 12, 5, 2, 1], np.float64)
        codes, points, mask = _build_huffman(counts)
        lens = mask.sum(axis=1).astype(int)
        # more frequent word → code no longer than a rarer word's
        assert all(lens[i] <= lens[j] for i in range(3) for j in range(3, 6))
        # prefix property: no word's code is a prefix of another's
        strs = ["".join(str(int(b)) for b in codes[i][: lens[i]])
                for i in range(len(counts))]
        for i in range(len(strs)):
            for j in range(len(strs)):
                if i != j:
                    assert not strs[j].startswith(strs[i]), (i, j, strs)
        # internal node ids stay in range (V-1 internal nodes)
        assert points[mask > 0].max() < len(counts) - 1
        assert points[mask > 0].min() >= 0

    def test_word2vec_hs_learns_topics(self, toy_corpus):
        w2v = Word2Vec(min_word_frequency=5, layer_size=16, window_size=3,
                       negative=0, epochs=10, subsample=0, seed=0,
                       use_hierarchic_softmax=True).fit(toy_corpus)
        assert w2v.use_hierarchic_softmax
        assert w2v.similarity("king", "queen") > w2v.similarity("king", "dog")

    def test_negative_zero_implies_hs(self):
        assert Word2Vec(negative=0).use_hierarchic_softmax
        assert not Word2Vec(negative=5).use_hierarchic_softmax


class TestFastText:
    CORPUS = [
        ("the cat sat on the mat with another cat", "animals"),
        ("dogs chase cats and cats chase mice", "animals"),
        ("my dog loves long walks in the park", "animals"),
        ("a kitten and a puppy played together", "animals"),
        ("the horse galloped across the green field", "animals"),
        ("stock markets rallied as rates fell", "finance"),
        ("the bank raised interest rates again", "finance"),
        ("investors bought shares after the earnings report", "finance"),
        ("the fund managers hedged their currency exposure", "finance"),
        ("bond yields dropped on inflation news", "finance"),
    ]

    def test_supervised_classification_and_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.nlp import FastText

        texts = [t for t, _ in self.CORPUS]
        labels = [l for _, l in self.CORPUS]
        ft = FastText(dim=32, epoch=60, lr=0.5, word_ngrams=2,
                      bucket=1 << 12, seed=1).fit(texts, labels)
        correct = sum(ft.predict(t) == l for t, l in self.CORPUS)
        assert correct >= 9, correct
        # generalization to unseen word combinations from the same fields
        assert ft.predict("the puppy chased the kitten") == "animals"
        assert ft.predict("rates and shares and yields") == "finance"
        probs = ft.predict_probabilities("dogs and cats")
        assert abs(sum(probs.values()) - 1.0) < 1e-5

        p = str(tmp_path / "ft.npz")
        ft.save(p)
        ft2 = FastText.load(p)
        for t, _ in self.CORPUS:
            assert ft2.predict(t) == ft.predict(t)

    def test_min_count_ids_contiguous(self):
        """Round-5 regression: with min_count>1 vocab ids were assigned
        before filtering — non-contiguous, overlapping the n-gram bucket
        range and able to exceed the embedding row count."""
        from deeplearning4j_tpu.nlp import FastText

        texts = [t for t, _ in self.CORPUS]
        labels = [l for _, l in self.CORPUS]
        ft = FastText(dim=8, epoch=2, min_count=2, bucket=64,
                      seed=0).fit(texts, labels)
        ids = sorted(ft.vocab.values())
        assert ids == list(range(len(ft.vocab)))
        # every id must index below the n-gram bucket range
        assert max(ids) < len(ft.vocab)
        ft.predict(texts[0])  # exercises the embedding lookup end-to-end
